"""Bridge server: executes the verb protocol against in-process frames.

The method surface mirrors the reference's builder factories
(``PythonInterface.scala:46-68``: ``map_blocks / map_rows / reduce_blocks /
reduce_rows / aggregate_blocks`` + graph/fetches/inputs/shape accessors) as
one-shot RPCs: each verb call carries the accumulated builder state
(GraphDef bytes, fetches, feed map, shape hints) in a single message.
Frames stay server-side (only ids cross the wire) — the analog of DataFrames
staying in the JVM while Python holds handles.

Serving-grade resilience (round 11) — the reference's Py4J gateway simply
blocks the driver thread per call; a front-end for real traffic cannot:

* **Per-request deadlines**: a request's ``deadline_ms`` becomes a
  ``cancellation.CancelScope`` active for the whole verb execution; the
  engine checks it at every block boundary and retry attempt, so an
  over-deadline verb raises a structured ``deadline_exceeded`` error at
  the next boundary — completed blocks are intact, the session's frames
  stay fully usable, and no worker thread is left stuck.
* **Admission control + backpressure** (:class:`AdmissionGate`): at most
  ``TFS_BRIDGE_MAX_INFLIGHT`` gated requests execute concurrently and at
  most ``TFS_BRIDGE_QUEUE_DEPTH`` wait; past that the server sheds with
  ``server_busy`` + ``retry_after_ms`` instead of queueing unboundedly.
* **Sessions survive connections**: a client that says ``hello`` gets a
  reattachable session token, so a dropped connection does not destroy
  its frames; verb requests carry an idempotency token the session
  dedups (bounded LRU), so a retried request after a dropped reply is
  served the original outcome and never double-executes.
* **Graceful drain**: :meth:`BridgeServer.close` rejects new admissions
  with ``draining``, waits up to ``TFS_BRIDGE_DRAIN_S`` for in-flight
  verbs, then cooperatively cancels stragglers through their cancel
  scopes before releasing the socket.
* **Health**: an ungated ``health`` RPC reports admission depth,
  quarantined devices (``ops/device_pool`` history), and HBM budget
  occupancy (``ops/frame_cache``) so clients can route around a sick
  server.
* **Chaos**: ``TFS_FAULT_INJECT`` bridge kinds (``bridge_stall`` /
  ``bridge_delay`` / ``bridge_drop``) exercise all of the above
  deterministically (``faults.maybe_inject_bridge``).
* **Telemetry** (round 13): every request records its end-to-end wall
  time (admission wait included) into the per-method latency
  histograms (``observability.latency_snapshot`` / ``metrics_text``);
  an ungated ``metrics`` RPC serves the Prometheus text exposition;
  ``health`` carries the gauge snapshot (host-byte high-water,
  flight-recorder depth/drops); with ``TFS_TRACE=1`` each request
  leaves ``request``/``admit``/``execute`` events on its handler
  thread's flight-recorder track (``docs/OBSERVABILITY.md``).
"""

from __future__ import annotations

import collections
import logging
import os
import socket
import socketserver
import threading
import time
import uuid
from typing import Any, Dict, Optional

import numpy as np

from .. import cancellation, faults, observability
from ..envutil import (
    env_float as _env_float,
    env_int as _env_int,
    env_raw as _env_raw,
)
from ..analyze import analyze as _analyze
from ..builder import OpBuilder
from ..frame import TensorFrame
from ..ops import bucketing, device_pool, frame_cache
from ..ops import engine as _engine_mod
from ..ops.engine import GroupedFrame
from ..ops.validation import ValidationError
from . import coalescer as _coalescer
from . import fleet as _fleet
from .protocol import (
    PROTOCOL_VERSION,
    decode_value,
    encode_value,
    read_message,
    write_message,
)

logger = logging.getLogger("tensorframes_tpu.bridge")

# -- knobs (env defaults; per-server constructor overrides win) --------------

ENV_MAX_INFLIGHT = "TFS_BRIDGE_MAX_INFLIGHT"
ENV_QUEUE_DEPTH = "TFS_BRIDGE_QUEUE_DEPTH"
ENV_DRAIN_S = "TFS_BRIDGE_DRAIN_S"
ENV_MAX_FRAMES = "TFS_BRIDGE_MAX_FRAMES"
ENV_SESSION_TTL_S = "TFS_BRIDGE_SESSION_TTL_S"
# round 18: colon-separated directory roots a pipeline RPC's path-based
# parquet source/sink may touch; unset = path access refused (frame_id
# sources and frame/collect sinks are always allowed)
ENV_PIPELINE_PATHS = "TFS_BRIDGE_PIPELINE_PATHS"
# per-reply cap on pipeline window-ledger snapshots; the tail past the
# cap folds into one synthetic entry so counter sums stay exact
_PIPELINE_WINDOW_SNAPS = 512

DEFAULT_MAX_INFLIGHT = 8  # 0 = unlimited (admission gate off)
DEFAULT_QUEUE_DEPTH = 16  # waiters allowed while inflight is full
DEFAULT_DRAIN_S = 5.0
DEFAULT_MAX_FRAMES = 0  # 0 = unlimited
DEFAULT_SESSION_TTL_S = 300.0
_IDEM_CACHE_CAP = 128  # replies remembered per session for dedup
# ...bounded by BYTES too: cached replies pin full result payloads
# (binary attachments included), so a count-only cap would let 128
# multi-MB reduce results per session pile up on exactly the saturated
# host admission control protects.  Oversized single results are not
# retained — a retry of one gets a structured marker instead.
_IDEM_CACHE_MAX_BYTES = 32 * 1024 * 1024
_IDEM_ENTRY_MAX_BYTES = 8 * 1024 * 1024


# methods that execute programs / move bulk data: these pass the
# admission gate and run under a cancel scope.  Cheap control-plane
# methods (ping, schema, release, hello, health, end_session) stay
# ungated so clients can health-check and clean up even when the server
# is saturated or draining.
_GATED_METHODS = frozenset(
    {
        "create_frame",
        "analyze",
        "map_blocks",
        "map_rows",
        "aggregate",
        "reduce_blocks",
        "reduce_rows",
        "collect",
        # round 16: registers + AOT-primes a program's (bucket, device)
        # executable grid — it compiles, so it pays admission like a verb
        "warm",
        # round 18: a whole source -> map -> join -> aggregate -> sink
        # streaming pipeline as ONE gated request — it compiles and
        # dispatches per window, so it pays admission, runs under the
        # request's cancel scope (checkpointed at every window
        # boundary), and attributes per window through nested ledgers
        "pipeline",
        # round 22: paged continuous decode — joins the running slot
        # batch at a step boundary, bills generated tokens per tenant,
        # honours deadline/cancel at step boundaries, and surfaces
        # page-pool exhaustion as a typed server_busy refusal
        "decode",
    }
)

# the complete ungated RPC surface, as an ALLOWLIST: anything not named
# here or in _GATED_METHODS is refused, so a future public helper on
# _Session can never silently become a remotely callable method (or
# bypass the admission gate under its raw name, as run_df_verb would).
# CONTRACT: ungated methods skip the idempotency dedup, so each must be
# NATURALLY idempotent (release is a pop that ignores unknown ids;
# check is pure — static analysis, nothing compiled or dispatched) —
# an ungated method with one-shot side effects would double-execute on
# a client retry.  ``check`` (round 17) is DELIBERATELY ungated: its
# whole point is that a tenant validates a program BEFORE burning an
# admission slot on a request the verb would refuse.
# ``job_status`` (round 20) is a pure journal read (no compile, no
# dispatch, naturally idempotent), ungated for the same reason as
# ``check``: a client deciding whether to resume must be able to ask
# even when the server is saturated or draining.
_UNGATED_METHODS = frozenset(
    {"ping", "schema", "release", "check", "job_status"}
)

# how long a retried request waits for its still-running original
# execution's outcome before giving up with ``retry_conflict``
_IDEM_WAIT_CAP_S = 600.0

# the complete method surface, for latency-histogram labelling: series
# are keyed by method name, so a client-supplied UNKNOWN name must not
# mint a new series per request (unbounded label cardinality = memory
# growth + metrics bloat on a long-lived server) — everything outside
# this set records under one "unknown" label
_ALL_METHODS = (
    _GATED_METHODS
    | _UNGATED_METHODS
    | frozenset({"hello", "health", "metrics", "attribution",
                 "end_session"})
)

# ledger snapshots retained for the ``attribution`` RPC, per server:
# bounded (LRU by arrival) so a long-lived server's attribution window
# is a sliding recent-history, not unbounded growth
_ATTRIBUTION_CAP = 256
_ATTRIBUTION_RECENT = 32  # returned by a no-cid attribution query


class BridgeServerError(RuntimeError):
    """A structured server-side refusal: carried to the client as
    ``{type, message, code, ...extra}`` so front-ends can branch on
    ``code`` instead of parsing prose."""

    code = "error"

    def __init__(self, message: str, code: Optional[str] = None, **extra):
        super().__init__(message)
        if code is not None:
            self.code = code  # instance override of the class default
        self.extra = extra


class ServerBusy(BridgeServerError):
    """Admission gate full: shed instead of queueing unboundedly.  The
    payload carries ``retry_after_ms`` — a deterministic backoff hint
    scaled by the current queue depth."""

    code = "server_busy"


class Draining(BridgeServerError):
    """The server is draining for shutdown; no new work is admitted."""

    code = "draining"


class FrameCapExceeded(BridgeServerError):
    """The per-session frame registry hit ``TFS_BRIDGE_MAX_FRAMES`` —
    almost always a client loop that never calls ``release``.  The
    payload names the leaked frame ids."""

    code = "frame_cap_exceeded"


class ResultEncodingError(BridgeServerError):
    """The verb EXECUTED but its result could not be serialized; the
    message preserves that context (the original handler lost it —
    round-11 satellite fix)."""

    code = "result_encoding"


class AdmissionGate:
    """Bounded concurrent-execution gate for the serving path.

    ``max_inflight`` gated requests execute at once; up to
    ``queue_depth`` more wait — a waiter's deadline keeps ticking and
    expires in place, and a NEW arrival never barges past waiters (the
    fast path requires an empty queue, so freed slots go to the queue
    first; wakeup order among waiters is the condition variable's).
    Anything past both bounds is shed immediately with
    :class:`ServerBusy`.  ``max_inflight=0`` disables the gate (every
    request admits instantly — the single-tenant / test default pinned
    by conftest)."""

    def __init__(self, max_inflight: int, queue_depth: int):
        self.max_inflight = max(0, int(max_inflight))
        self.queue_depth = max(0, int(queue_depth))
        self._cond = threading.Condition()
        # FIFO tickets: freed slots are granted strictly in queue-arrival
        # order, so a deadline-carrying waiter cannot be starved by later
        # arrivals repeatedly winning the condition-wakeup race
        self._waiters: "collections.deque" = collections.deque()
        self.inflight = 0
        self.queued = 0
        self.draining = False
        self.shed = 0

    def snapshot(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "inflight": self.inflight,
                "queued": self.queued,
                "max_inflight": self.max_inflight,
                "queue_depth": self.queue_depth,
                "draining": self.draining,
                "shed_total": self.shed,
            }

    def _shed(self, exc: BridgeServerError) -> None:
        self.shed += 1
        observability.note_bridge_shed()
        raise exc

    def admit(self, scope: Optional[cancellation.CancelScope]) -> None:
        """Admit the calling request or raise: :class:`Draining` while
        draining, :class:`ServerBusy` when both the inflight and queue
        bounds are full, ``DeadlineExceeded`` when the request's
        deadline expires while queued."""
        with self._cond:
            if self.draining:
                self._shed(Draining("server is draining; not admitting"))
            # fast path only with an EMPTY queue: a new arrival taking a
            # freed slot ahead of parked waiters would starve them (a
            # deadline-carrying waiter could expire despite capacity
            # turning over many times)
            if self.max_inflight <= 0 or (
                self.inflight < self.max_inflight and not self._waiters
            ):
                self.inflight += 1
                return
            if self.queued >= self.queue_depth:
                self._shed(
                    ServerBusy(
                        f"admission gate full ({self.inflight} in flight, "
                        f"{self.queued} queued; {ENV_MAX_INFLIGHT}="
                        f"{self.max_inflight} {ENV_QUEUE_DEPTH}="
                        f"{self.queue_depth})",
                        retry_after_ms=25 * (self.queued + 1),
                    )
                )
            ticket = object()
            self._waiters.append(ticket)
            self.queued += 1
            try:
                while True:
                    if self.draining:
                        self._shed(
                            Draining("server began draining while queued")
                        )
                    if (
                        self.inflight < self.max_inflight
                        and self._waiters[0] is ticket
                    ):
                        # strictly FIFO: only the HEAD ticket may take a
                        # freed slot, so later queuers cannot win the
                        # wakeup race over an earlier deadline-bound one
                        self.inflight += 1
                        return
                    remaining = (
                        scope.time_remaining() if scope is not None else None
                    )
                    if remaining is not None and remaining <= 0:
                        raise cancellation.DeadlineExceeded(
                            "request deadline expired while queued for "
                            "admission (never executed)"
                        )
                    self._cond.wait(timeout=remaining)
            finally:
                self.queued -= 1
                try:
                    self._waiters.remove(ticket)
                except ValueError:  # pragma: no cover - defensive
                    pass
                # whatever removed us from the head (grant, shed,
                # expiry), the next ticket must get a look
                self._cond.notify_all()

    def release(self) -> None:
        with self._cond:
            self.inflight -= 1
            self._cond.notify_all()

    def start_draining(self) -> None:
        with self._cond:
            self.draining = True
            self._cond.notify_all()

    def wait_idle(self, timeout_s: float) -> bool:
        """Block until no gated request is in flight (True) or
        ``timeout_s`` elapsed (False)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while self.inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True


class _Session:
    """Server-side session state: the frame registry, the idempotency
    dedup cache, and the per-method call counters fault injection keys
    on.  Addressed by a ``hello`` token, so it survives its TCP
    connection (reattach after a drop); no-``hello`` legacy connections
    get an implicit session that dies with the connection."""

    def __init__(self, engine=None, token: str = "", max_frames: int = 0):
        self.engine = engine
        self.frames: Dict[int, TensorFrame] = {}
        self._next = 0
        self.token = token
        self.max_frames = int(max_frames)
        self.lock = threading.Lock()
        self.idem: "collections.OrderedDict[str, tuple]" = (
            collections.OrderedDict()
        )
        self._idem_bytes = 0
        # tokens whose FIRST execution is still running: a client whose
        # read timed out mid-verb retries while the original handler
        # thread is still executing — the retry must wait for that
        # outcome, not start a concurrent second execution
        self.idem_inflight: Dict[str, threading.Event] = {}
        self.method_calls: Dict[str, int] = {}
        self.explicit = False  # attached via hello (reattachable)
        self.refs = 0  # connections currently attached
        self.last_active = time.monotonic()

    def register(self, frame: TensorFrame) -> int:
        with self.lock:
            if self.max_frames and len(self.frames) >= self.max_frames:
                ids = sorted(self.frames)
                shown = ", ".join(map(str, ids[:16]))
                if len(ids) > 16:
                    shown += f", ... ({len(ids) - 16} more)"
                raise FrameCapExceeded(
                    f"session holds {len(self.frames)} frames — the "
                    f"{ENV_MAX_FRAMES}={self.max_frames} cap; release "
                    f"leaked frame ids [{shown}] (a loop that never "
                    f"calls release() grows the registry for the life "
                    f"of the session)",
                    leaked_frame_ids=ids[:64],
                )
            self._next += 1
            self.frames[self._next] = frame
            return self._next

    def frame(self, fid: int) -> TensorFrame:
        if fid not in self.frames:
            raise KeyError(f"unknown frame id {fid}")
        return self.frames[fid]

    # -- idempotency dedup ---------------------------------------------------

    def idem_lookup(self, token: str):
        with self.lock:
            entry = self.idem.get(token)
            if entry is not None:
                self.idem.move_to_end(token)
            return entry

    def idem_begin(self, token: str):
        """-> ``("hit", entry)`` (outcome already recorded),
        ``("wait", event)`` (first execution still running — wait for
        its outcome instead of double-executing), or ``("own", None)``
        (this request executes and must call :meth:`idem_finish`)."""
        with self.lock:
            entry = self.idem.get(token)
            if entry is not None:
                self.idem.move_to_end(token)
                return "hit", entry
            ev = self.idem_inflight.get(token)
            if ev is not None:
                return "wait", ev
            ev = threading.Event()
            self.idem_inflight[token] = ev
            return "own", None

    def idem_finish(self, token: str, entry) -> None:
        """Record the owner's outcome (``entry`` may be None when the
        request was refused before executing, e.g. shed) and wake any
        retries waiting on it.  The cache is bounded by entry count AND
        bytes; a single result past ``_IDEM_ENTRY_MAX_BYTES`` is
        replaced with a replay-unavailable marker (the execution still
        happened exactly once — only the replay is withheld)."""
        if entry is not None:
            kind, payload, bins = entry
            nbytes = sum(len(b) for b in bins) + _approx_payload_bytes(
                payload
            )
            if nbytes > _IDEM_ENTRY_MAX_BYTES:
                entry = (
                    "error",
                    {
                        "type": "IdemReplayUnavailable",
                        "message": (
                            "the original request executed exactly once, "
                            "but its result was too large to retain for "
                            "idempotent replay; re-issue as a NEW request"
                        ),
                        "code": "retry_conflict",
                    },
                    [],
                )
                nbytes = 512
            entry = entry + (nbytes,)
        with self.lock:
            if entry is not None:
                self.idem[token] = entry
                self._idem_bytes += entry[3]
                while self.idem and (
                    len(self.idem) > _IDEM_CACHE_CAP
                    or self._idem_bytes > _IDEM_CACHE_MAX_BYTES
                ):
                    _, old = self.idem.popitem(last=False)
                    self._idem_bytes -= old[3]
            ev = self.idem_inflight.pop(token, None)
        if ev is not None:
            ev.set()

    def next_call_index(self, method: str) -> int:
        with self.lock:
            i = self.method_calls.get(method, 0)
            self.method_calls[method] = i + 1
            return i

    # -- methods (the RPC surface) ------------------------------------------

    def create_frame(self, columns: Dict[str, Any], num_blocks: int = 1):
        frame = TensorFrame.from_arrays(dict(columns), num_blocks=num_blocks)
        fid = self.register(frame)
        return {"frame_id": fid, "schema": self._schema(frame)}

    def analyze(self, frame_id: int):
        frame = _analyze(self.frame(frame_id))
        self.frames[frame_id] = frame
        return {"schema": self._schema(frame)}

    def schema(self, frame_id: int):
        return {"schema": self._schema(self.frame(frame_id))}

    def _schema(self, frame: TensorFrame):
        return [
            {
                "name": c.name,
                "dtype": c.scalar_type.name,
                "block_shape": list(c.block_shape),
            }
            for c in frame.schema
        ]

    def _builder(self, verb: str, target, params: Dict[str, Any]) -> OpBuilder:
        factory = {
            "map_blocks": lambda: OpBuilder.map_blocks(
                target, trim=bool(params.get("trim", False)), engine_=self.engine
            ),
            "map_rows": lambda: OpBuilder.map_rows(target, engine_=self.engine),
            "reduce_blocks": lambda: OpBuilder.reduce_blocks(
                target, engine_=self.engine
            ),
            "reduce_rows": lambda: OpBuilder.reduce_rows(
                target, engine_=self.engine
            ),
            "aggregate": lambda: OpBuilder.aggregate_blocks(
                target, engine_=self.engine
            ),
        }[verb]
        b = factory()
        b.graph(params["graph"])  # GraphDef bytes — the reference transport
        if params.get("fetches"):
            b.fetches(params["fetches"])
        if params.get("inputs"):
            b.inputs(params["inputs"])
        for name, shape in (params.get("shapes") or {}).items():
            b.shape(name, shape)
        return b

    def run_df_verb(self, verb: str, frame_id: int, **params):
        frame = self.frame(frame_id)
        target: Any = frame
        if verb == "aggregate":
            target = GroupedFrame(frame, params.pop("keys"))
        out = self._builder(verb, target, params).build_df()
        fid = self.register(out)
        return {"frame_id": fid, "schema": self._schema(out)}

    def run_row_verb(self, verb: str, frame_id: int, **params):
        out = self._builder(verb, self.frame(frame_id), params).build_row()
        # raw ndarrays: the handler's single encode_value(result, bins)
        # routes bulk payloads to the binary attachments — pre-encoding
        # here would pin them to inline base64
        return {"row": {k: np.asarray(v) for k, v in out.items()}}

    def collect(self, frame_id: int, columns=None):
        frame = self.frame(frame_id)
        names = columns or frame.column_names
        out = {}
        for n in names:
            col = frame.column(n)
            if col.is_ragged or not col.info.scalar_type.device_ok:
                out[n] = list(col.cells())
            else:
                out[n] = np.asarray(col.data)
        return {"columns": out, "num_rows": frame.num_rows}

    def release(self, frame_id: int):
        self.frames.pop(frame_id, None)
        return {}

    @staticmethod
    def _check_pipeline_paths(source, sink) -> None:
        """Path-based pipeline sources/sinks touch the SERVER's
        filesystem — the only bridge surface that does — so they are
        refused unless the path falls under one of the operator-
        configured ``TFS_BRIDGE_PIPELINE_PATHS`` roots (colon-
        separated).  Registered frames (``frame_id`` sources, frame /
        collect sinks) need no filesystem access and are always
        allowed."""
        wants = []
        if isinstance(source, dict) and "parquet" in source:
            wants.append(("source", source["parquet"]))
        if isinstance(sink, dict) and sink.get("kind") == "parquet":
            wants.append(("sink", sink.get("path")))
        if not wants:
            return
        roots = [
            os.path.realpath(r)
            for r in _env_raw(ENV_PIPELINE_PATHS, "").split(":")
            if r
        ]
        for what, p in wants:
            rp = os.path.realpath(str(p))
            if not any(
                rp == root or rp.startswith(root.rstrip("/") + "/")
                for root in roots
            ):
                raise ValidationError(
                    f"bridge pipeline {what} path {str(p)!r} is not "
                    f"under any {ENV_PIPELINE_PATHS} root "
                    f"({roots or 'none configured'}); path-based "
                    f"sources/sinks read/write the server's "
                    f"filesystem — register a frame and use frame_id "
                    f"(or a collect sink) instead, or have the "
                    f"operator allow the directory"
                )

    def pipeline(self, source=None, stages=None, sink=None, job_id=None):
        """The gated ``pipeline`` RPC (round 18): execute a declarative
        source -> map -> join -> aggregate -> sink streaming pipeline
        (``relational/pipeline.py``) against this session's frames.
        Key-column contracts are verified BEFORE the first window
        dispatches (the ``tfs.check`` TFS14x codes ride the refusal);
        per-window ledgers nest under this request's ledger, so the
        returned window attributions sum to the request's counters
        delta.  The result frame (aggregate / collect sinks) registers
        in the session like any verb output.

        ``job_id`` (round 20) makes the pipeline durable: the journal
        (``TFS_JOURNAL_DIR``) records every window boundary, so a
        client that lost its server (``SessionLost``) reattaches,
        re-registers its frames, and re-issues the SAME spec + job_id —
        the server resumes from the last journaled window, and a job
        that already completed returns its journaled result WITHOUT
        executing (exactly-once, composing with — not relying on — the
        per-session idempotency tokens, which cannot survive a server
        restart).  A resume racing the still-running original is
        refused with the typed ``job_active`` error, never executed
        concurrently."""
        from ..recovery import JobActive
        from ..relational import run_stream_pipeline

        self._check_pipeline_paths(source, sink)
        try:
            out = run_stream_pipeline(
                source,
                stages=stages,
                sink=sink,
                frames=self.frames,
                engine=self.engine,
                job_id=job_id,
            )
        except JobActive as exc:
            raise BridgeServerError(
                str(exc), code="job_active", retry_after_ms=250
            ) from exc
        snaps = out["windows"]
        if len(snaps) > _PIPELINE_WINDOW_SNAPS:
            # bound the reply without breaking the exact-sum contract:
            # the tail's snapshots FOLD into one synthetic entry, so
            # summing the returned windows' counters still equals the
            # request's attribution ledger
            head = snaps[: _PIPELINE_WINDOW_SNAPS - 1]
            tail = snaps[_PIPELINE_WINDOW_SNAPS - 1 :]
            folded: Dict[str, Any] = {
                "correlation_id": (
                    tail[0]["correlation_id"] + "+"
                ),
                "tenant": tail[0]["tenant"],
                "method": tail[0]["method"],
                "folded_windows": len(tail),
                "wall_s": round(sum(s["wall_s"] for s in tail), 6),
                "rows": sum(s["rows"] for s in tail),
                "counters": {},
                "blocks_per_device": {},
                "latency": {},
            }
            for s in tail:
                for k, n in s["counters"].items():
                    folded["counters"][k] = (
                        folded["counters"].get(k, 0) + n
                    )
                for d, n in s["blocks_per_device"].items():
                    folded["blocks_per_device"][d] = (
                        folded["blocks_per_device"].get(d, 0) + n
                    )
            snaps = head + [folded]
        reply: Dict[str, Any] = {
            "rows": out["rows"],
            "windows": snaps,
            "window_count": len(out["windows"]),
            "diagnostics": out["diagnostics"],
            "sink": out["sink"],
        }
        if out.get("resumed"):
            reply["resumed"] = True
        frame = out.get("frame")
        if frame is not None:
            fid = self.register(frame)
            reply["frame_id"] = fid
            reply["schema"] = self._schema(frame)
        return reply

    def check(
        self,
        frame_id: int,
        verb: str,
        graph=None,
        fetches=None,
        inputs=None,
        shapes=None,
        keys=None,
        trim: bool = False,
        right_frame_id=None,
        how: str = "inner",
    ):
        """Pre-dispatch contract verification (``tfs.check``, round 17):
        validate a program against a registered frame WITHOUT paying
        admission, idempotency, or compile costs — returns the
        structured ``TFSxxx`` diagnostics instead of the late refusal
        the matching verb request would earn.

        Deliberately ungated, with a known tradeoff: unlike the other
        ungated methods (all O(1)), a check runs abstract traces
        (``program.analyze`` eval_shape + the classifier's canonical
        probes) on the server thread, outside admission/deadline/
        fair-share scope and unmemoized across RPCs (each call builds a
        fresh Program, so ``_derived`` never hits).  That is the point —
        tenants must be able to validate BEFORE burning admission
        budget — but it means a tenant looping ``check()`` with large
        graphs consumes server CPU the shed machinery cannot see.
        Acceptable while traces are ms-scale; if it bites, the fix is a
        server-side (graph fingerprint, schema) -> diagnostics LRU, not
        gating."""
        frame = self.frame(frame_id)
        from .. import analysis

        v = "map_blocks_trimmed" if (verb == "map_blocks" and trim) else verb
        diags = analysis.check(
            frame,
            graph,
            v,
            fetches=list(fetches) if fetches else None,
            inputs=dict(inputs) if inputs else None,
            shapes=dict(shapes) if shapes else None,
            keys=list(keys) if keys else None,
            # round 18: the relational verbs (join/shuffle) validate
            # key contracts against a second registered frame
            right=(
                self.frame(right_frame_id)
                if right_frame_id is not None
                else None
            ),
            how=how,
        )
        return {"diagnostics": [d.as_dict() for d in diags]}

    def job_status(self, job_id: str = ""):
        """Durable-job status (round 20, ungated): the journal's view
        of ``job_id`` — present/running/interrupted/complete, completed
        boundary, owner liveness.  The resume decision surface: a
        client that caught ``SessionLost`` asks here what survived the
        restart before re-issuing work."""
        from .. import recovery

        return recovery.job_status(str(job_id))

    def ping(self):
        return {"pong": True}


def _approx_payload_bytes(v, _depth: int = 0) -> int:
    """Cheap size estimate of an already-ENCODED (JSON-safe) payload for
    the idem-cache byte bound: strings (inline base64 tensors included)
    dominate real payload size, so summing their lengths approximates
    the wire cost without paying a second full ``json.dumps`` on the
    serving hot path."""
    if isinstance(v, str):
        return len(v)
    if _depth < 16:
        if isinstance(v, dict):
            return sum(
                len(k) + _approx_payload_bytes(x, _depth + 1)
                for k, x in v.items()
            )
        if isinstance(v, (list, tuple)):
            return sum(
                _approx_payload_bytes(x, _depth + 1) for x in v
            )
    return 8


def _error_payload(e: BaseException) -> Dict[str, Any]:
    """Exception -> structured wire error (and the matching evidence
    counter — bumped here, at payload CREATION, so a dedup-served cached
    error never double-counts)."""
    payload: Dict[str, Any] = {"type": type(e).__name__, "message": str(e)}
    if isinstance(e, cancellation.DeadlineExceeded):
        payload["code"] = "deadline_exceeded"
        observability.note_bridge_deadline_exceeded()
    elif isinstance(e, cancellation.Cancelled):
        payload["code"] = "cancelled"
        observability.note_bridge_cancel()
    elif isinstance(e, BridgeServerError):
        payload["code"] = e.code
        for k, v in e.extra.items():
            payload[k] = v
    elif isinstance(getattr(e, "code", None), str):
        # dispatch-time TFSxxx codes (ValidationError / GraphImportError
        # / UnsupportedOpError, round 17) ride the wire too, so a
        # front-end can branch on the same code whether it validated
        # early (the check RPC) or failed late
        payload["code"] = e.code
    return payload


def _sliced_sleep(
    ms: float, scope: Optional[cancellation.CancelScope]
) -> None:
    """An injected stall that still cooperates with cancellation: sleep
    in small slices, checking the scope between them."""
    end = time.monotonic() + ms / 1000.0
    while True:
        if scope is not None:
            scope.check()
        remaining = end - time.monotonic()
        if remaining <= 0:
            return
        time.sleep(min(0.01, remaining))


class _DropReply(Exception):
    """Internal: injected ``bridge_drop`` — sever the connection
    instead of writing the (already computed and dedup-cached) reply."""


class _Handler(socketserver.StreamRequestHandler):
    def setup(self):
        super().setup()
        # keepalive: a client host that dies without FIN/RST (power
        # loss, silent partition) would otherwise block this handler in
        # readline forever with the session pinned at refs=1 — beyond
        # the TTL reaper's reach.  OS keepalive eventually surfaces the
        # dead peer as a read error, which detaches and frees it.
        try:
            self.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1
            )
        except OSError:  # pragma: no cover - exotic socket types
            pass
        self._session: Optional[_Session] = None
        self._err_logged = False
        self._req_cid: Optional[str] = None

    def finish(self):
        if self._session is not None:
            self.server._detach(self._session)  # type: ignore[attr-defined]
            self._session = None
        super().finish()

    def _log_once(self, what: str, exc: BaseException) -> None:
        """Once-per-connection error-path log: the old handler died
        silently when the error reply itself failed (round-11 satellite
        fix); repeated failures on one connection stay one line."""
        if not self._err_logged:
            self._err_logged = True
            logger.warning(
                "bridge connection %s: %s: %s: %s",
                self.client_address,
                what,
                type(exc).__name__,
                exc,
            )

    def handle(self):
        while True:
            try:
                msg, rbins = read_message(self.rfile)
            except (ConnectionError, ValueError):
                return
            mid = msg.get("id")
            try:
                reply, bins = self._run_method(msg, rbins)
            except _DropReply:
                return  # injected dropped reply: sever without writing
            except ConnectionError:
                return
            except Exception as e:  # noqa: BLE001 — surfaced to the client
                reply, bins = {"error": _error_payload(e)}, []
            try:
                write_message(self.wfile, dict(reply, id=mid), bins)
            except ConnectionError:
                # BrokenPipe AND reset-by-peer: an ordinary client
                # disconnect mid-write (e.g. its read-timeout teardown),
                # not a serialization failure — no fallback, no log spam
                return
            except Exception as we:  # noqa: BLE001 — degrade, don't die
                # the reply write itself failed (result payload past a
                # wire cap, serialization bug): fall back to a minimal
                # error so the client is never left waiting on a
                # silently dead loop
                self._log_once("reply write failed", we)
                try:
                    write_message(
                        self.wfile,
                        {
                            "id": mid,
                            "error": {
                                "type": type(we).__name__,
                                "message": str(we),
                            },
                        },
                    )
                except Exception as we2:  # noqa: BLE001
                    self._log_once(
                        "minimal error reply failed; closing", we2
                    )
                    return

    # -- per-request processing ---------------------------------------------

    def _run_method(self, msg: dict, rbins: list):
        """Latency/trace envelope around :meth:`_dispatch` (round 13):
        every bridge method — gated or not, success or refusal — records
        its END-TO-END wall time (admission wait included) into the
        ``bridge`` latency-histogram family, and with the flight
        recorder on, a ``request <method>`` event on this handler
        thread's track."""
        method = msg.get("method")
        label = method if method in _ALL_METHODS else "unknown"
        track = (
            f"bridge/{threading.current_thread().name.split(' ')[0]}"
        )
        t0 = time.perf_counter()
        t_tr = t0 if observability.trace_enabled() else None
        self._req_cid = None  # set by _dispatch for gated requests
        try:
            return self._dispatch(msg, rbins, method, track)
        finally:
            observability.record_latency(
                "bridge", label, time.perf_counter() - t0
            )
            # the request event closes AFTER the ledger context is
            # reset, so the cid is passed explicitly (round 15)
            if self._req_cid is not None:
                observability.trace_complete(
                    f"request {label}", track, t_tr, cid=self._req_cid
                )
            else:
                observability.trace_complete(
                    f"request {label}", track, t_tr
                )

    def _dispatch(self, msg: dict, rbins: list, method, track: str):
        """-> ``(reply_without_id, bins)``; raises ``_DropReply`` for an
        injected dropped reply and structured exceptions for refusals."""
        server = self.server  # type: ignore[attr-defined]
        if not isinstance(method, str) or method.startswith("_"):
            raise AttributeError(f"unknown method {method!r}")

        # connection-scoped control plane (no session state touched)
        if method == "hello":
            params = decode_value(msg.get("params") or {}, rbins)
            sess = server._attach(params.get("session"))
            # ALWAYS balance the previous attach — a repeated hello with
            # the same token would otherwise leak a ref (attach bumps
            # refs every time; finish() only decrements once), pinning
            # the session past its TTL forever
            if self._session is not None:
                server._detach(self._session)
            self._session = sess
            return {
                "result": {
                    "session": sess.token,
                    "pv": PROTOCOL_VERSION,
                    # round 21 (additive): which replica answered, so a
                    # failover client can tell whether its reattach
                    # landed somewhere new
                    "replica": server.replica_identity(),
                }
            }, []
        if method == "health":
            bins: list = []
            return {
                "result": encode_value(server.health_snapshot(), bins)
            }, bins
        if method == "metrics":
            # ungated like health: a saturated or draining server must
            # still be scrapeable — that is when the metrics matter
            return {"result": {"text": server.metrics_text()}}, []
        if method == "attribution":
            # ungated like metrics: per-request cost attribution must be
            # readable from a saturated server (that is when a tenant's
            # spend matters most)
            params = decode_value(msg.get("params") or {}, rbins)
            bins = []
            return {
                "result": encode_value(
                    server.attribution_snapshot(
                        params.get("correlation_id")
                    ),
                    bins,
                )
            }, bins

        sess = self._session
        if sess is None:
            # legacy no-hello path: an implicit session that dies with
            # the connection (nothing to reattach to without a token)
            sess = self._session = server._attach(None)
            sess.explicit = False
        if method == "end_session":
            server._drop_session(sess)
            # unbind: the next request on this connection re-attaches a
            # fresh REGISTERED session instead of executing against a
            # zombie the reaper and health can no longer see
            self._session = None
            return {"result": {}}, []

        call_i = sess.next_call_index(method)
        fplan = (
            faults.maybe_inject_bridge(method, call_i)
            if faults.bridge_active()
            else None
        )
        if fplan is not None and fplan.kill_after_ms is not None:
            # round 21 chaos: arm a real SIGKILL on a daemon timer and
            # keep executing — the process dies MID-request, exactly the
            # death the fleet failover + journal migration must survive
            faults.schedule_replica_kill(fplan.kill_after_ms)
        gated = method in _GATED_METHODS
        if not gated:
            if method not in _UNGATED_METHODS:
                raise AttributeError(f"unknown method {method!r}")
            if fplan is not None and fplan.stall_ms:
                # ungated methods have no cancel scope; the stall still
                # applies (chaos on ping/schema/release exercises client
                # timeouts), just uncancellable
                _sliced_sleep(fplan.stall_ms, None)
            params = decode_value(msg.get("params") or {}, rbins)
            result = getattr(sess, method)(**params)
            return self._finish_reply(
                *self._encode_result(method, result), fplan
            )

        deadline_ms = msg.get("deadline_ms")
        scope = cancellation.CancelScope(
            deadline_s=(
                float(deadline_ms) / 1000.0
                if deadline_ms is not None
                else None
            ),
            label=f"bridge:{method}",
        )

        # request-scoped telemetry (round 15): the client-stamped
        # correlation id (or a server-minted one) becomes a RequestLedger
        # on the contextvar — alongside the cancel scope — for the whole
        # gated request: admission wait, execution, every engine /
        # staging-lane / fault counter bump and trace event attribute to
        # it.  The envelope keys are additive (old clients simply get
        # server-minted cids).
        cid = msg.get("cid")
        cid = cid if isinstance(cid, str) and cid else (
            observability.new_correlation_id()
        )
        tenant = msg.get("tenant")
        tenant = tenant if isinstance(tenant, str) and tenant else None
        self._req_cid = cid
        ledger = observability.RequestLedger(
            cid, tenant=tenant, method=f"bridge:{method}"
        )
        ledger_token = observability.activate_request(ledger)
        try:
            return self._dispatch_gated(
                msg, rbins, method, track, sess, scope, fplan
            )
        finally:
            observability.deactivate_request(ledger_token)
            ledger.finish()
            server._record_attribution(ledger)

    def _dispatch_gated(
        self, msg, rbins, method, track, sess, scope, fplan
    ):
        """The admission-gated request body (factored out in round 15 so
        the request-ledger install/finish wraps it cleanly)."""
        server = self.server  # type: ignore[attr-defined]

        # idempotency dedup BEFORE admission: a retried request whose
        # first run already recorded an outcome is served that outcome
        # without costing an admission slot; a retry racing its ORIGINAL
        # (client read-timeout while the verb still runs) waits for the
        # original's outcome instead of double-executing
        idem = msg.get("idem")
        owner = False
        if isinstance(idem, str):
            state, val = sess.idem_begin(idem)
            if state == "hit":
                observability.note_bridge_idem_hit()
                kind, payload, bins = val[:3]
                return self._finish_reply(
                    {("result" if kind == "result" else "error"): payload},
                    bins,
                    fplan,
                )
            if state == "wait":
                remaining = scope.time_remaining()
                val.wait(
                    _IDEM_WAIT_CAP_S
                    if remaining is None
                    else max(0.0, min(remaining, _IDEM_WAIT_CAP_S))
                )
                hit = sess.idem_lookup(idem)
                if hit is not None:
                    observability.note_bridge_idem_hit()
                    kind, payload, bins = hit[:3]
                    return self._finish_reply(
                        {
                            ("result" if kind == "result" else "error"):
                            payload
                        },
                        bins,
                        fplan,
                    )
                # an expired deadline while waiting is a deadline, not a
                # conflict — clients branch on deadline_exceeded to stop
                # retrying a dead request
                scope.check()
                raise BridgeServerError(
                    f"idempotent retry of {method} raced its original "
                    f"execution and no outcome was recorded within the "
                    f"wait window; retry again later",
                    code="retry_conflict",
                )
            owner = True
        else:
            idem = None

        # gated: admission -> cancel scope -> execute -> encode; every
        # outcome (success or error) is dedup-cached under the idem
        # token, and waiters are woken even when admission refuses
        entry = None
        try:
            # SLO-aware admission policy (round 16) BEFORE the gate: an
            # over-budget tenant (or the dominant consumer under tail
            # pressure) is shed with a structured hint instead of
            # queueing into the very backlog that blows p99.  Only the
            # BILLED compute verbs are subject to it — shedding a cheap
            # metadata call (create_frame/analyze) frees nothing and
            # just burns the tenant's retries.
            decision = (
                server.scheduler.check(
                    getattr(
                        observability.current_request(), "tenant", None
                    ),
                    contention=(
                        server.gate.max_inflight > 0
                        and (
                            server.gate.queued > 0
                            or server.gate.inflight
                            >= server.gate.max_inflight
                        )
                    ),
                )
                if method in server._BILLED_METHODS
                else None
            )
            if decision is not None:
                observability.note_bridge_shed()
                raise ServerBusy(
                    f"{method} shed by the SLO scheduler "
                    f"({decision['reason']}: tenant "
                    f"{decision['tenant']!r} used "
                    f"{decision.get('rows_used', 0)} rows in the "
                    f"window)",
                    **decision,
                )
            # flight recorder: admission wait and execution are separate
            # events on this handler's track, so queueing-vs-compute time
            # is visible per request in the Perfetto view
            t_admit = observability.trace_now()
            server.gate.admit(scope)
            observability.trace_complete(f"admit {method}", track, t_admit)
            server._register_scope(scope)
            t_exec = observability.trace_now()
            try:
                with observability.verb_span(
                    f"bridge:{method}", 0, 0
                ) as span:
                    span.annotate("admission", server.gate.snapshot())
                    try:
                        # decode AFTER admission: a shed request must not
                        # pay the base64/ndarray materialization CPU the
                        # gate exists to protect admitted requests from
                        params = decode_value(
                            msg.get("params") or {}, rbins
                        )
                        if fplan is not None and fplan.stall_ms:
                            _sliced_sleep(fplan.stall_ms, scope)
                        with cancellation.activate(scope):
                            scope.check()  # deadline may have passed queued
                            observability.note_bridge_verb_executed()
                            if method in ("map_blocks", "map_rows"):
                                # round 16: map verbs route through the
                                # coalescer (warm program pool + micro-
                                # batching); solo when coalescing is off
                                result = server.coalescer.run_map_verb(
                                    sess, method, scope=scope, **params
                                )
                            elif method == "aggregate":
                                result = sess.run_df_verb(method, **params)
                            elif method in ("reduce_blocks", "reduce_rows"):
                                result = sess.run_row_verb(method, **params)
                            elif method == "warm":
                                result = server.warm_program(**params)
                            elif method == "decode":
                                result = server.run_decode(**params)
                            else:  # create_frame / analyze / collect
                                result = getattr(sess, method)(**params)
                            server._note_usage(sess, method, params)
                        reply, bins = self._encode_result(method, result)
                        entry = ("result", reply["result"], bins)
                    except Exception as e:  # noqa: BLE001 — structured
                        span.annotate("failed", True)
                        payload = _error_payload(e)
                        reply, bins = {"error": payload}, []
                        entry = ("error", payload, [])
            finally:
                observability.trace_complete(
                    f"execute {method}", track, t_exec
                )
                server._unregister_scope(scope)
                server.gate.release()
        finally:
            if owner:
                sess.idem_finish(idem, entry)
        return self._finish_reply(reply, bins, fplan)

    def _encode_result(self, method: str, result):
        """Encode a successful result, preserving execution context when
        serialization itself fails (round-11 satellite: the old path
        surfaced a bare encoding error as if the verb had failed)."""
        bins: list = []
        try:
            return {"result": encode_value(result, bins)}, bins
        except Exception as enc_exc:  # noqa: BLE001
            self._log_once("result serialization failed", enc_exc)
            raise ResultEncodingError(
                f"{method} executed, but its result could not be "
                f"serialized: {type(enc_exc).__name__}: {enc_exc}"
            ) from enc_exc

    def _finish_reply(self, reply, bins, fplan):
        """Apply injected reply-path chaos: delay, then drop.  The drop
        counts in ``faults_injected`` HERE — at the point the
        connection is actually severed — so a request refused before
        its reply (shed, draining) never reads as a fired fault."""
        if fplan is not None:
            if fplan.delay_ms:
                time.sleep(fplan.delay_ms / 1000.0)
            if fplan.drop:
                observability.note_fault_injected()
                logger.warning(
                    "bridge: injected dropped reply (bridge_drop); "
                    "severing %s",
                    self.client_address,
                )
                raise _DropReply()
        return reply, bins


class BridgeServer(socketserver.ThreadingTCPServer):
    """Localhost TCP bridge server; sessions are token-addressed and
    survive their connections (``hello`` reattaches).

    The protocol executes client-supplied programs and is UNauthenticated —
    it is a local IPC seam (the analog of the reference's in-process Py4J
    gateway), not a network service.  Binding a non-loopback address
    therefore requires ``allow_remote=True``, an explicit statement that
    the network path is trusted (e.g. inside a pod's private fabric)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        engine=None,
        allow_remote: bool = False,
        max_inflight: Optional[int] = None,
        queue_depth: Optional[int] = None,
        drain_s: Optional[float] = None,
        max_frames: Optional[int] = None,
        session_ttl_s: Optional[float] = None,
        coalesce_us: Optional[float] = None,
        coalesce_rows: Optional[int] = None,
        warm_spec: Optional[str] = None,
        fair_rows: Optional[int] = None,
        fair_window_s: Optional[float] = None,
        slo_ms: Optional[float] = None,
        decode_model: Optional[Dict[str, Any]] = None,
    ):
        if not allow_remote and host not in ("127.0.0.1", "::1", "localhost"):
            raise ValueError(
                f"refusing to bind the unauthenticated bridge to {host!r}; "
                f"pass allow_remote=True only on a trusted network"
            )
        super().__init__((host, port), _Handler)
        self.engine = engine
        self.gate = AdmissionGate(
            _env_int(ENV_MAX_INFLIGHT, DEFAULT_MAX_INFLIGHT)
            if max_inflight is None
            else max_inflight,
            _env_int(ENV_QUEUE_DEPTH, DEFAULT_QUEUE_DEPTH)
            if queue_depth is None
            else queue_depth,
        )
        self.drain_s = (
            _env_float(ENV_DRAIN_S, DEFAULT_DRAIN_S)
            if drain_s is None
            else float(drain_s)
        )
        self.max_frames = (
            _env_int(ENV_MAX_FRAMES, DEFAULT_MAX_FRAMES)
            if max_frames is None
            else int(max_frames)
        )
        self.session_ttl_s = (
            _env_float(ENV_SESSION_TTL_S, DEFAULT_SESSION_TTL_S)
            if session_ttl_s is None
            else float(session_ttl_s)
        )
        # round 16 — the serving throughput layer: request coalescing
        # over a warm program pool, and the SLO-aware admission policy
        # consulted BEFORE the gate (fair-share row budgets + proactive
        # tail shedding).  Knobs come from the env unless constructor
        # overrides are passed (like every other bridge knob).
        self.coalescer = _coalescer.Coalescer(
            engine=engine,
            wait_us=coalesce_us,
            max_rows=coalesce_rows,
            warm=_coalescer.WarmPool(
                _coalescer.WarmSpec.from_env(warm_spec)
                if warm_spec is not None
                else None
            ),
            register_scope=self._register_scope,
            unregister_scope=self._unregister_scope,
        )
        self.scheduler = _coalescer.SloScheduler(
            fair_rows=fair_rows, window_s=fair_window_s, slo_ms=slo_ms
        )
        # round 22 — paged continuous decode: a server given a model
        # (``decode_model={"params": ..., "cfg": ..., [draft_params,
        # draft_cfg, max_slots, tokens_per_page, max_seq, pool_pages]}``)
        # serves the gated ``decode`` RPC through a DecodeScheduler
        # whose slots hold page tables into one shared PagePool; no
        # model configured = the method refuses with a typed error
        self.decode_scheduler = None
        if decode_model is not None:
            dm = dict(decode_model)
            self.decode_scheduler = _coalescer.DecodeScheduler(
                dm.pop("params"), dm.pop("cfg"), **dm
            )
        # round 21 — stable replica identity: pid + a start-time epoch
        # token.  The NAME is stable across restarts (the fleet spawner
        # pins it via TFS_FLEET_REPLICA); the EPOCH changes every start,
        # which is how a router tells "same replica recovered" from
        # "replica restarted" without guessing from connection resets.
        self._started_mono = time.monotonic()
        self._replica_name = _env_raw(_fleet.ENV_FLEET_REPLICA, "")
        self._replica_epoch = f"{os.getpid():x}-{uuid.uuid4().hex[:12]}"
        self._sessions: Dict[str, _Session] = {}
        self._sessions_lock = threading.Lock()
        # per-request attribution history (round 15): ledger snapshots
        # keyed by correlation id, bounded LRU-by-arrival
        self._attribution: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        self._attribution_lock = threading.Lock()
        self._scopes: set = set()
        self._scopes_lock = threading.Lock()
        self._closed = False
        # periodic reaper: attach/detach/health also reap
        # opportunistically, but only a timer guarantees an idle host
        # (no further connections, no health polls) releases a crashed
        # client's frames once their session passes the TTL
        self._reaper_stop = threading.Event()
        if self.session_ttl_s > 0:
            t = threading.Thread(
                target=self._reap_loop, name="tfs-bridge-reaper", daemon=True
            )
            t.start()
        # round 21 — fleet registry heartbeat: one atomic JSON file per
        # replica whose mtime is the liveness signal the janitor and
        # peers trust ACROSS processes (a same-host ``os.kill(pid, 0)``
        # cannot see into another container or pid namespace).  No
        # registry configured (TFS_FLEET_REGISTRY unset) = no-op.
        self._registry_dir = _fleet.registry_dir()
        if self._registry_dir:
            self._registry_beat()
            threading.Thread(
                target=self._registry_loop,
                name="tfs-fleet-heartbeat",
                daemon=True,
            ).start()
        # metrics exposition (round 13): the admission gauges register as
        # providers so the standalone TFS_METRICS_PORT endpoint (started
        # here from the env when set) scrapes them alongside the process
        # counters/histograms; close() unregisters exactly these
        # closures, so a replacement server's providers survive
        # ONE grouped provider, not three: the gauges come from a single
        # gate.snapshot() per scrape, so inflight/queued/draining are
        # mutually consistent (three independent lambdas could read
        # three different gate states mid-load).  No shed gauge: the
        # process-wide ``bridge_shed`` counter already exposes sheds as
        # tfs_bridge_shed_total — a same-named gauge would emit a
        # duplicate TYPE family.
        self._gauge_providers = {
            "tfs_bridge_admission": self._admission_gauges,
            # round 16: coalescer queue depth / open programs / warm-pool
            # residency — ONE grouped provider per the round-13 rule
            # (one snapshot per scrape, no counter-name collisions)
            "tfs_bridge_coalescer": self.coalescer.gauges,
        }
        if self.decode_scheduler is not None:
            # round 22: the tfs_kv_pages gauge family (pool occupancy +
            # slot population) — grouped, one snapshot per scrape
            self._gauge_providers["tfs_kv_pages"] = (
                self.decode_scheduler.gauges
            )
        for name, fn in self._gauge_providers.items():
            observability.register_gauge(name, fn)
        observability.maybe_start_metrics_server()
        # durable-execution startup recovery (round 20): a restarted
        # server inherits the journal's view of the world — reclaim
        # dead processes' spill/journal leftovers (the orphan janitor)
        # and inventory the interrupted jobs a reattaching client can
        # resume (surfaced via health + the job_status RPC).  Never
        # blocks or fails server start.
        self._journal_recovery: Dict[str, Any] = {"configured": False}
        try:
            from .. import recovery as _recovery

            if _recovery.configured():
                arts = _recovery.janitor.scan()
                reclaimed = _recovery.janitor.reclaim(artifacts=arts)
                interrupted = sorted(
                    _recovery.janitor.summary(arts)["interrupted_jobs"]
                )
                self._journal_recovery = {
                    "configured": True,
                    "interrupted_jobs": interrupted,
                    "reclaimed_count": reclaimed["count"],
                    "reclaimed_bytes": reclaimed["bytes"],
                }
                if interrupted:
                    logger.info(
                        "bridge: journal holds %d resumable job(s) "
                        "from dead processes: %s",
                        len(interrupted),
                        interrupted,
                    )
        except Exception:  # noqa: BLE001 — recovery must not block start
            logger.warning(
                "bridge: journal startup recovery failed", exc_info=True
            )

    def _registry_name(self) -> str:
        return self._replica_name or f"pid{os.getpid()}"

    def _registry_beat(self) -> None:
        try:
            _fleet.registry_write(
                self._registry_name(),
                self.address[0],
                self.address[1],
                epoch=self._replica_epoch,
                root=self._registry_dir,
            )
        except OSError:
            logger.warning(
                "bridge: fleet-registry heartbeat failed", exc_info=True
            )

    def _registry_loop(self) -> None:
        # 3 beats per TTL: one missed write (busy box, slow fs) never
        # reads as death
        interval = max(0.5, _fleet.REGISTRY_TTL_S / 3.0)
        while not self._reaper_stop.wait(interval):
            self._registry_beat()

    def _admission_gauges(self) -> Dict[str, Any]:
        s = self.gate.snapshot()
        return {
            "tfs_bridge_inflight": s["inflight"],
            "tfs_bridge_queued": s["queued"],
            "tfs_bridge_draining": int(s["draining"]),
        }

    @property
    def address(self):
        return self.server_address

    # -- session registry ----------------------------------------------------

    def _attach(self, token: Optional[str]) -> _Session:
        now = time.monotonic()
        with self._sessions_lock:
            self._reap_locked(now)
            if token is not None:
                sess = self._sessions.get(token)
                if sess is None:
                    raise BridgeServerError(
                        f"unknown or expired session {token!r} (frames do "
                        f"not survive a session's TTL; create a new one)",
                        code="unknown_session",
                    )
                sess.refs += 1
                sess.last_active = now
                return sess
            tok = uuid.uuid4().hex
            sess = _Session(
                engine=self.engine, token=tok, max_frames=self.max_frames
            )
            sess.explicit = True
            sess.refs = 1
            self._sessions[tok] = sess
            return sess

    def _detach(self, sess: _Session) -> None:
        now = time.monotonic()
        with self._sessions_lock:
            sess.refs -= 1
            sess.last_active = now
            if sess.refs <= 0 and not sess.explicit:
                self._sessions.pop(sess.token, None)
            # reap on every disconnect too (not just new attaches), so a
            # host whose clients all left does not retain their frames
            # past the TTL waiting for a connection that never comes
            self._reap_locked(now)

    def _drop_session(self, sess: _Session) -> None:
        with self._sessions_lock:
            self._sessions.pop(sess.token, None)
            sess.frames.clear()

    def _reap_loop(self) -> None:
        interval = max(1.0, min(self.session_ttl_s / 2.0, 60.0))
        while not self._reaper_stop.wait(interval):
            with self._sessions_lock:
                self._reap_locked(time.monotonic())

    def _reap_locked(self, now: float) -> None:
        if self.session_ttl_s <= 0:
            return
        dead = [
            tok
            for tok, s in self._sessions.items()
            if s.refs <= 0 and now - s.last_active > self.session_ttl_s
        ]
        for tok in dead:
            s = self._sessions.pop(tok)
            logger.info(
                "bridge: reaped idle session %s (%d frames)",
                tok[:8],
                len(s.frames),
            )

    # -- in-flight scope registry (drain cancellation) -----------------------

    def _register_scope(self, scope: cancellation.CancelScope) -> None:
        with self._scopes_lock:
            self._scopes.add(scope)

    def _unregister_scope(self, scope: cancellation.CancelScope) -> None:
        with self._scopes_lock:
            self._scopes.discard(scope)

    # -- serving throughput layer (round 16) ---------------------------------

    # methods whose rows bill the tenant's fair-share window: the
    # compute/data-moving verbs.  Metadata ops (create_frame, analyze,
    # warm) are not usage — billing them would charge a tenant for
    # DESCRIBING work it never ran.
    _BILLED_METHODS = frozenset(
        {
            "map_blocks",
            "map_rows",
            "aggregate",
            "reduce_blocks",
            "reduce_rows",
            "collect",
            # round 22: decode bills GENERATED TOKENS (not frame rows)
            # to the tenant's fair-share window — the billing happens in
            # run_decode once the count is known; membership here puts
            # decode under the SLO scheduler's shed policy like every
            # other compute verb
            "decode",
        }
    )

    def _note_usage(self, sess: _Session, method: str, params) -> None:
        """Bill an executed gated request's rows to its tenant's
        fair-share window (frame-addressed compute verbs only; the rows
        are the INPUT frame's — the work the request put on the
        machine)."""
        if not self.scheduler.enabled():
            return
        if method not in self._BILLED_METHODS:
            return
        fid = params.get("frame_id") if isinstance(params, dict) else None
        if fid is None:
            return
        frame = sess.frames.get(fid)
        if frame is None:
            return
        led = observability.current_request()
        self.scheduler.note(
            led.tenant if led is not None else None, frame.num_rows
        )

    def warm_program(
        self,
        graph=None,
        fetches=None,
        inputs=None,
        shapes=None,
        verb: str = "map_rows",
        trim: bool = False,
        columns=None,
        rows=None,
    ) -> Dict[str, Any]:
        """The gated ``warm`` RPC (round 16): register the program in
        the warm pool and AOT-prime its ``(bucket, device)`` executable
        grid via ``Executor.warmup`` — backed by the persistent compile
        cache (``TFS_COMPILE_CACHE``), so a restarted server's priming
        is a disk fetch, and the first real request pays neither the
        GraphDef import nor the compile.

        ``columns`` maps column name -> a small sample array (>= 0 rows;
        only dtype + cell shape are read); ``rows`` lists the block row
        counts to prime (default: the ``TFS_BRIDGE_WARM`` spec's
        ``buckets``)."""
        if verb not in ("map_rows", "map_blocks"):
            raise BridgeServerError(
                f"warm supports the map verbs, not {verb!r}",
                code="bad_request",
            )
        if not columns:
            raise BridgeServerError(
                "warm needs columns={name: sample array} to learn the "
                "schema it should prime",
                code="bad_request",
            )
        sizes = [int(r) for r in (rows or []) if int(r) > 0]
        if not sizes:
            sizes = [
                b for b in self.coalescer.warm.spec.buckets if b > 0
            ]
        if not sizes:
            raise BridgeServerError(
                f"warm needs rows=[...] (or buckets in {_coalescer.ENV_WARM})",
                code="bad_request",
            )
        _, ent, hit = self.coalescer.warm.entry(
            verb, graph, fetches, inputs, shapes, trim
        )
        ex = _engine_mod._resolve(self.engine)
        n_lanes = (
            len(device_pool.pool_devices())
            if device_pool.enabled()
            else 1
        )
        fps = []
        for r in sizes:
            cols = {}
            for name, sample in columns.items():
                arr = np.asarray(sample)
                cols[name] = np.zeros(
                    (r * max(1, n_lanes),) + arr.shape[1:], arr.dtype
                )
            frame = TensorFrame.from_arrays(
                cols, num_blocks=max(1, n_lanes)
            )
            fps.extend(
                ex.warmup(
                    ent.program, frame, rows_level=(verb == "map_rows")
                )
            )
            # Executor.warmup primes the (bucket, device) grid for
            # POOL/cached topologies; a single-default-device server
            # (the common serving child) still needs the dispatch
            # entry's jit cache seeded by one real execution — programs
            # are pure by contract, so a zeros dispatch has no effect
            # beyond the caches, and trace counting is suppressed
            # (warmup is analysis, not traffic)
            with observability.suppress_trace_count():
                warm_frame = TensorFrame.from_arrays(
                    {
                        name: np.zeros(
                            (r,) + np.asarray(s).shape[1:],
                            np.asarray(s).dtype,
                        )
                        for name, s in columns.items()
                    },
                    num_blocks=1,
                )
                if verb == "map_rows":
                    ex.map_rows(ent.program, warm_frame)
                else:
                    ex.map_blocks(ent.program, warm_frame, trim=trim)
        return {
            "primed_rows": sizes,
            "buckets": sorted(
                {bucketing.bucket_for(r) for r in sizes}
            ),
            "executables": len(set(fps)),
            "devices": max(1, n_lanes),
            "warm_hit": hit,
            "resident": len(self.coalescer.warm),
        }

    def run_decode(
        self,
        prompt=None,
        max_new: int = 16,
        speculative: bool = False,
        gamma: int = 4,
        stop_token: Optional[int] = None,
    ) -> Dict[str, Any]:
        """The gated ``decode`` RPC (round 22): stream ``max_new``
        greedy tokens continuing ``prompt`` through the paged decode
        scheduler.  The request joins the running slot batch at the
        next step boundary; its cancel scope (deadline/cancel/drain) is
        honoured at step boundaries, where retirement frees the
        sequence's KV pages.  ``speculative=True`` opts this request
        into the draft/verify path (needs a draft model configured;
        runs solo — B=1 by its contract — and is verified bit-exactly
        by the target model).  Generated tokens bill the tenant's
        fair-share window; page-pool/slot exhaustion surfaces as
        ``server_busy`` with ``retry_after_ms``."""
        sched = self.decode_scheduler
        if sched is None:
            raise BridgeServerError(
                "this server has no decode model configured "
                "(BridgeServer(decode_model={'params': ..., 'cfg': ...}))",
                code="decode_unavailable",
            )
        prompt = np.asarray(prompt if prompt is not None else [], np.int64)
        if prompt.ndim != 1 or prompt.size < 1:
            raise BridgeServerError(
                "decode needs prompt=[t0, t1, ...] (a non-empty 1-D "
                "token list)",
                code="bad_request",
            )
        led = observability.current_request()
        tenant = led.tenant if led is not None else None
        until = (
            (lambda t, s=int(stop_token): t == s)
            if stop_token is not None
            else None
        )
        try:
            if speculative:
                toks = sched.speculative(
                    prompt, int(max_new), gamma=int(gamma), tenant=tenant
                )
                if until is not None:
                    for i, t in enumerate(toks):
                        if until(t):
                            toks = toks[: i + 1]
                            break
            else:
                toks = sched.submit(
                    prompt, int(max_new), until=until, tenant=tenant
                )
        except _coalescer.DecodeRefused as e:
            raise ServerBusy(
                str(e),
                retry_after_ms=e.retry_after_ms,
                reason=e.reason,
            ) from e
        # tokens are the work decode put on the machine — the billing
        # unit for its fair-share window (frame verbs bill rows)
        if self.scheduler.enabled():
            self.scheduler.note(tenant, len(toks))
        return {
            "tokens": [int(t) for t in toks],
            "generated": len(toks),
            "speculative": bool(speculative),
        }

    # -- health --------------------------------------------------------------

    def replica_identity(self) -> Dict[str, Any]:
        """Stable replica identity (round 21): fleet-assigned name
        (stable across restarts; '' outside a fleet), pid, start-time
        EPOCH token (new every start — a router seeing a new epoch
        under an old name knows the replica RESTARTED rather than
        recovered, without guessing from connection resets), uptime."""
        return {
            "name": self._replica_name,
            "pid": os.getpid(),
            "epoch": self._replica_epoch,
            "uptime_s": round(time.monotonic() - self._started_mono, 3),
        }

    def health_snapshot(self) -> Dict[str, Any]:
        """The ``health`` RPC body: admission depth, drain state,
        session/frame counts, device-quarantine history (PR 4), and HBM
        budget occupancy (PR 5) — enough for a client-side balancer to
        route around a sick or saturated server."""
        gate = self.gate.snapshot()
        with self._sessions_lock:
            # health polls double as the idle-host reaper tick
            self._reap_locked(time.monotonic())
            n_sessions = len(self._sessions)
            n_frames = sum(len(s.frames) for s in self._sessions.values())
        c = observability.counters()
        return {
            "status": "draining" if gate["draining"] else "ok",
            **gate,
            # round 21: who answered — the fleet router keys flap/restart
            # detection off the epoch token in here
            "replica": self.replica_identity(),
            "sessions": n_sessions,
            "frames": n_frames,
            "quarantined_devices": device_pool.recently_quarantined(),
            "hbm": {
                "budget_bytes": frame_cache.hbm_budget(),
                "resident_bytes": frame_cache.budget_bytes_resident(),
            },
            # round 16: coalescer + SLO-scheduler state (queue depth per
            # program, batch-size histogram, warm-pool residency,
            # per-tenant window usage) for serving dashboards/balancers
            "coalescer": self.coalescer.snapshot(),
            "scheduler": self.scheduler.snapshot(),
            # round 22: paged-decode population + page-pool occupancy
            # (None when no decode model is configured)
            "decode": (
                self.decode_scheduler.snapshot()
                if self.decode_scheduler is not None
                else None
            ),
            # round 20: what the startup janitor found — whether a
            # journal is configured, the resumable jobs dead processes
            # left, and the stale bytes reclaimed at start
            "journal": self._journal_recovery,
            "counters": {
                k: c[k]
                for k in (
                    "bridge_deadline_exceeded",
                    "bridge_shed",
                    "bridge_cancels",
                    "bridge_idem_hits",
                    "bridge_verbs_executed",
                    "devices_quarantined",
                    "coalesced_batches",
                    "coalesced_requests",
                    "coalesce_solo_requests",
                    "warm_program_hits",
                    "fair_share_sheds",
                    "slo_sheds",
                    # round 21: the fleet acceptance evidence — journal
                    # exactly-once accounting, persistent-compile-cache
                    # hits (zero-recompile proof on warm rejoin), and
                    # the fleet lifecycle counters
                    "stream_windows",
                    "journal_appends",
                    "journal_windows_skipped",
                    "journal_resumes",
                    "journal_fence_rejections",
                    "persistent_cache_hits",
                    "persistent_cache_misses",
                    "fleet_failovers",
                    "fleet_jobs_migrated",
                    "fleet_quarantines",
                    "fleet_replica_restarts",
                    # round 22: paged-decode acceptance evidence —
                    # tokens served, page churn, prefill batching
                    "decode_tokens",
                    "kv_pages_allocated",
                    "kv_pages_freed",
                    "decode_prefill_batches",
                )
            },
            # round 13: the gauge snapshot serving operators need
            # without scraping the metrics endpoint — host-byte
            # high-water and flight-recorder depth/drop state
            "gauges": {
                "live_host_bytes": observability.live_host_bytes(),
                "peak_host_bytes": c["peak_host_bytes"],
                "trace_enabled": observability.trace_enabled(),
                "trace_events": observability.trace_depth(),
                "trace_drops": observability.trace_drops(),
            },
        }

    def metrics_text(self) -> str:
        """The ``metrics`` RPC body: the process-wide Prometheus text
        (counters, gauges, verb + bridge latency histograms) with THIS
        server's admission gauges merged in — a multi-server process's
        RPC always reflects the server that answered it."""
        return observability.metrics_text(
            extra_gauges=self._admission_gauges()
        )

    # -- per-request attribution (round 15) ----------------------------------

    def _record_attribution(self, ledger) -> None:
        """Retain one finished request ledger's snapshot for the
        ``attribution`` RPC (bounded history).  A retry served from the
        idempotency dedup cache arrives under the SAME correlation id
        as its original execution (the client keeps the cid stable
        across reconnects, like the idem token) with a near-empty
        ledger — it must never REPLACE the original's attribution, so a
        non-executing snapshot yields to an existing executed one."""
        snap = ledger.snapshot()
        cid = ledger.correlation_id
        with self._attribution_lock:
            old = self._attribution.get(cid)
            if (
                old is not None
                and old["counters"].get("bridge_verbs_executed")
                and not snap["counters"].get("bridge_verbs_executed")
            ):
                self._attribution.move_to_end(cid)
                return
            self._attribution[cid] = snap
            self._attribution.move_to_end(cid)
            while len(self._attribution) > _ATTRIBUTION_CAP:
                self._attribution.popitem(last=False)

    def attribution_snapshot(
        self, correlation_id: Optional[str] = None
    ) -> Dict[str, Any]:
        """The ``attribution`` RPC body: one request's ledger (by
        correlation id) or the recent-request history, newest last —
        counters-delta resource usage, blocks/rows per device, per-verb
        latency, and wall time, each stamped with its correlation id and
        tenant."""
        with self._attribution_lock:
            if correlation_id is not None:
                snap = self._attribution.get(correlation_id)
                return {
                    "found": snap is not None,
                    "ledger": snap,
                    "retained": len(self._attribution),
                }
            recent = list(self._attribution.values())[-_ATTRIBUTION_RECENT:]
            return {"recent": recent, "retained": len(self._attribution)}

    # -- lifecycle -----------------------------------------------------------

    def close(self, drain_s: Optional[float] = None) -> None:
        """Graceful drain, then stop serving and release the socket.

        Phases: (1) reject new admissions with ``draining``; (2) wait up
        to ``drain_s`` (default ``TFS_BRIDGE_DRAIN_S``) for in-flight
        gated requests to finish; (3) cooperatively cancel stragglers
        through their cancel scopes (they surface a structured
        ``cancelled`` error at their next block boundary) and give them
        a short grace period; (4) shutdown + server_close."""
        if self._closed:
            return
        self._closed = True
        self._reaper_stop.set()
        if self._registry_dir:
            # leave no heartbeat behind: a cleanly-closed replica's pid
            # must not pin journal artifacts against the janitor
            _fleet.registry_remove(
                self._registry_name(), root=self._registry_dir
            )
        for name, fn in self._gauge_providers.items():
            observability.unregister_gauge(name, fn)
        budget = self.drain_s if drain_s is None else float(drain_s)
        self.gate.start_draining()
        if not self.gate.wait_idle(budget):
            with self._scopes_lock:
                stragglers = list(self._scopes)
            logger.warning(
                "bridge: drain window (%.1fs) expired with %d request(s) "
                "in flight; cancelling cooperatively",
                budget,
                len(stragglers),
            )
            for scope in stragglers:
                scope.cancel("server draining")
            # short FIXED grace: cancellation lands at the next block
            # boundary, which does not scale with the drain budget —
            # close() is bounded by budget + 1s, not 2x budget
            self.gate.wait_idle(1.0)
        if self.decode_scheduler is not None:
            # after the gate drained/cancelled: in-flight decode
            # requests' scopes were cancelled above, so the driver
            # retires them (freeing their pages) at its next boundary
            self.decode_scheduler.close()
        self.shutdown()
        self.server_close()


def serve(
    host: str = "127.0.0.1",
    port: int = 0,
    engine=None,
    background: bool = True,
    allow_remote: bool = False,
    **server_kw,
) -> BridgeServer:
    """Start a bridge server; ``background=True`` runs it on a daemon
    thread and returns immediately (``server.address`` has the bound
    port).  ``server_kw`` forwards the resilience knobs
    (``max_inflight``, ``queue_depth``, ``drain_s``, ``max_frames``,
    ``session_ttl_s``), the round-16 serving knobs (``coalesce_us``,
    ``coalesce_rows``, ``warm_spec``, ``fair_rows``, ``fair_window_s``,
    ``slo_ms``), and the round-22 paged-decode model (``decode_model``)
    past their env defaults."""
    server = BridgeServer(
        host, port, engine=engine, allow_remote=allow_remote, **server_kw
    )
    if background:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
    else:
        server.serve_forever()
    return server
