"""Wire protocol: newline-delimited JSON with base64-encoded tensors.

Each message is one JSON object per line (UTF-8).  Requests carry
``{"id": n, "method": str, "params": {...}}``; responses carry
``{"id": n, "result": ...}`` or ``{"id": n, "error": {"type", "message"}}``.
Tensors are ``{"__tensor__": {"dtype", "shape", "data"(b64)}}``; binary
cells are ``{"__bytes__": b64}``.  Mirrors the role (not the format) of the
reference's Py4J value marshalling.
"""

from __future__ import annotations

import base64
import json
from typing import Any

import numpy as np


def encode_value(v: Any) -> Any:
    """python/numpy value -> JSON-safe structure."""
    if isinstance(v, np.ndarray):
        if v.dtype == object or v.dtype.kind in "SU":
            return [encode_value(c) for c in v.tolist()]
        return {
            "__tensor__": {
                "dtype": v.dtype.name,
                "shape": list(v.shape),
                "data": base64.b64encode(np.ascontiguousarray(v).tobytes()).decode(),
            }
        }
    if isinstance(v, (bytes, bytearray)):
        return {"__bytes__": base64.b64encode(bytes(v)).decode()}
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, dict):
        return {k: encode_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [encode_value(x) for x in v]
    return v


def decode_value(v: Any) -> Any:
    """JSON structure -> python/numpy value."""
    if isinstance(v, dict):
        if "__tensor__" in v:
            t = v["__tensor__"]
            raw = base64.b64decode(t["data"])
            return np.frombuffer(raw, dtype=np.dtype(t["dtype"])).reshape(
                t["shape"]
            ).copy()
        if "__bytes__" in v:
            return base64.b64decode(v["__bytes__"])
        return {k: decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [decode_value(x) for x in v]
    return v


# One message must fit in memory (whole-line JSON framing); cap it so a
# single oversized/malicious request cannot exhaust the server (ADVICE r2).
# 256 MiB ≈ a 190 MB tensor after base64 — far above any control-plane
# message, below any plausible memory budget.
MAX_MESSAGE_BYTES = 256 * 1024 * 1024


def write_message(sock_file, msg: dict) -> None:
    data = json.dumps(msg).encode() + b"\n"
    if len(data) > MAX_MESSAGE_BYTES:
        raise ValueError(
            f"bridge message of {len(data)} bytes exceeds the "
            f"{MAX_MESSAGE_BYTES}-byte cap; move bulk data out of band "
            f"(the bridge is a control plane, not a bulk transport)"
        )
    sock_file.write(data)
    sock_file.flush()


def read_message(sock_file) -> dict:
    line = sock_file.readline(MAX_MESSAGE_BYTES + 1)
    if not line:
        raise ConnectionError("bridge peer closed the connection")
    if len(line) > MAX_MESSAGE_BYTES:
        raise ConnectionError(
            f"bridge message exceeds the {MAX_MESSAGE_BYTES}-byte cap"
        )
    return json.loads(line)
