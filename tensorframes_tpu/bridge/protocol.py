"""Wire protocol: newline-delimited JSON control plane + out-of-band binary
tensor frames.

Each message is one JSON object per line (UTF-8).  Requests carry
``{"id": n, "method": str, "params": {...}}`` plus two OPTIONAL
resilience keys (round 11): ``"deadline_ms"`` (the server cancels the
verb at the next block boundary past it) and ``"idem"`` (an idempotency
token the server dedups, making retries after a dropped reply
exactly-once).  Responses carry ``{"id": n, "result": ...}`` or
``{"id": n, "error": {"type", "message"}}``; structured refusals add
``"code"`` (``deadline_exceeded`` / ``cancelled`` / ``server_busy`` /
``draining`` / ``frame_cap_exceeded`` / ``unknown_session``) and
code-specific fields (``retry_after_ms``, ``leaked_frame_ids``).  All
round-11 keys are additive and ignorable — the framing is unchanged, so
the protocol version stays 2 (the version exists to prevent *stream
corruption*, not to gate optional envelope keys).  Round 13 adds one
METHOD, not a wire change: ``metrics`` (ungated, like ``health``)
returns ``{"text": <Prometheus exposition>}`` — an old server answers
it with the standard unknown-method error, so the version stays 2 here
too.
Round 21 is additive the same way: ``hello`` replies and the ``health``
payload gain a ``"replica"`` identity object (``{"name", "pid",
"epoch", "uptime_s"}`` — the epoch token is new per server START, which
is how a fleet router tells a restarted replica from a recovered one),
and ``health``'s ``scheduler`` object gains ``"p99_ms"``.  Old clients
ignore the extra keys; old servers simply omit them (clients treat a
missing ``"replica"`` as a pre-fleet server) — the version stays 2.
Round 22 adds one METHOD, not a wire change: ``decode`` (gated, billed)
takes ``{"prompt": [ints], "max_new", "speculative", "gamma",
"stop_token"}`` and returns ``{"tokens": [ints], "generated",
"speculative"}``; page-pool exhaustion answers with the existing
``server_busy`` error shape (``retry_after_ms`` + a ``"reason"`` of
``"pages"``/``"slots"``), and ``health`` gains a ``"decode"`` object —
all additive, so the version stays 2 here too.
Small tensors ride inline as ``{"__tensor__": {"dtype", "shape",
"data"(b64)}}``; binary cells as ``{"__bytes__": b64}``.

Bulk data does NOT ride the JSON line: a tensor whose payload exceeds
``BINARY_THRESHOLD`` becomes ``{"__tensor__": {"dtype", "shape",
"bin": i}}`` referencing the i-th *binary attachment*, and the JSON line
(carrying ``"nbin"``) is followed by that many length-prefixed raw chunks
(8-byte big-endian length + bytes).  ``collect`` of a large frame thus
crosses the socket at 1.0x raw size, chunk by chunk, instead of 1.33x
base64 inside one bufferred JSON line (VERDICT r2 weak #8).  Mirrors the
role (not the format) of the reference's Py4J value marshalling.
"""

from __future__ import annotations

import base64
import json
import struct
from typing import Any, List, Optional

import numpy as np

# Tensor/bytes payloads above this go out of band as binary attachments;
# below it, inline base64 keeps one-line messages debuggable (and avoids
# per-chunk syscalls for scalar-sized control values).
BINARY_THRESHOLD = 4096


def encode_value(v: Any, bins: Optional[List[bytes]] = None) -> Any:
    """python/numpy value -> JSON-safe structure.

    With ``bins`` (a mutable list), payloads larger than
    ``BINARY_THRESHOLD`` are appended to it and referenced by index
    (``"bin": i``) instead of inlined as base64; ``write_message`` ships
    the list as length-prefixed raw chunks after the JSON line."""
    if isinstance(v, np.ndarray):
        if v.dtype == object or v.dtype.kind in "SU":
            return [encode_value(c, bins) for c in v.tolist()]
        raw = np.ascontiguousarray(v).tobytes()
        head = {"dtype": v.dtype.name, "shape": list(v.shape)}
        if bins is not None and len(raw) > BINARY_THRESHOLD:
            head["bin"] = len(bins)
            bins.append(raw)
        else:
            head["data"] = base64.b64encode(raw).decode()
        return {"__tensor__": head}
    if isinstance(v, (bytes, bytearray)):
        raw = bytes(v)
        if bins is not None and len(raw) > BINARY_THRESHOLD:
            bins.append(raw)
            return {"__bytes__": {"bin": len(bins) - 1}}
        return {"__bytes__": base64.b64encode(raw).decode()}
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, dict):
        return {k: encode_value(x, bins) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [encode_value(x, bins) for x in v]
    return v


def _bin_ref(bins: Optional[List[bytes]], i: Any) -> bytes:
    """Resolve a binary-attachment reference, surfacing corruption as a
    protocol error (not a bare IndexError) like every other malformed-
    stream case."""
    if not isinstance(i, int) or bins is None or not 0 <= i < len(bins):
        raise ConnectionError(
            f"bridge message references binary attachment {i!r} but only "
            f"{len(bins or [])} arrived — corrupt or version-skewed peer"
        )
    return bins[i]


def decode_value(v: Any, bins: Optional[List[bytes]] = None) -> Any:
    """JSON structure -> python/numpy value."""
    if isinstance(v, dict):
        if "__tensor__" in v:
            t = v["__tensor__"]
            if "bin" in t:
                raw = _bin_ref(bins, t["bin"])
            else:
                raw = base64.b64decode(t["data"])
            return np.frombuffer(raw, dtype=np.dtype(t["dtype"])).reshape(
                t["shape"]
            ).copy()
        if "__bytes__" in v:
            b = v["__bytes__"]
            if isinstance(b, dict):
                return _bin_ref(bins, b["bin"])
            return base64.b64decode(b)
        return {k: decode_value(x, bins) for k, x in v.items()}
    if isinstance(v, list):
        return [decode_value(x, bins) for x in v]
    return v


# Every message carries a protocol version: a version-skewed peer (e.g. an
# attachment-capable writer talking to a pre-attachment reader would leave
# raw frames in the stream and desync) fails with an immediate, explicit
# error instead of stream corruption (ADVICE r3).  Bump on wire changes.
PROTOCOL_VERSION = 2

# The JSON control line must fit in memory (whole-line framing); cap it so
# a single oversized/malicious request cannot exhaust the server (ADVICE
# r2).  Bulk data rides the binary attachments under their own cap — the
# cap IS the per-message/per-connection memory bound (attachments are
# buffered before dispatch), so both stay modest by default and are
# DEPLOYMENT-CONFIGURABLE (ADVICE r3): env vars
# ``TFS_BRIDGE_MAX_MESSAGE_BYTES`` / ``TFS_BRIDGE_MAX_BINARY_BYTES`` at
# import, or :func:`configure_limits` at runtime — raise them deliberately
# alongside allow_remote's trust statement if a deployment really collects
# multi-GB frames through the bridge.
from .. import envutil as _envutil


def _env_bytes(name: str, default: int) -> int:
    raw = _envutil.env_raw(name)
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"environment variable {name} must be an integer byte count, "
            f"got {raw!r}"
        ) from None


MAX_MESSAGE_BYTES = _env_bytes(
    "TFS_BRIDGE_MAX_MESSAGE_BYTES", 64 * 1024 * 1024
)
MAX_BINARY_BYTES = _env_bytes(
    "TFS_BRIDGE_MAX_BINARY_BYTES", 256 * 1024 * 1024
)
# attachment COUNT cap: per-bytes-object heap overhead (~50 B) means a
# huge nbin of tiny chunks could exhaust memory under the byte cap alone
MAX_BINARY_COUNT = 65_536


def configure_limits(
    max_message_bytes: Optional[int] = None,
    max_binary_bytes: Optional[int] = None,
) -> None:
    """Set the per-message memory caps process-wide (both peers of a
    connection must agree; the caps bound what one message can make the
    receiver buffer)."""
    global MAX_MESSAGE_BYTES, MAX_BINARY_BYTES
    if max_message_bytes is not None:
        MAX_MESSAGE_BYTES = int(max_message_bytes)
    if max_binary_bytes is not None:
        MAX_BINARY_BYTES = int(max_binary_bytes)


def write_message(sock_file, msg: dict, bins: Optional[List[bytes]] = None) -> None:
    msg = dict(msg, pv=PROTOCOL_VERSION)
    if bins:
        total = sum(len(b) for b in bins)
        if total > MAX_BINARY_BYTES:
            raise ValueError(
                f"bridge binary payload of {total} bytes exceeds the "
                f"{MAX_BINARY_BYTES}-byte cap; raise it on BOTH peers via "
                f"TFS_BRIDGE_MAX_BINARY_BYTES or configure_limits()"
            )
        msg = dict(msg, nbin=len(bins))
    data = json.dumps(msg).encode() + b"\n"
    if len(data) > MAX_MESSAGE_BYTES:
        raise ValueError(
            f"bridge message of {len(data)} bytes exceeds the "
            f"{MAX_MESSAGE_BYTES}-byte cap; move bulk data out of band "
            f"(large tensors should ride the binary attachments), or raise "
            f"the cap on BOTH peers via TFS_BRIDGE_MAX_MESSAGE_BYTES or "
            f"configure_limits()"
        )
    sock_file.write(data)
    for b in bins or ():
        sock_file.write(struct.pack(">Q", len(b)))
        sock_file.write(b)
    sock_file.flush()


def read_message(sock_file) -> "tuple[dict, List[bytes]]":
    """-> (message, binary attachments)."""
    line = sock_file.readline(MAX_MESSAGE_BYTES + 1)
    if not line:
        raise ConnectionError("bridge peer closed the connection")
    if len(line) > MAX_MESSAGE_BYTES:
        raise ConnectionError(
            f"bridge message exceeds the {MAX_MESSAGE_BYTES}-byte cap "
            f"(TFS_BRIDGE_MAX_MESSAGE_BYTES / configure_limits() raise it, "
            f"on both peers)"
        )
    msg = json.loads(line)
    pv = msg.get("pv")
    if pv != PROTOCOL_VERSION:
        raise ConnectionError(
            f"bridge protocol version skew: peer speaks "
            f"{'no declared version' if pv is None else f'version {pv}'}, "
            f"this side speaks {PROTOCOL_VERSION} — upgrade both ends "
            f"(mixed versions would corrupt the stream at the first "
            f"binary attachment)"
        )
    nbin = msg.get("nbin", 0)
    # peer-supplied: a non-int (or bool) here is stream corruption and gets
    # the same clean ConnectionError as every other malformed-stream case
    if (
        not isinstance(nbin, int)
        or isinstance(nbin, bool)
        or not 0 <= nbin <= MAX_BINARY_COUNT
    ):
        raise ConnectionError(
            f"bridge message carries invalid nbin {nbin!r} — corrupt or "
            f"version-skewed peer (cap {MAX_BINARY_COUNT})"
        )
    bins: List[bytes] = []
    remaining = MAX_BINARY_BYTES
    for _ in range(nbin):
        header = sock_file.read(8)
        if len(header) != 8:
            raise ConnectionError("bridge peer closed mid-attachment")
        (n,) = struct.unpack(">Q", header)
        if n > remaining:
            raise ConnectionError(
                f"bridge binary attachments exceed the "
                f"{MAX_BINARY_BYTES}-byte cap"
            )
        remaining -= n
        chunk = sock_file.read(n)
        if len(chunk) != n:
            raise ConnectionError("bridge peer closed mid-attachment")
        bins.append(chunk)
    return msg, bins
