"""Fleet replica entrypoint (round 21): one bridge server, one OS
process.

``python -m tensorframes_tpu.bridge.replica --host H --port P --name N``
serves a :class:`~tensorframes_tpu.bridge.server.BridgeServer` on
(H, P) until SIGTERM, which triggers the round-11 graceful drain
(reject new admissions, finish in-flight requests, cooperatively cancel
stragglers) and exits 0 — the "drain" half of a rolling restart.
SIGKILL (the ``replica_kill`` fault, or an impatient operator) skips
all of that, which is the point: the fleet's journal-backed migration
is what makes that death survivable.

Everything else — shared compile cache, journal dir, fleet registry,
fault specs — arrives via the environment the spawner
(:class:`~tensorframes_tpu.bridge.fleet.BridgeFleet`) builds, so this
module stays a thin arg-parse around :func:`serve`.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tensorframes_tpu.bridge.replica",
        description="run one bridge fleet replica (SIGTERM = drain)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--name", default="")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    log = logging.getLogger("tensorframes_tpu.bridge.replica")

    if args.name:
        # the server reads its replica name from the env; pin it here
        # too so a hand-launched replica (no fleet spawner) still gets
        # a stable identity from --name
        from ..envutil import env_set_default
        from .fleet import ENV_FLEET_REPLICA

        env_set_default(ENV_FLEET_REPLICA, args.name)

    from .server import serve

    server = serve(host=args.host, port=args.port, background=True)
    log.info(
        "replica %s pid=%d serving on %s:%d",
        args.name or "?",
        os.getpid(),
        server.address[0],
        server.address[1],
    )

    done = threading.Event()

    def _on_term(signum, frame):  # noqa: ARG001 — signal signature
        log.info("replica %s: SIGTERM — draining", args.name or "?")
        # drain off the signal handler's thread: close() blocks on
        # in-flight requests, and a handler must return promptly
        threading.Thread(
            target=lambda: (server.close(), done.set()), daemon=True
        ).start()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    done.wait()
    log.info("replica %s: drained, exiting", args.name or "?")
    return 0


if __name__ == "__main__":
    sys.exit(main())
