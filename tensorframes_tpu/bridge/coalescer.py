"""Multi-tenant serving throughput layer (round 16).

The round-11/15 bridge gave the serving path *resilience* (admission,
deadlines, sessions, drain) and *attribution* (per-request ledgers,
per-tenant metrics) — but every request still executed alone: each
concurrent small request paid its own GraphDef import, program trace,
staging, and dispatch.  This module is the throughput layer on top:

* :class:`WarmPool` — an LRU of **hot compiled programs** keyed by the
  full builder signature (graph bytes + fetches + feeds + shape hints),
  so a repeat request reuses the SAME :class:`~..program.Program` object
  and therefore its jit signature cache: zero GraphDef re-import, zero
  re-trace.  ``Executor.warmup`` primes the ``(bucket, device)``
  executable grid for a registered program (the bridge ``warm`` RPC),
  and with ``TFS_COMPILE_CACHE`` configured the priming is a disk fetch
  in a fresh process — first-request latency without the compile.

* :class:`Coalescer` — **request coalescing**: concurrent map-verb
  requests carrying the same program/schema signature wait up to
  ``TFS_BRIDGE_COALESCE_US`` for company, then dispatch as ONE
  bucket-canonical micro-batch (rows concatenated, dealt into
  ``ops/bucketing.coalesced_blocks`` blocks so the device pool spreads
  them, padded on the same geometric ladder every verb uses).  The
  batch runs through the ordinary engine dispatch — the pooled path is
  REUSED, not forked — and outputs are sliced back per request.
  Per-request results are bit-identical to solo execution: ``map_rows``
  rows are independent by construction (vmap), and ``map_blocks``
  coalescing is gated on the same row-independence gate bucketing uses
  (``analysis.rows_independent``: static classification first,
  exact-size probe on ``UNKNOWN``) — a cross-row program never
  coalesces.
  Attribution stays exact: the shared dispatch runs under a private
  batch ledger whose counters are apportioned to the participants by
  row share (largest-remainder, so the shares SUM to the batch's global
  counters delta bit-for-bit), and one flight-recorder instant carries
  every participating correlation id.

* :class:`SloScheduler` — **SLO-aware admission policy**: reads the
  round-13 latency histograms and sliding-window per-tenant row usage
  to shed *before* p99 blows instead of FIFO-shedding at a fixed depth.
  ``TFS_BRIDGE_FAIR_ROWS`` gives each tenant a row budget per
  ``TFS_BRIDGE_FAIR_WINDOW_S`` window — an over-budget tenant is shed
  (with a ``retry_after_ms`` hint) only when another tenant shared the
  window, so a lone tenant can always use the whole machine even when
  its own requests back up the gate; ``TFS_BRIDGE_SLO_MS``
  additionally sheds the dominant row consumer once the measured bridge
  p99 climbs past 80% of the target.

* :class:`ContinuousBatcher` — **continuous decode batching** (builds
  on bench config 8): decode-style requests join a RUNNING batch at
  step boundaries and retire the moment their own stream finishes, so
  a short request never waits for a long one and the step executable
  (one jit(vmap) signature) stays hot across the whole request
  population.  Per-row results are bit-identical to solo execution for
  the same reason ``map_rows`` bucketing is: rows under vmap are
  independent by construction.

Knobs (absence = feature off; the conftest pins them off for the main
suite, ``run_tests.sh``'s serving tier runs them live):

=============================  =============================================
``TFS_BRIDGE_COALESCE_US``     micro-batch gather window in µs (0 = off)
``TFS_BRIDGE_COALESCE_ROWS``   max rows per coalesced batch (default 4096)
``TFS_BRIDGE_WARM``            warm program-pool spec: ``N`` or
                               ``cap=N;buckets=64,512`` (0 = off)
``TFS_BRIDGE_FAIR_ROWS``       per-tenant rows per fairness window (0 = off)
``TFS_BRIDGE_FAIR_WINDOW_S``   fairness sliding window (default 10s)
``TFS_BRIDGE_SLO_MS``          serving p99 target; shed past 80% (0 = off)
=============================  =============================================
"""

from __future__ import annotations

import collections
import hashlib
import logging
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from .. import cancellation, observability
from ..builder import compile_program
from ..envutil import env_float as _env_float, env_int as _env_int
from ..frame import TensorFrame
from ..analysis import rowdep as analysis
from ..ops import bucketing, device_pool
from ..ops import engine as engine_mod
from ..ops import validation
from .. import envutil

logger = logging.getLogger("tensorframes_tpu.bridge.coalescer")

ENV_COALESCE_US = "TFS_BRIDGE_COALESCE_US"
ENV_COALESCE_ROWS = "TFS_BRIDGE_COALESCE_ROWS"
ENV_WARM = "TFS_BRIDGE_WARM"
ENV_FAIR_ROWS = "TFS_BRIDGE_FAIR_ROWS"
ENV_FAIR_WINDOW_S = "TFS_BRIDGE_FAIR_WINDOW_S"
ENV_SLO_MS = "TFS_BRIDGE_SLO_MS"

DEFAULT_COALESCE_ROWS = 4096
DEFAULT_FAIR_WINDOW_S = 10.0
# shed when measured p99 passes this fraction of TFS_BRIDGE_SLO_MS —
# "before p99 blows", not after the SLO is already violated
SLO_PRESSURE_FRACTION = 0.8
# how long a cached latency snapshot serves admission decisions before
# the scheduler re-reads the histograms (a snapshot per request would
# put a lock + full copy on the admission hot path)
_SLO_SNAPSHOT_TTL_S = 0.5


# the ONE exact integer-split behind shared-work ledger attribution —
# promoted to observability (round 19) so the planner's CSE registry and
# this coalescer cannot drift apart; the name stays for callers/tests
_apportion = observability.apportion


# ---------------------------------------------------------------------------
# warm program pool
# ---------------------------------------------------------------------------


class WarmSpec:
    """Parsed ``TFS_BRIDGE_WARM``: an int capacity (``"8"``) or a
    ``cap=8;buckets=64,512`` spec whose bucket list seeds the default
    priming sizes for the ``warm`` RPC."""

    def __init__(self, cap: int = 0, buckets: Tuple[int, ...] = ()):
        self.cap = max(0, int(cap))
        self.buckets = tuple(int(b) for b in buckets if int(b) > 0)

    @classmethod
    def from_env(cls, raw: Optional[str] = None) -> "WarmSpec":
        if raw is None:
            raw = envutil.env_raw(ENV_WARM)  # never None, already stripped
        raw = raw.strip()
        if not raw:
            return cls()
        try:
            if "=" not in raw:
                return cls(cap=int(raw))
            cap, buckets = 0, ()
            for part in raw.split(";"):
                part = part.strip()
                if not part:
                    continue
                k, _, v = part.partition("=")
                if k.strip() == "cap":
                    cap = int(v)
                elif k.strip() == "buckets":
                    buckets = tuple(
                        int(x) for x in v.split(",") if x.strip()
                    )
                else:
                    raise ValueError(f"unknown key {k!r}")
            return cls(cap=cap, buckets=buckets)
        except (ValueError, TypeError):
            logger.warning(
                "%s=%r is malformed (use an int cap or "
                "'cap=N;buckets=64,512'); warm pool disabled",
                ENV_WARM,
                raw,
            )
            return cls()


def program_signature(
    verb: str,
    graph: Any,
    fetches: Optional[Sequence[str]],
    inputs: Optional[Mapping[str, str]],
    shapes: Optional[Mapping[str, Sequence[int]]],
    trim: bool,
) -> Tuple:
    """The coalescing/warm-pool identity of a bridge map-verb request:
    two requests with the same signature run the same compiled program.
    GraphDef bytes hash (never the bytes themselves — signatures are
    dict keys held for the pool's lifetime)."""
    if isinstance(graph, (bytes, bytearray)):
        gk = hashlib.sha1(bytes(graph)).hexdigest()
    else:
        gk = ("obj", id(graph))
    return (
        verb,
        bool(trim),
        gk,
        tuple(fetches or ()),
        tuple(sorted((inputs or {}).items())),
        tuple(
            sorted((k, tuple(v)) for k, v in (shapes or {}).items())
        ),
    )


class _WarmEntry:
    __slots__ = ("program", "requests", "coalesce_ok")

    def __init__(self, program):
        self.program = program
        self.requests = 0  # map-verb requests served by this program
        # map_blocks coalescability memo: None = unproven, else bool
        self.coalesce_ok: Optional[bool] = None


class WarmPool:
    """LRU of hot compiled programs, keyed by the full builder
    signature.  ``cap=0`` disables retention (every lookup rebuilds —
    the pre-round-16 behavior); lookups are still served so the
    coalescer has one program-construction path either way."""

    def __init__(self, spec: Optional[WarmSpec] = None):
        self.spec = spec if spec is not None else WarmSpec.from_env()
        self._lock = threading.Lock()
        self._lru: "collections.OrderedDict[Tuple, _WarmEntry]" = (
            collections.OrderedDict()
        )

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def entry(
        self,
        verb: str,
        graph: Any,
        fetches=None,
        inputs=None,
        shapes=None,
        trim: bool = False,
    ) -> Tuple[Tuple, _WarmEntry, bool]:
        """-> ``(signature, entry, hit)``; builds (and, with capacity,
        retains) the compiled program on a miss."""
        key = program_signature(verb, graph, fetches, inputs, shapes, trim)
        with self._lock:
            ent = self._lru.get(key)
            if ent is not None:
                self._lru.move_to_end(key)
                ent.requests += 1
                observability.note_warm_program(True)
                return key, ent, True
        # build OUTSIDE the lock: GraphDef import is the expensive part
        program = compile_program(
            graph, fetches=fetches, inputs=inputs, shapes=shapes,
            what=f"bridge:{verb}",
        )
        ent = _WarmEntry(program)
        ent.requests = 1
        observability.note_warm_program(False)
        if self.spec.cap > 0:
            with self._lock:
                # a racing builder may have inserted the same key: keep
                # the resident one (its jit cache may already be warm)
                existing = self._lru.get(key)
                if existing is not None:
                    self._lru.move_to_end(key)
                    existing.requests += 1
                    return key, existing, True
                self._lru[key] = ent
                while len(self._lru) > self.spec.cap:
                    self._lru.popitem(last=False)
        return key, ent, False

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "resident": len(self._lru),
                "cap": self.spec.cap,
                "requests": {
                    k[2][:8] if isinstance(k[2], str) else str(k[2]):
                    e.requests
                    for k, e in self._lru.items()
                },
            }


# ---------------------------------------------------------------------------
# request coalescing
# ---------------------------------------------------------------------------


class _Member:
    """One request parked in a coalescing batch."""

    __slots__ = (
        "sess",
        "frame",
        "rows",
        "scope",
        "ledger",
        "cid",
        "result",
        "error",
        "abandoned",
        "reg_lock",
    )

    def __init__(self, sess, frame, scope):
        self.sess = sess
        self.frame = frame
        self.rows = frame.num_rows
        self.scope = scope
        self.ledger = observability.current_request()
        self.cid = self.ledger.correlation_id if self.ledger else None
        self.result = None
        self.error: Optional[BaseException] = None
        # abandonment handshake: the member's handler thread may give up
        # (deadline) while the leader is still executing the batch; the
        # leader must not register an output frame into the member's
        # session that the client will never learn about (it would leak
        # against the session's frame cap).  reg_lock makes the
        # register-vs-abandon decision atomic.
        self.abandoned = False
        self.reg_lock = threading.Lock()

    def abandon(self) -> None:
        """Mark this member abandoned and release its output frame if
        the leader already registered one."""
        with self.reg_lock:
            self.abandoned = True
            res = self.result
        if res is not None:
            self.sess.release(res["frame_id"])


class _Batch:
    __slots__ = ("key", "members", "rows", "sealed", "full", "done")

    def __init__(self, key):
        self.key = key
        self.members: List[_Member] = []
        self.rows = 0
        self.sealed = False
        self.full = threading.Event()  # rows cap reached: leader wakes
        self.done = threading.Event()  # results distributed


class Coalescer:
    """Coalesces concurrent same-program map-verb requests into one
    bucket-canonical dispatch.  See the module docstring for the policy;
    the server routes every gated ``map_blocks``/``map_rows`` through
    :meth:`run_map_verb`."""

    def __init__(
        self,
        engine=None,
        wait_us: Optional[float] = None,
        max_rows: Optional[int] = None,
        warm: Optional[WarmPool] = None,
        register_scope: Optional[Callable] = None,
        unregister_scope: Optional[Callable] = None,
    ):
        self.engine = engine
        self.wait_us = (
            _env_float(ENV_COALESCE_US, 0.0)
            if wait_us is None
            else float(wait_us)
        )
        self.max_rows = (
            _env_int(ENV_COALESCE_ROWS, DEFAULT_COALESCE_ROWS, floor=1)
            if max_rows is None
            else max(1, int(max_rows))
        )
        self.warm = warm if warm is not None else WarmPool()
        self._register_scope = register_scope or (lambda s: None)
        self._unregister_scope = unregister_scope or (lambda s: None)
        self._lock = threading.Lock()
        self._open: Dict[Tuple, _Batch] = {}
        # batch-size histogram (requests per dispatched batch): tiny,
        # bounded by max observed batch size; served by health + gauges
        self._batch_hist: Dict[int, int] = {}
        self._rows_batched = 0

    # -- public surface ------------------------------------------------------

    def enabled(self) -> bool:
        return self.wait_us > 0

    def snapshot(self) -> Dict[str, Any]:
        """Coalescer state for the health RPC: open queue depth per
        program, the batch-size histogram, and warm-pool residency."""
        with self._lock:
            queued = {
                (k[2][:8] if isinstance(k[2], str) else str(k[2])):
                len(b.members)
                for k, b in self._open.items()
            }
            hist = dict(self._batch_hist)
            rows = self._rows_batched
        return {
            "enabled": self.enabled(),
            "wait_us": self.wait_us,
            "max_rows": self.max_rows,
            "queued": sum(queued.values()),
            "queue_by_program": queued,
            "batch_size_hist": {str(k): v for k, v in sorted(hist.items())},
            "rows_batched": rows,
            "warm_pool": self.warm.snapshot(),
        }

    def gauges(self) -> Dict[str, Any]:
        """The grouped gauge provider body (one consistent snapshot per
        scrape; names are distinct from every counter family, per the
        round-13 no-duplicate-family rule)."""
        with self._lock:
            queued = sum(len(b.members) for b in self._open.values())
            open_programs = len(self._open)
        return {
            "tfs_bridge_coalesce_queued": queued,
            "tfs_bridge_coalesce_open_programs": open_programs,
            "tfs_bridge_warm_resident": len(self.warm),
        }

    def run_map_verb(
        self,
        sess,
        verb: str,
        frame_id: int,
        graph: Any = None,
        fetches: Optional[Sequence[str]] = None,
        inputs: Optional[Mapping[str, str]] = None,
        shapes: Optional[Mapping[str, Sequence[int]]] = None,
        trim: bool = False,
        scope: Optional[cancellation.CancelScope] = None,
    ) -> Dict[str, Any]:
        """The server's gated map-verb entry: coalesce when profitable,
        else execute solo (always through the warm program pool)."""
        frame = sess.frame(frame_id)
        key, ent, hit = self.warm.entry(
            verb, graph, fetches, inputs, shapes, trim
        )
        program = ent.program
        if not (
            self.enabled()
            and frame.num_rows > 0
            and self._coalescable(verb, trim, frame, program, ent)
        ):
            out = self._execute(program, verb, trim, frame)
            fid = sess.register(out)
            return {"frame_id": fid, "schema": sess._schema(out)}
        member = _Member(sess, frame, scope)
        batch, leader = self._join(key + self._schema_sig(frame), member)
        if leader:
            self._gather_then_run(batch, verb, trim, program, ent)
        else:
            self._await_result(batch, member)
        if member.error is not None:
            raise member.error
        if member.result is None:  # pragma: no cover - defensive
            raise RuntimeError("coalesced batch produced no result")
        return member.result

    # -- eligibility ---------------------------------------------------------

    @staticmethod
    def _schema_sig(frame: TensorFrame) -> Tuple:
        return tuple(
            (c.name, c.scalar_type.name, tuple(c.cell_shape))
            for c in frame.schema
        )

    def _coalescable(self, verb, trim, frame, program, ent) -> bool:
        """Whether this request may merge with others: every column must
        be a plain uniform device-ok array (concat + split is a pure
        row-slice), and a trimmed map never coalesces (its output row
        count is program-defined, so row shares are undefined).
        ``map_blocks`` is additionally gated on the row-independence
        proof, memoized per program (``_prove_coalesce``)."""
        if trim:
            return False
        if ent.coalesce_ok is False:
            return False
        for c in frame.schema:
            col = frame.column(c.name)
            if col.is_ragged or col.is_device:
                return False
            if not c.scalar_type.device_ok:
                return False
            if not isinstance(col.data, np.ndarray):
                return False
        return True

    def _prove_coalesce(
        self, verb, program, ent, members, block_sizes
    ) -> bool:
        """``map_rows`` rows are independent by construction;
        ``map_blocks`` must pass the jaxpr row-independence proof at
        every size it runs solo AND coalesced (the exact condition
        bucketing's pad-and-slice uses).  The verdict is memoized on the
        warm entry — a structurally cross-row program is rejected once,
        then skips the coalesce path entirely."""
        if verb == "map_rows":
            return True
        if ent.coalesce_ok is not None:
            return ent.coalesce_ok
        try:
            import jax

            frame0 = members[0].frame
            infos = validation.check_map_inputs(
                program, frame0, verb, host_staged=()
            )
            sizes = set(block_sizes)
            for m in members:
                sizes.update(m.frame.block_sizes)
            if bucketing.enabled():
                sizes.update(
                    bucketing.bucket_for(s) for s in list(sizes)
                )
            specs = analysis.input_specs_for(program, infos)
            ok = specs is not None and analysis.rows_independent(
                program, specs, sorted(s for s in sizes if s > 0)
            )
        except analysis.AnalysisXCheckError:
            raise  # the differential fence must fail loudly
        except Exception:  # noqa: BLE001 — unprovable = not coalescable
            ok = False
        ent.coalesce_ok = ok
        if not ok:
            logger.info(
                "coalescer: map_blocks program failed the row-"
                "independence proof; its requests will run solo"
            )
        return ok

    # -- batching ------------------------------------------------------------

    def _join(self, key, member) -> Tuple[_Batch, bool]:
        with self._lock:
            batch = self._open.get(key)
            if (
                batch is None
                or batch.sealed
                or batch.rows + member.rows > self.max_rows
            ):
                if batch is not None and not batch.sealed:
                    # displaced from _open: no later request can join it,
                    # so wake its leader instead of letting the batch
                    # sleep out the rest of the gather window
                    batch.full.set()
                batch = _Batch(key)
                self._open[key] = batch
            leader = not batch.members
            batch.members.append(member)
            batch.rows += member.rows
            if batch.rows >= self.max_rows:
                batch.full.set()
        return batch, leader

    def _seal(self, batch) -> List[_Member]:
        with self._lock:
            batch.sealed = True
            if self._open.get(batch.key) is batch:
                del self._open[batch.key]
            return list(batch.members)

    def _gather_then_run(self, batch, verb, trim, program, ent) -> None:
        # the leader parks for the gather window (bounded by its own
        # remaining deadline), then seals and executes for everyone
        wait_s = self.wait_us / 1e6
        lead = batch.members[0]
        if lead.scope is not None:
            remaining = lead.scope.time_remaining()
            if remaining is not None:
                wait_s = max(0.0, min(wait_s, remaining))
        batch.full.wait(timeout=wait_s)
        members = self._seal(batch)
        try:
            self._run_batch(batch, verb, trim, program, ent, members)
        finally:
            batch.done.set()

    def _await_result(self, batch, member) -> None:
        remaining = (
            member.scope.time_remaining()
            if member.scope is not None
            else None
        )
        if not batch.done.wait(timeout=remaining):
            # the member's own deadline expired while its batch was
            # still gathering/executing: cancel THIS request only — the
            # batch (and every other member) is unaffected
            member.abandon()
            raise cancellation.DeadlineExceeded(
                "request deadline expired while waiting for its "
                "coalesced batch"
            )
        if member.scope is not None:
            try:
                member.scope.check()
            except BaseException:
                member.abandon()
                raise

    def _run_batch(
        self, batch, verb, trim, program, ent, members: List[_Member]
    ) -> None:
        # drop members whose deadline already expired — they are
        # cancelled individually, the rest still batch
        alive: List[_Member] = []
        for m in members:
            if m.scope is not None and m.scope.expired():
                m.error = cancellation.DeadlineExceeded(
                    "request deadline expired before its coalesced "
                    "batch dispatched"
                )
            else:
                alive.append(m)
        if not alive:
            return
        if len(alive) == 1:
            # nobody arrived within the gather window: solo semantics
            # (the member's OWN block structure — re-blocking a lone
            # map_blocks request could change a cross-row program's
            # results), counted as the coalesce_miss evidence
            observability.note_coalesce_solo()
            with self._lock:
                self._batch_hist[1] = self._batch_hist.get(1, 0) + 1
            self._run_solo_for(alive[0], verb, trim, program)
            return
        total = sum(m.rows for m in alive)
        n_lanes = (
            len(device_pool.pool_devices()) if device_pool.enabled() else 1
        )
        nb = bucketing.coalesced_blocks(total, n_lanes)
        block_sizes = [
            total // nb + (1 if i < total % nb else 0) for i in range(nb)
        ]
        if not self._prove_coalesce(
            verb, program, ent, alive, block_sizes
        ):
            # structurally cross-row map_blocks: solo semantics for each
            # member, executed sequentially on the leader thread with
            # exact per-member attribution
            for m in alive:
                self._run_solo_for(m, verb, trim, program)
            return
        try:
            self._dispatch_coalesced(
                verb, trim, program, alive, total, nb
            )
        except BaseException as e:  # noqa: BLE001 — every member gets it
            for m in alive:
                if m.error is None and m.result is None:
                    m.error = e

    # -- execution -----------------------------------------------------------

    def _executor(self):
        return engine_mod._resolve(self.engine)

    def _execute(self, program, verb, trim, frame) -> TensorFrame:
        """One solo dispatch through the ordinary engine path (shared by
        the ineligible/solo branch and the proof-failed fallback).

        Round 19: with ``TFS_PLAN`` live on the server, the dispatch
        routes through the planner instead — concurrent requests on the
        SAME registered frame with the same warm-pool Program then
        rendezvous in the cross-plan CSE registry and execute the
        subplan exactly once, each absorbing its exact ledger share
        (``plan_cse_hits``); coalescing still owns the different-rows
        case, CSE owns the identical-subplan case."""
        if self.engine is None:
            from ..ops import planner

            if planner.planning_enabled() and isinstance(
                frame, TensorFrame
            ):
                node = planner.root_for(frame)._append(
                    "map_rows" if verb == "map_rows" else "map_blocks",
                    program,
                    trim=trim,
                )
                return node._materialize(count_use=False)
        ex = self._executor()
        if verb == "map_rows":
            return ex.map_rows(program, frame)
        return ex.map_blocks(program, frame, trim=trim)

    def _run_solo_for(self, m: _Member, verb, trim, program) -> None:
        """Execute one member with solo semantics on the leader thread,
        attributing the delta to the member's OWN ledger (the leader's
        thread context carries the leader's ledger, not the member's)."""
        try:
            shares, blocks, rows, out = self._metered(
                lambda: self._execute(program, verb, trim, m.frame)
            )
            if m.ledger is not None:
                m.ledger.absorb(shares, blocks, rows)
            with m.reg_lock:
                if not m.abandoned:
                    fid = m.sess.register(out)
                    m.result = {
                        "frame_id": fid,
                        "schema": m.sess._schema(out),
                    }
        except BaseException as e:  # noqa: BLE001
            m.error = e

    def _metered(self, fn):
        """Run ``fn`` under a private root ledger (the leader's own
        request context suspended), returning the exact counters /
        blocks-per-device / rows delta plus the result."""
        tok0 = observability.activate_request(None)
        led = observability.RequestLedger(method="bridge:coalesce")
        tok1 = observability.activate_request(led)
        try:
            out = fn()
        finally:
            observability.deactivate_request(tok1)
            observability.deactivate_request(tok0)
        return dict(led.counters), dict(led.blocks_per_device), led.rows, out

    def _dispatch_coalesced(
        self, verb, trim, program, alive: List[_Member], total: int, nb: int
    ) -> None:
        names = [c.name for c in alive[0].frame.schema]
        combined = {
            n: np.concatenate(
                [np.asarray(m.frame.column(n).data) for m in alive]
            )
            if len(alive) > 1
            else np.asarray(alive[0].frame.column(n).data)
            for n in names
        }
        cframe = TensorFrame.from_arrays(combined, num_blocks=nb)
        # the batch scope: the most patient member's deadline (None when
        # any member has none).  Registered with the server so graceful
        # drain cancels in-flight batches cooperatively.
        deadline_s: Optional[float] = 0.0
        for m in alive:
            r = (
                m.scope.time_remaining() if m.scope is not None else None
            )
            if r is None:
                deadline_s = None
                break
            deadline_s = max(deadline_s, r)
        scope = cancellation.CancelScope(
            deadline_s=deadline_s, label="bridge:coalesce"
        )
        self._register_scope(scope)
        t_tr = observability.trace_now()
        try:
            with cancellation.activate(scope):
                counters, blocks, rows, out = self._metered(
                    lambda: self._execute(program, verb, trim, cframe)
                )
        finally:
            self._unregister_scope(scope)
        # one trace record for the shared dispatch, carrying every
        # participating correlation id
        cids = [m.cid for m in alive if m.cid]
        observability.trace_complete(
            f"coalesced {verb}",
            "bridge/coalescer",
            t_tr,
            cids=",".join(cids),
            requests=len(alive),
            rows=total,
            blocks=nb,
        )
        observability.note_coalesced_batch(len(alive), total)
        with self._lock:
            k = len(alive)
            self._batch_hist[k] = self._batch_hist.get(k, 0) + 1
            if k > 1:
                self._rows_batched += total
        # split outputs per member and bill each its exact row share
        self._distribute(alive, out, counters, blocks, rows, total)

    def _distribute(
        self, alive, out: TensorFrame, counters, blocks, rows, total
    ) -> None:
        out_cols = {
            c.info.name: np.asarray(c.data) for c in out.columns
        }
        weights = [m.rows for m in alive]
        shares_by_key = {
            k: _apportion(v, weights) for k, v in counters.items() if v
        }
        block_shares = {
            d: _apportion(v, weights) for d, v in blocks.items() if v
        }
        row_shares = _apportion(rows, weights)
        offset = 0
        n_members = len(alive)
        for i, m in enumerate(alive):
            try:
                sub = {
                    n: a[offset : offset + m.rows]
                    for n, a in out_cols.items()
                }
                rf = TensorFrame.from_arrays(
                    sub, num_blocks=min(m.frame.num_blocks, m.rows)
                )
                if m.ledger is not None:
                    m.ledger.absorb(
                        {k: s[i] for k, s in shares_by_key.items()},
                        {d: s[i] for d, s in block_shares.items()},
                        row_shares[i],
                    )
                with m.reg_lock:
                    if not m.abandoned:
                        fid = m.sess.register(rf)
                        m.result = {
                            "frame_id": fid,
                            "schema": m.sess._schema(rf),
                            "coalesced": {
                                "requests": n_members,
                                "rows": total,
                                "row_share": m.rows,
                            },
                        }
            except BaseException as e:  # noqa: BLE001 — per-member
                m.error = e
            offset += m.rows


# ---------------------------------------------------------------------------
# SLO-aware admission policy
# ---------------------------------------------------------------------------


class SloScheduler:
    """Per-tenant fair-share row budgets + latency-aware proactive
    shedding, consulted BEFORE the admission gate.

    Returns a shed *decision* (dict) rather than raising — the server
    owns the ``ServerBusy`` wire error, and this module must not import
    the server (the server imports it)."""

    def __init__(
        self,
        fair_rows: Optional[int] = None,
        window_s: Optional[float] = None,
        slo_ms: Optional[float] = None,
    ):
        self.fair_rows = (
            _env_int(ENV_FAIR_ROWS, 0)
            if fair_rows is None
            else max(0, int(fair_rows))
        )
        self.window_s = (
            _env_float(ENV_FAIR_WINDOW_S, DEFAULT_FAIR_WINDOW_S, floor=0.1)
            if window_s is None
            else max(0.1, float(window_s))
        )
        self.slo_ms = (
            _env_float(ENV_SLO_MS, 0.0)
            if slo_ms is None
            else max(0.0, float(slo_ms))
        )
        self._lock = threading.Lock()
        self._usage: Dict[str, "collections.deque"] = {}
        # tenant -> last check() arrival: makes a tenant whose first
        # request is still queued (nothing billed yet) visible to the
        # fairness trigger
        self._arrivals: Dict[str, float] = {}
        self._snapshot: Tuple[float, Optional[float]] = (0.0, None)

    def enabled(self) -> bool:
        return self.fair_rows > 0 or self.slo_ms > 0

    # -- recording -----------------------------------------------------------

    def note(self, tenant: Optional[str], rows: int) -> None:
        """Record ``rows`` served for ``tenant`` (called after a gated
        verb executes)."""
        if not self.enabled() or rows <= 0:
            return
        t = tenant or "default"
        now = time.monotonic()
        with self._lock:
            dq = self._usage.setdefault(t, collections.deque())
            dq.append((now, int(rows)))
            self._prune_locked(now)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.window_s
        for t in list(self._usage):
            dq = self._usage[t]
            while dq and dq[0][0] < horizon:
                dq.popleft()
            if not dq:
                del self._usage[t]

    def _rows_by_tenant(self) -> Dict[str, int]:
        now = time.monotonic()
        with self._lock:
            self._prune_locked(now)
            return {
                t: sum(r for _, r in dq) for t, dq in self._usage.items()
            }

    def _bridge_p99_s(self) -> Optional[float]:
        """Worst gated-method p99 from the always-on bridge histograms,
        re-read at most every ``_SLO_SNAPSHOT_TTL_S``."""
        now = time.monotonic()
        with self._lock:
            t, v = self._snapshot
            if now - t < _SLO_SNAPSHOT_TTL_S:
                return v
        worst: Optional[float] = None
        for key, s in observability.latency_snapshot().items():
            if not key.startswith("bridge:"):
                continue
            if s.get("count", 0) < 8:
                continue
            p99 = s.get("p99_s")
            if p99 and (worst is None or p99 > worst):
                worst = p99
        with self._lock:
            self._snapshot = (now, worst)
        return worst

    # -- policy --------------------------------------------------------------

    def check(
        self,
        tenant: Optional[str],
        rows_hint: int = 0,
        contention: bool = False,
    ) -> Optional[Dict[str, Any]]:
        """Shed decision for one arriving gated request, or None to
        admit.  Fairness only bites when ANOTHER tenant shared the
        window (billed rows, or a request that arrived but has not
        executed yet) — a lone over-budget tenant is just using the
        machine, even when its own requests back up the admission gate.
        ``contention`` is the gate's view (queue non-empty or inflight
        at the bound); it never sheds by itself, it only hardens the
        retry hint."""
        if not self.enabled():
            return None
        t = tenant or "default"
        now = time.monotonic()
        with self._lock:
            self._arrivals[t] = now
            horizon = now - self.window_s
            for k in [
                k for k, ts in self._arrivals.items() if ts < horizon
            ]:
                del self._arrivals[k]
            others_arrived = any(k != t for k in self._arrivals)
        usage = self._rows_by_tenant()
        mine = usage.get(t, 0)
        others = [v for k, v in usage.items() if k != t]
        over_budget = self.fair_rows > 0 and mine > self.fair_rows
        if over_budget and (bool(others) or others_arrived):
            observability.note_fair_share_shed()
            return {
                "reason": "fair_share",
                "tenant": t,
                "rows_used": mine,
                "fair_rows": self.fair_rows,
                "window_s": self.window_s,
                # back off proportionally to the overshoot (harder when
                # the gate is also backed up): the hint drains the
                # window instead of hammering it
                "retry_after_ms": int(
                    min(
                        1000.0 * self.window_s,
                        50.0
                        * max(1.0, mine / self.fair_rows)
                        * (2.0 if contention else 1.0),
                    )
                ),
            }
        if self.slo_ms > 0:
            p99 = self._bridge_p99_s()
            if (
                p99 is not None
                and p99 * 1000.0 >= SLO_PRESSURE_FRACTION * self.slo_ms
                and others
                and mine >= max(others)
            ):
                # tail pressure: the dominant row consumer yields first,
                # BEFORE the p99 breaches the target
                observability.note_slo_shed()
                return {
                    "reason": "slo_pressure",
                    "tenant": t,
                    "p99_ms": round(p99 * 1000.0, 3),
                    "slo_ms": self.slo_ms,
                    "rows_used": mine,
                    "retry_after_ms": int(max(25.0, self.slo_ms)),
                }
        return None

    def snapshot(self) -> Dict[str, Any]:
        p99_s = self._bridge_p99_s()
        return {
            "enabled": self.enabled(),
            "fair_rows": self.fair_rows,
            "window_s": self.window_s,
            "slo_ms": self.slo_ms,
            "rows_by_tenant": self._rows_by_tenant(),
            # round 21: the worst gated-method p99 (None until 8+
            # samples) — surfaced through ``health`` so the fleet
            # router's latency-SLO signal needs no metrics scrape
            "p99_ms": (
                round(p99_s * 1000.0, 3) if p99_s is not None else None
            ),
        }


# ---------------------------------------------------------------------------
# continuous decode batching
# ---------------------------------------------------------------------------


class ContinuousBatcher:
    """Continuous batching for autoregressive decode (bench config 8's
    serving form): requests JOIN the running batch at step boundaries
    and RETIRE the moment their own stream finishes — a short request
    never waits out a long neighbor, and the step executable (one
    ``jit(vmap(row_step))`` signature at ``max_batch``) stays hot for
    the whole request population.

    ``row_step(state, token) -> (state, token)`` is the per-row decode
    step over a pytree ``state`` (e.g. a KV cache slice + position) and
    a scalar token; the batcher vmaps it over the slot axis, so per-row
    results are independent by construction — the same guarantee that
    makes ``map_rows`` bucket padding bit-identical.  Free slots step
    garbage that no one reads.

    ``submit`` blocks until the request's stream completes and returns
    the emitted tokens; it is thread-safe (one server handler thread
    per request parks here while the driver thread steps the batch).
    """

    def __init__(self, row_step, max_batch: int = 8):
        import jax

        self.max_batch = max(1, int(max_batch))
        self._step = jax.jit(jax.vmap(row_step))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: "collections.deque" = collections.deque()
        self._active: Dict[int, "_DecodeSlot"] = {}
        self._free = list(range(self.max_batch))
        self._states = None  # stacked pytree, built from the first row
        self._tokens = None  # np [max_batch]
        self._driver: Optional[threading.Thread] = None
        self._closed = False
        self.steps = 0  # batch steps executed (telemetry/tests)
        self.joined_mid_run = 0  # requests admitted while others ran

    # -- public --------------------------------------------------------------

    def submit(
        self,
        state,
        first_token,
        max_new: int,
        until: Optional[Callable[[Any], bool]] = None,
        timeout_s: Optional[float] = None,
    ) -> List[Any]:
        """Decode up to ``max_new`` tokens from ``(state, first_token)``,
        stopping early when ``until(token)`` is true.  Returns the
        emitted tokens (the stop token included)."""
        slot_req = _DecodeSlot(state, first_token, max_new, until)
        with self._cv:
            if self._closed:
                raise RuntimeError("ContinuousBatcher is closed")
            self._pending.append(slot_req)
            self._ensure_driver()
            self._cv.notify_all()
        if not slot_req.done.wait(timeout=timeout_s):
            with self._cv:
                slot_req.abandoned = True
            raise TimeoutError(
                f"decode request did not finish within {timeout_s}s"
            )
        if slot_req.error is not None:
            raise slot_req.error
        return slot_req.out

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._driver is not None:
            self._driver.join(timeout=5.0)

    # -- driver --------------------------------------------------------------

    def _ensure_driver(self) -> None:
        if self._driver is None or not self._driver.is_alive():
            self._driver = threading.Thread(
                target=self._drive, name="tfs-decode-batcher", daemon=True
            )
            self._driver.start()

    def _drive(self) -> None:
        import jax
        import jax.numpy as jnp

        try:
            while True:
                with self._cv:
                    while (
                        not self._closed
                        and not self._pending
                        and not self._active
                    ):
                        self._cv.wait()
                    if self._closed and not self._active:
                        # clean shutdown: requests still queued (never
                        # admitted to a slot) must not block their
                        # submit() callers forever
                        err = RuntimeError(
                            "ContinuousBatcher closed before this "
                            "request was admitted"
                        )
                        for req in self._pending:
                            req.error = err
                            req.done.set()
                        self._pending.clear()
                        return
                    was_running = bool(self._active)
                    # step boundary: admit pending requests into free slots
                    while self._pending and self._free:
                        req = self._pending.popleft()
                        if req.abandoned:
                            continue
                        slot = self._free.pop()
                        self._admit(slot, req, jnp)
                        if was_running:
                            self.joined_mid_run += 1
                    active = dict(self._active)
                if not active:
                    continue
                states, toks = self._step(self._states, self._tokens)
                self._states, self._tokens = states, toks
                self.steps += 1
                emitted = np.asarray(toks)
                with self._cv:
                    for slot, req in list(self._active.items()):
                        tok = emitted[slot]
                        req.out.append(tok)
                        req.emitted += 1
                        stop = req.emitted >= req.max_new or (
                            req.until is not None and bool(req.until(tok))
                        )
                        if stop or req.abandoned:
                            del self._active[slot]
                            self._free.append(slot)
                            req.done.set()
        except BaseException as e:  # noqa: BLE001 — fail every waiter
            with self._cv:
                for req in list(self._active.values()):
                    req.error = e
                    req.done.set()
                for req in self._pending:
                    req.error = e
                    req.done.set()
                self._active.clear()
                self._pending.clear()
                self._free = list(range(self.max_batch))

    def _admit(self, slot: int, req: "_DecodeSlot", jnp) -> None:
        import jax

        if self._states is None:
            # stack template from the first row: zeros at [max_batch,...]
            self._states = jax.tree_util.tree_map(
                lambda a: jnp.zeros(
                    (self.max_batch,) + tuple(np.shape(a)),
                    jnp.asarray(a).dtype,
                ),
                req.state,
            )
            t0 = jnp.asarray(req.first_token)
            self._tokens = jnp.zeros((self.max_batch,), t0.dtype)
        self._states = jax.tree_util.tree_map(
            lambda stack, row: stack.at[slot].set(row),
            self._states,
            req.state,
        )
        self._tokens = self._tokens.at[slot].set(req.first_token)
        self._active[slot] = req


class _DecodeSlot:
    __slots__ = (
        "state",
        "first_token",
        "max_new",
        "until",
        "out",
        "emitted",
        "done",
        "error",
        "abandoned",
    )

    def __init__(self, state, first_token, max_new, until):
        self.state = state
        self.first_token = first_token
        self.max_new = max(1, int(max_new))
        self.until = until
        self.out: List[Any] = []
        self.emitted = 0
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.abandoned = False


# ---------------------------------------------------------------------------
# paged continuous decode (round 22)
# ---------------------------------------------------------------------------

ENV_DECODE_MAX_SLOTS = "TFS_DECODE_MAX_SLOTS"
DEFAULT_DECODE_MAX_SLOTS = 8
# bounded retry against injected/real transient dispatch failures at a
# step boundary — the functional (kp, vp, tables) state makes a retry
# recompute the identical step
_DECODE_STEP_ATTEMPTS = 3

# live schedulers, weakly held: tfs.doctor() reads the first open one's
# snapshot without the caller having to thread it through
_LIVE_DECODE: "weakref.WeakSet[DecodeScheduler]" = weakref.WeakSet()


def decode_doctor_snapshot() -> Optional[Dict[str, Any]]:
    """Snapshot of the live :class:`DecodeScheduler`, if one exists —
    the evidence feed for doctor's ``kv_fragmentation`` /
    ``decode_slot_starvation`` rules (injectable there as
    ``decode=``)."""
    for sched in list(_LIVE_DECODE):
        if not sched._closed:
            return sched.snapshot()
    return None


class DecodeRefused(RuntimeError):
    """Typed decode admission refusal: the page pool (``reason:
    'pages'``) or the slot/backlog bound (``reason: 'slots'``) cannot
    take the sequence now.  Carries ``retry_after_ms`` — the serving
    layer maps this to ``server_busy`` so clients back off instead of
    the scheduler OOMing mid-step."""

    def __init__(self, reason: str, retry_after_ms: int, detail: str = ""):
        self.reason = reason
        self.retry_after_ms = int(retry_after_ms)
        super().__init__(
            f"decode admission refused ({reason}): "
            f"{detail or 'resources exhausted'}; "
            f"retry after {self.retry_after_ms}ms"
        )


class _PagedSeq:
    """One admitted sequence: its prompt, page reservation, and stream
    bookkeeping.  ``charge`` is the pool's pinned-budget handle — the
    slot holds it (the budget LRU only holds a weakref) until the pages
    are freed at retirement."""

    __slots__ = (
        "prompt", "max_new", "until", "tenant", "scope", "charge",
        "table_row", "out", "emitted", "done", "error", "abandoned",
    )

    def __init__(self, prompt, max_new, until, tenant, scope, charge):
        self.prompt = prompt  # np.int32 [Lp]
        self.max_new = max(1, int(max_new))
        self.until = until
        self.tenant = tenant
        self.scope = scope  # cancellation.CancelScope | None
        self.charge = charge  # kv_pager._SeqPages
        self.table_row = None  # np.int32 [max_pages], set at admission
        self.out: List[int] = []
        self.emitted = 0
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.abandoned = False


class DecodeScheduler:
    """Continuous decode over the PAGED KV cache (round 22): the
    serving form of ``models/kv_pager.py``.

    The ContinuousBatcher above batches opaque per-row step functions;
    this scheduler owns the transformer serving path end to end — each
    of its ``TFS_DECODE_MAX_SLOTS`` slots holds a page table into the
    shared :class:`~..models.kv_pager.PagePool`, and the driver thread
    alternates two fixed-shape compiled dispatches:

    * **prefill lane** (disaggregated): sequences admitted at a step
      boundary prefill together as one bucket-padded batch
      (``ops/bucketing`` ladder — the same geometric ladder every verb
      uses, so the executable grid stays bounded), writing their
      prompts' KV straight into their reserved pages;
    * **decode lane**: one ``[max_slots]``-shaped greedy step for the
      whole population; slots join at step boundaries and retire the
      moment their stream finishes (``max_new`` reached, ``until`` hit,
      deadline expired, or caller abandoned), returning their pages to
      the pool immediately — early retirement is what lets short
      requests subsidise long ones under a fixed page budget.

    Admission is synchronous and typed: ``submit`` reserves the FULL
    page span (``ceil((Lp + max_new) / P)``) up front, so a sequence
    that starts decoding can always finish — pool exhaustion surfaces
    as :class:`DecodeRefused` with ``retry_after_ms`` at admission,
    never as an OOM three steps into a stream.  Deadlines and cancels
    (the request's :mod:`cancellation` scope, captured at submit) are
    honoured at step boundaries, where retirement frees pages without
    perturbing neighbors: per-row results are bit-identical to solo
    ``decode.generate`` at the scheduler's capacity (rows under the
    batched einsums are independent; masked slots carry exact-zero
    weight; the attention reduction extent matches by construction).

    ``speculative`` runs the draft/verify path (B=1 by its contract)
    solo in the caller's thread — an opt-in per-request latency knob,
    verified bit-exactly by the target model inside
    ``decode.speculative_generate`` itself.
    """

    def __init__(
        self,
        params,
        cfg,
        *,
        max_slots: Optional[int] = None,
        tokens_per_page: Optional[int] = None,
        max_seq: Optional[int] = None,
        pool_pages: Optional[int] = None,
        draft_params=None,
        draft_cfg=None,
    ):
        from ..models import decode as decode_mod
        from ..models import kv_pager

        self._kv = kv_pager
        self._decode = decode_mod
        self.cfg = cfg
        self._raw_params = params  # speculative casts per-model itself
        self._params = decode_mod.cast_params(params, cfg.dtype)
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.max_slots = max(
            1,
            int(max_slots)
            if max_slots is not None
            else _env_int(ENV_DECODE_MAX_SLOTS, DEFAULT_DECODE_MAX_SLOTS),
        )
        P = (
            int(tokens_per_page)
            if tokens_per_page is not None
            else kv_pager.page_tokens()
        )
        cap = int(max_seq) if max_seq is not None else int(cfg.max_seq)
        # capacity rounds UP to a whole page: the gathered attention
        # extent is max_pages * P, and bit-identity vs the contiguous
        # path is pinned at exactly this capacity (``cache_len=cap``)
        self.max_pages = kv_pager.pages_for(cap, P)
        self.cap = self.max_pages * P
        n_pages = (
            int(pool_pages)
            if pool_pages is not None
            else self.max_slots * self.max_pages + 1
        )
        self.pool = kv_pager.PagePool(cfg, n_pages, tokens_per_page=P)
        self._kp = self.pool.k_pages
        self._vp = self.pool.v_pages
        self._tables = np.zeros(
            (self.max_slots, self.max_pages), np.int32
        )
        self._indices = np.zeros((self.max_slots,), np.int32)
        self._toks = np.zeros((self.max_slots,), np.int32)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: "collections.deque[_PagedSeq]" = collections.deque()
        self._active: Dict[int, _PagedSeq] = {}
        self._free = list(range(self.max_slots))
        self._driver: Optional[threading.Thread] = None
        self._closed = False
        # telemetry (guarded by _lock where racy)
        self.steps = 0
        self.joined_mid_run = 0
        self.retired = 0
        self.total_tokens = 0
        self.prefill_batches = 0
        self.refusals = {"pages": 0, "slots": 0}
        # refusals issued while at least one slot sat idle: the bound
        # (pool size / backlog cap), not compute, was the limit — the
        # decode_slot_starvation doctor rule's evidence
        self.refused_while_idle = 0
        _LIVE_DECODE.add(self)

    # -- public --------------------------------------------------------------

    def submit(
        self,
        prompt,
        max_new: int,
        until: Optional[Callable[[int], bool]] = None,
        tenant: Optional[str] = None,
        timeout_s: Optional[float] = None,
    ) -> List[int]:
        """Stream up to ``max_new`` greedy tokens continuing ``prompt``
        (1-D int array).  Joins the running batch at the next step
        boundary; blocks until the stream retires and returns the
        emitted tokens.  Raises :class:`DecodeRefused` when the page
        pool or the slot backlog cannot take the sequence."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("decode needs a non-empty prompt")
        max_new = max(1, int(max_new))
        total = int(prompt.size) + max_new
        if total > self.cap:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new} exceeds the "
                f"scheduler capacity {self.cap} tokens"
            )
        with self._cv:
            if self._closed:
                raise RuntimeError("DecodeScheduler is closed")
            # bounded backlog: refusing here (with a hint) beats an
            # unbounded queue whose tail waits out every stream ahead
            if len(self._pending) + len(self._active) >= 2 * self.max_slots:
                self.refusals["slots"] += 1
                if len(self._active) < self.max_slots:
                    self.refused_while_idle += 1
                raise DecodeRefused(
                    "slots",
                    retry_after_ms=100 * max(1, len(self._pending)),
                    detail=(
                        f"{len(self._active)} active + "
                        f"{len(self._pending)} pending vs "
                        f"{self.max_slots} slots"
                    ),
                )
        # reserve the FULL span up front — outside the scheduler lock
        # (the pool has its own) so a slow budget walk never stalls the
        # step loop
        try:
            charge, pages = self.pool.allocate(
                self._kv.pages_for(total, self.pool.tokens_per_page),
                tenant=tenant,
            )
        except self._kv.PagesExhausted as e:
            with self._cv:
                self.refusals["pages"] += 1
                if len(self._active) < self.max_slots:
                    self.refused_while_idle += 1
            raise DecodeRefused(
                "pages", e.retry_after_ms, detail=str(e)
            ) from e
        req = _PagedSeq(
            prompt, max_new, until, tenant,
            cancellation.current_scope(), charge,
        )
        row = np.zeros((self.max_pages,), np.int32)
        row[: len(pages)] = pages
        req.table_row = row
        with self._cv:
            if self._closed:
                self.pool.free(charge)
                raise RuntimeError("DecodeScheduler is closed")
            self._pending.append(req)
            self._ensure_driver()
            self._cv.notify_all()
        if not req.done.wait(timeout=timeout_s):
            with self._cv:
                req.abandoned = True
                self._cv.notify_all()
            raise TimeoutError(
                f"decode request did not finish within {timeout_s}s"
            )
        if req.error is not None:
            raise req.error
        return req.out

    def speculative(
        self,
        prompt,
        max_new: int,
        gamma: int = 4,
        tenant: Optional[str] = None,
    ) -> List[int]:
        """Opt-in per-request speculative decoding: the draft model
        proposes, the target verifies bit-exactly
        (``decode.speculative_generate``).  Runs solo in the caller's
        thread — B=1 by the draft/verify contract — so it never blocks
        the batch; greedy output equals the batched path's."""
        if self.draft_params is None or self.draft_cfg is None:
            raise ValueError(
                "speculative decode needs a draft model "
                "(DecodeScheduler(draft_params=..., draft_cfg=...))"
            )
        import jax.numpy as jnp

        prompt = np.asarray(prompt, np.int32).reshape(1, -1)
        out = self._decode.speculative_generate(
            self.draft_params, self.draft_cfg,
            self._raw_params, self.cfg,
            jnp.asarray(prompt), int(max_new), gamma=int(gamma),
        )
        toks = [int(t) for t in np.asarray(out)[0, prompt.shape[1]:]]
        with self._cv:
            self.total_tokens += len(toks)
        observability.note_decode_tokens(len(toks))
        return toks

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._driver is not None:
            self._driver.join(timeout=5.0)

    # -- telemetry -----------------------------------------------------------

    def gauges(self) -> Dict[str, float]:
        """The ``tfs_kv_pages`` gauge family (grouped provider)."""
        stats = self.pool.stats()
        with self._lock:
            active, pending = len(self._active), len(self._pending)
        return {
            "tfs_kv_pages_free": float(stats["pages_free"]),
            "tfs_kv_pages_used": float(stats["pages_used"]),
            "tfs_kv_pages_capacity": float(stats["pages_total"]),
            "tfs_decode_slots_active": float(active),
            "tfs_decode_slots_free": float(self.max_slots - active),
            "tfs_decode_pending": float(pending),
        }

    def snapshot(self) -> Dict[str, Any]:
        stats = self.pool.stats()
        with self._lock:
            return {
                "max_slots": self.max_slots,
                "cap_tokens": self.cap,
                "page_tokens": self.pool.tokens_per_page,
                "active": len(self._active),
                "pending": len(self._pending),
                "steps": self.steps,
                "retired": self.retired,
                "joined_mid_run": self.joined_mid_run,
                "total_tokens": self.total_tokens,
                "prefill_batches": self.prefill_batches,
                "refused_pages": self.refusals["pages"],
                "refused_slots": self.refusals["slots"],
                "refused_while_idle": self.refused_while_idle,
                "pages_free": stats["pages_free"],
                "pages_used": stats["pages_used"],
                "pages_capacity": stats["pages_total"],
                "pages_allocated_total": stats["allocated_total"],
                "pages_freed_total": stats["freed_total"],
            }

    # -- driver --------------------------------------------------------------

    def _ensure_driver(self) -> None:
        if self._driver is None or not self._driver.is_alive():
            self._driver = threading.Thread(
                target=self._drive, name="tfs-paged-decode", daemon=True
            )
            self._driver.start()

    def _retire_locked(self, slot: int, req: _PagedSeq) -> None:
        """Free a slot at a step boundary: pages back to the pool, the
        table row back to all-trash (so the slot's idle writes land on
        page 0), the waiter released.  Holding the lock is fine — the
        pool lock nests under no other."""
        del self._active[slot]
        self._free.append(slot)
        self._tables[slot] = 0
        self._indices[slot] = 0
        self._toks[slot] = 0
        self.retired += 1
        self.pool.free(req.charge)
        req.done.set()

    def _dispatch(self, fn, *args):
        """One compiled dispatch with chaos injection + bounded retry:
        ``faults.maybe_inject`` fires configured transients at the step
        boundary (site='dispatch', so attempt selectors work), and the
        functional (pages, tables, tokens) state means a retry
        recomputes the identical step."""
        from .. import faults

        attempt = 0
        while True:
            try:
                faults.maybe_inject(self.steps, attempt, site="dispatch")
                return fn(*args)
            except faults.InjectedTransient:
                attempt += 1
                if attempt >= _DECODE_STEP_ATTEMPTS:
                    raise

    def _drive(self) -> None:
        import jax.numpy as jnp

        kv = self._kv
        try:
            while True:
                with self._cv:
                    while (
                        not self._closed
                        and not self._pending
                        and not self._active
                    ):
                        self._cv.wait()
                    if self._closed and not self._active:
                        err = RuntimeError(
                            "DecodeScheduler closed before this "
                            "request was admitted"
                        )
                        for req in self._pending:
                            self.pool.free(req.charge)
                            req.error = err
                            req.done.set()
                        self._pending.clear()
                        return
                    # step boundary: deadline/cancel checks retire
                    # expired rows and free their pages BEFORE admission
                    # (their slots are immediately reusable)
                    for slot, req in list(self._active.items()):
                        if req.abandoned:
                            self._retire_locked(slot, req)
                            continue
                        if req.scope is not None:
                            try:
                                req.scope.check()
                            except cancellation.Cancelled as e:
                                req.error = e
                                self._retire_locked(slot, req)
                                observability.note_bridge_deadline_exceeded()
                    was_running = bool(self._active)
                    admitted: List[Tuple[int, _PagedSeq]] = []
                    while self._pending and self._free:
                        req = self._pending.popleft()
                        if req.abandoned:
                            self.pool.free(req.charge)
                            req.done.set()
                            continue
                        slot = self._free.pop()
                        self._tables[slot] = req.table_row
                        self._indices[slot] = 0
                        self._active[slot] = req
                        admitted.append((slot, req))
                        if was_running:
                            self.joined_mid_run += 1
                    active = bool(self._active)
                if not active:
                    continue
                if admitted:
                    self._prefill(admitted, jnp)
                    # prefill may retire 1-token streams at once; the
                    # boundary loop re-checks before the next step
                    with self._cv:
                        for slot, req in admitted:
                            if slot in self._active and (
                                req.emitted >= req.max_new
                                or (
                                    req.until is not None
                                    and req.out
                                    and bool(req.until(req.out[-1]))
                                )
                            ):
                                self._retire_locked(slot, req)
                        if not self._active:
                            continue
                # decode lane: one fixed-shape step for the population
                toks, self._kp, self._vp = self._dispatch(
                    kv.paged_decode_step,
                    self._params,
                    jnp.asarray(self._toks),
                    jnp.asarray(self._tables),
                    jnp.asarray(self._indices),
                    self._kp,
                    self._vp,
                    self.cfg,
                )
                emitted = np.asarray(toks)
                self.steps += 1
                with self._cv:
                    for slot, req in list(self._active.items()):
                        self._indices[slot] += 1
                        tok = int(emitted[slot])
                        self._toks[slot] = tok
                        req.out.append(tok)
                        req.emitted += 1
                        self.total_tokens += 1
                        observability.note_decode_tokens(1)
                        stop = req.emitted >= req.max_new or (
                            req.until is not None and bool(req.until(tok))
                        )
                        if stop or req.abandoned:
                            self._retire_locked(slot, req)
                    # idle slots keep index 0 / token 0: their writes
                    # land on the trash page via their all-zero tables
        except BaseException as e:  # noqa: BLE001 — fail every waiter
            with self._cv:
                for req in list(self._active.values()):
                    self.pool.free(req.charge)
                    req.error = e
                    req.done.set()
                for req in self._pending:
                    self.pool.free(req.charge)
                    req.error = e
                    req.done.set()
                self._active.clear()
                self._pending.clear()
                self._free = list(range(self.max_slots))
                self._tables[:] = 0
                self._indices[:] = 0
                self._toks[:] = 0

    def _prefill(self, admitted, jnp) -> None:
        """The disaggregated prefill lane: the boundary's newly admitted
        sequences prefill as ONE bucket-padded batch through the
        existing ladder.  Rows not being prefilled ride along with
        all-trash tables (their live tables stay untouched — prefill
        writes only through the batch's own table argument)."""
        kv = self._kv
        max_lp = max(int(r.prompt.size) for _, r in admitted)
        lb = min(max(bucketing.bucket_for(max_lp), 1), self.cap)
        lb = max(lb, max_lp)
        toks = np.zeros((self.max_slots, lb), np.int32)
        tables = np.zeros((self.max_slots, self.max_pages), np.int32)
        last_pos = np.zeros((self.max_slots,), np.int32)
        for slot, req in admitted:
            lp = int(req.prompt.size)
            toks[slot, :lp] = req.prompt
            tables[slot] = req.table_row
            last_pos[slot] = lp - 1
        tok0, self._kp, self._vp = self._dispatch(
            kv.paged_prefill,
            self._params,
            jnp.asarray(toks),
            jnp.asarray(tables),
            jnp.asarray(last_pos),
            self._kp,
            self._vp,
            self.cfg,
        )
        tok0 = np.asarray(tok0)
        self.prefill_batches += 1
        observability.note_decode_prefill_batch()
        with self._cv:
            for slot, req in admitted:
                lp = int(req.prompt.size)
                self._indices[slot] = lp
                tok = int(tok0[slot])
                self._toks[slot] = tok
                req.out.append(tok)
                req.emitted += 1
                self.total_tokens += 1
                observability.note_decode_tokens(1)
