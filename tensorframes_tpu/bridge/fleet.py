"""Elastic bridge fleet (round 21): replicated servers, journal-backed
job migration, zero-downtime rolling restarts.

The reference's topology is a single Spark driver owning every session
(SURVEY.md §L2/L3) — one resident process, one failure domain.  The
rounds before this one built every piece of surviving that process's
death: token-addressed sessions + graceful drain (round 11), the SLO
scheduler and warm pools (round 16), and the fenced job journal with
``SessionLost`` resume (round 20).  This module assembles them into a
horizontally-scaled service:

* :class:`FleetRouter` — rendezvous-hashes a session key over the
  healthy replicas (minimal disruption: removing a replica only remaps
  the keys it owned), polls each replica's ungated ``health`` RPC, and
  quarantines flappers the way the device pool quarantines chips
  (``recently_quarantined``-style history, bounded hold).
* :class:`BridgeFleet` — runs N ``BridgeServer`` replicas, each its own
  OS process (``python -m tensorframes_tpu.bridge.replica``) sharing
  the persistent compile cache (``TFS_COMPILE_CACHE``), the planner
  calibration file, and the job journal (``TFS_JOURNAL_DIR``) — so a
  fresh replica's first request pays zero compiles and a dead replica's
  durable jobs are adoptable by any peer.  A ``mode="thread"`` fleet
  runs the replicas in-process for cheap router/drain tests (no real
  SIGKILL there; process mode is the chaos surface).
* :class:`FleetClient` — the failover-aware front end: a
  :class:`~tensorframes_tpu.bridge.client.BridgeClient` bound to the
  routed replica with the router wired in, so ``Draining``, severed
  connections, and ``SessionLost`` reroute to a healthy peer instead of
  surfacing.  A re-issued durable request (``job_id=``) adopts the dead
  replica's journal fence on the new replica and resumes from the last
  window boundary — exactly-once by the round-20 construction, counted
  in ``fleet_jobs_migrated``.
* the **fleet registry** — one heartbeat file per replica
  (``TFS_FLEET_REGISTRY``), written by the server and consulted by the
  recovery janitor so artifacts owned by a pid that is alive IN THE
  FLEET are never reclaimed on the word of a same-host ``os.kill(pid,
  0)`` (which cannot see across containers / pid namespaces).

Rolling restarts compose the existing drain: mark the replica draining
in the router (new sessions route elsewhere), drain it (in-flight
requests finish; durable stragglers hand off via the journal), restart
the process, wait for it to rejoin healthy — warm, because the compile
cache is shared.  ``docs/SERVING.md`` documents the knobs;
``docs/RESILIENCE.md`` the failure-mode rows; ``tests/test_fleet.py``
and the ``fleet`` CI tier drive the chaos (``replica_kill``) and
rolling-restart acceptance criteria.
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .. import envutil, observability
from .protocol import read_message, write_message

logger = logging.getLogger("tensorframes_tpu.bridge.fleet")

ENV_FLEET_SIZE = "TFS_FLEET_SIZE"
ENV_FLEET_REGISTRY = "TFS_FLEET_REGISTRY"
ENV_FLEET_HEALTH_S = "TFS_FLEET_HEALTH_S"
ENV_FLEET_QUARANTINE_AFTER = "TFS_FLEET_QUARANTINE_AFTER"
ENV_FLEET_QUARANTINE_S = "TFS_FLEET_QUARANTINE_S"
# set per replica by the fleet spawner; the server stamps it into its
# health/hello replica identity so routers and logs name replicas
# stably across restarts (the EPOCH token is what changes)
ENV_FLEET_REPLICA = "TFS_FLEET_REPLICA"

DEFAULT_HEALTH_S = 0.5
DEFAULT_QUARANTINE_AFTER = 3
DEFAULT_QUARANTINE_S = 30.0
# flap window: DOWN transitions (and epoch changes = silent restarts)
# inside this many seconds count toward the quarantine threshold
FLAP_WINDOW_S = 60.0
# a registry heartbeat older than this marks its writer unknown-dead:
# generous against GC pauses / busy boxes, small enough that a truly
# dead replica's artifacts become reclaimable within a janitor sweep
REGISTRY_TTL_S = 15.0


# ---------------------------------------------------------------------------
# fleet registry (heartbeat files; the janitor's cross-process liveness)
# ---------------------------------------------------------------------------


def registry_dir() -> str:
    """The live fleet-registry root ('' = no registry configured)."""
    return envutil.env_raw(ENV_FLEET_REGISTRY)


def registry_write(
    name: str,
    host: str,
    port: int,
    pid: Optional[int] = None,
    epoch: str = "",
    root: Optional[str] = None,
) -> None:
    """Write/refresh one replica's heartbeat file (atomic replace; the
    file's mtime IS the heartbeat — no clock parsing on the read side).
    A no-op when no registry is configured."""
    r = registry_dir() if root is None else root
    if not r:
        return
    os.makedirs(r, exist_ok=True)
    doc = {
        "name": name,
        "host": host,
        "port": int(port),
        "pid": int(os.getpid() if pid is None else pid),
        "epoch": epoch,
        "time": time.time(),
    }
    path = os.path.join(r, f"replica-{name}.json")
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(doc))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def registry_remove(name: str, root: Optional[str] = None) -> None:
    """Remove a replica's heartbeat (clean shutdown).  Best effort."""
    r = registry_dir() if root is None else root
    if not r:
        return
    try:
        os.remove(os.path.join(r, f"replica-{name}.json"))
    except OSError:
        pass


def registry_live_pids(
    root: Optional[str] = None, ttl_s: float = REGISTRY_TTL_S
) -> frozenset:
    """Pids with a FRESH heartbeat in the fleet registry — the janitor's
    cross-process liveness source: an artifact owned by one of these is
    never reclaimable, whatever the scanning process's ``os.kill(pid,
    0)`` says (a registry replica may live in another container or pid
    namespace where that probe lies)."""
    r = registry_dir() if root is None else root
    if not r:
        return frozenset()
    now = time.time()
    out = set()
    try:
        names = os.listdir(r)
    except OSError:
        return frozenset()
    for n in names:
        if not (n.startswith("replica-") and n.endswith(".json")):
            continue
        path = os.path.join(r, n)
        try:
            if now - os.path.getmtime(path) > ttl_s:
                continue
            with open(path) as f:
                doc = json.load(f)
            out.add(int(doc["pid"]))
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return frozenset(out)


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------


def _rendezvous_score(name: str, key: str) -> int:
    return int.from_bytes(
        hashlib.sha256(f"{name}|{key}".encode()).digest()[:8], "big"
    )


def _fetch_health(
    host: str, port: int, timeout_s: float = 2.0
) -> Dict[str, Any]:
    """One raw ``health`` round trip — no ``hello``, so a poll never
    creates (and TTL-leaks) a server-side session."""
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        w = s.makefile("wb")
        r = s.makefile("rb")
        write_message(w, {"id": 1, "method": "health", "params": {}})
        resp, _bins = read_message(r)
    if "error" in resp:
        raise ConnectionError(f"health refused: {resp['error']}")
    return resp["result"]


class _ReplicaState:
    __slots__ = (
        "name", "host", "port", "healthy", "draining", "pid", "epoch",
        "uptime_s", "p99_ms", "sessions", "flaps", "quarantined_until",
        "last_ok", "failures",
    )

    def __init__(self, name: str, host: str, port: int):
        self.name = name
        self.host = host
        self.port = int(port)
        self.healthy = False  # unknown until the first poll succeeds
        self.draining = False
        self.pid: Optional[int] = None
        self.epoch: str = ""
        self.uptime_s: float = 0.0
        self.p99_ms: Optional[float] = None
        self.sessions: int = 0
        # monotonic times of DOWN transitions + epoch changes (restarts)
        self.flaps: "collections.deque[float]" = collections.deque(
            maxlen=64
        )
        self.quarantined_until: float = 0.0
        self.last_ok: float = 0.0
        self.failures: int = 0

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)


# live routers, for tfs.doctor()'s fleet rules (weakrefs so a dropped
# router never outlives its test)
import weakref  # noqa: E402

_live_routers: "weakref.WeakSet" = weakref.WeakSet()


def doctor_snapshot() -> Optional[Dict[str, Any]]:
    """The newest live router's :meth:`FleetRouter.snapshot`, or None —
    the evidence surface the ``replica_flap`` / ``fleet_imbalance``
    doctor rules read."""
    snap = None
    for r in _live_routers:
        try:
            snap = r.snapshot()
        except Exception:  # noqa: BLE001 — doctor evidence is best effort
            continue
    return snap


class FleetRouter:
    """Rendezvous-hash router + health poller over bridge replicas.

    Routing is *rendezvous* (highest-random-weight): every (key,
    replica) pair gets a deterministic score and the eligible replica
    with the highest score owns the key — so adding or removing one
    replica remaps only that replica's keys, which is exactly the
    property a rolling restart wants (drained replica's keys spread
    over the peers; everyone else's sessions stay put).

    Eligibility excludes draining, quarantined, and known-unhealthy
    replicas; when nothing is eligible the router degrades gracefully
    (draining peers, then anything known) rather than refusing — a
    degraded route can still shed structured errors the client's retry
    loop understands, which beats routing nowhere.

    Health state comes from :meth:`poll_once` (a background thread via
    :meth:`start`, or called explicitly by tests with an injected
    ``fetch``) plus client feedback (:meth:`note_failed` /
    :meth:`note_draining`).  A replica whose identity EPOCH changes
    between polls restarted silently — that counts as a flap, same as a
    down transition; ``quarantine_after`` flaps inside
    ``FLAP_WINDOW_S`` quarantines it for ``quarantine_s`` (counted in
    ``fleet_quarantines``), mirroring the device pool's chip
    quarantine."""

    def __init__(
        self,
        replicas: Optional[
            Sequence[Tuple[str, str, int]]
        ] = None,  # (name, host, port)
        health_s: Optional[float] = None,
        quarantine_after: Optional[int] = None,
        quarantine_s: Optional[float] = None,
        fetch: Optional[Callable[[str, int], Dict[str, Any]]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.health_s = (
            envutil.env_float(ENV_FLEET_HEALTH_S, DEFAULT_HEALTH_S)
            if health_s is None
            else float(health_s)
        )
        self.quarantine_after = (
            envutil.env_int(
                ENV_FLEET_QUARANTINE_AFTER, DEFAULT_QUARANTINE_AFTER
            )
            if quarantine_after is None
            else int(quarantine_after)
        )
        self.quarantine_s = (
            envutil.env_float(ENV_FLEET_QUARANTINE_S, DEFAULT_QUARANTINE_S)
            if quarantine_s is None
            else float(quarantine_s)
        )
        self._fetch = fetch or _fetch_health
        self._clock = clock
        self._lock = threading.Lock()
        self._replicas: Dict[str, _ReplicaState] = {}
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self._gauge_provider = self._gauges
        observability.register_gauge("tfs_fleet", self._gauge_provider)
        for name, host, port in replicas or ():
            self.add(name, host, port)
        _live_routers.add(self)

    # -- membership ----------------------------------------------------------

    def add(self, name: str, host: str, port: int) -> None:
        with self._lock:
            self._replicas[name] = _ReplicaState(name, host, port)

    def remove(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)

    def set_addr(self, name: str, host: str, port: int) -> None:
        """Re-point a replica (restart on a new port) without losing its
        flap history."""
        with self._lock:
            st = self._replicas.get(name)
            if st is None:
                self._replicas[name] = _ReplicaState(name, host, port)
            else:
                st.host, st.port = host, int(port)

    def __len__(self) -> int:
        with self._lock:
            return len(self._replicas)

    # -- routing -------------------------------------------------------------

    def _eligible_locked(self) -> List[_ReplicaState]:
        now = self._clock()
        all_ = list(self._replicas.values())
        best = [
            s for s in all_
            if s.healthy and not s.draining and s.quarantined_until <= now
        ]
        if best:
            return best
        # degrade: draining beats dead; anything beats nothing
        alive = [s for s in all_ if s.healthy]
        return alive or all_

    def route(self, key: str) -> _ReplicaState:
        """The replica that owns ``key`` right now."""
        with self._lock:
            cands = self._eligible_locked()
            if not cands:
                raise RuntimeError("fleet router has no replicas")
            return max(
                cands, key=lambda s: _rendezvous_score(s.name, key)
            )

    def pick(
        self,
        exclude: Optional[Tuple[str, int]] = None,
        key: Optional[str] = None,
    ) -> Optional[Tuple[str, int]]:
        """A healthy address for a failing-over client — the rendezvous
        choice for ``key`` among replicas other than ``exclude`` (the
        address the client is leaving).  None when no other replica is
        known."""
        with self._lock:
            cands = [
                s for s in self._eligible_locked() if s.addr != exclude
            ]
            if not cands:
                cands = [
                    s
                    for s in self._replicas.values()
                    if s.addr != exclude
                ]
            if not cands:
                return None
            k = key if key is not None else uuid.uuid4().hex
            return max(
                cands, key=lambda s: _rendezvous_score(s.name, k)
            ).addr

    def failover_budget(self) -> int:
        """How many reroutes a single client call may spend — one per
        known peer, so a call can walk the whole fleet once but a fully
        dead fleet still surfaces promptly."""
        return max(1, len(self))

    # -- health --------------------------------------------------------------

    def _record_flap_locked(self, st: _ReplicaState) -> None:
        now = self._clock()
        st.flaps.append(now)
        recent = [t for t in st.flaps if now - t <= FLAP_WINDOW_S]
        if (
            len(recent) >= self.quarantine_after
            and st.quarantined_until <= now
        ):
            st.quarantined_until = now + self.quarantine_s
            observability.note_fleet_quarantine()
            logger.warning(
                "fleet: quarantining replica %s for %.0fs (%d flaps "
                "in %.0fs)",
                st.name,
                self.quarantine_s,
                len(recent),
                FLAP_WINDOW_S,
            )

    def poll_once(self) -> None:
        """One health sweep over every replica (the poll thread's body;
        tests call it directly with an injected ``fetch``/``clock``)."""
        with self._lock:
            targets = list(self._replicas.values())
        for st in targets:
            try:
                h = self._fetch(st.host, st.port)
            except Exception:  # noqa: BLE001 — any failure = unhealthy
                with self._lock:
                    st.failures += 1
                    if st.healthy:
                        st.healthy = False
                        self._record_flap_locked(st)
                continue
            rep = h.get("replica") or {}
            sched = h.get("scheduler") or {}
            with self._lock:
                new_epoch = str(rep.get("epoch") or "")
                if st.epoch and new_epoch and new_epoch != st.epoch:
                    # same name, new life: a restart we never saw go
                    # down (the identity token is what makes this
                    # detectable without guessing from resets)
                    self._record_flap_locked(st)
                st.epoch = new_epoch or st.epoch
                st.pid = rep.get("pid") or st.pid
                st.uptime_s = float(rep.get("uptime_s") or 0.0)
                st.p99_ms = sched.get("p99_ms")
                st.sessions = int(h.get("sessions") or 0)
                st.draining = h.get("status") == "draining"
                st.last_ok = self._clock()
                st.failures = 0
                if not st.healthy:
                    st.healthy = True

    def start(self) -> "FleetRouter":
        """Start the background poll thread (idempotent)."""
        if self._poll_thread is None or not self._poll_thread.is_alive():
            self._poll_stop.clear()
            t = threading.Thread(
                target=self._poll_loop, name="tfs-fleet-poll", daemon=True
            )
            self._poll_thread = t
            t.start()
        return self

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.health_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the poller must survive
                logger.warning("fleet: health poll failed", exc_info=True)

    def close(self) -> None:
        self._poll_stop.set()
        observability.unregister_gauge("tfs_fleet", self._gauge_provider)

    # -- client feedback -----------------------------------------------------

    def _by_addr_locked(
        self, addr: Tuple[str, int]
    ) -> Optional[_ReplicaState]:
        for s in self._replicas.values():
            if s.addr == tuple(addr):
                return s
        return None

    def note_failed(self, addr: Tuple[str, int]) -> None:
        """A client's connection to ``addr`` died — mark it down now
        instead of waiting out a poll period."""
        with self._lock:
            st = self._by_addr_locked(addr)
            if st is not None and st.healthy:
                st.healthy = False
                self._record_flap_locked(st)

    def note_draining(self, addr: Tuple[str, int]) -> None:
        """A client got ``Draining`` from ``addr`` — route around it."""
        with self._lock:
            st = self._by_addr_locked(addr)
            if st is not None:
                st.draining = True

    def mark_draining(self, name: str, draining: bool = True) -> None:
        """Operator/rolling-restart lever: stop (or resume) routing new
        work to ``name`` ahead of the server's own drain status."""
        with self._lock:
            st = self._replicas.get(name)
            if st is not None:
                st.draining = draining

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        now = self._clock()
        with self._lock:
            reps = {}
            for s in self._replicas.values():
                reps[s.name] = {
                    "host": s.host,
                    "port": s.port,
                    "healthy": s.healthy,
                    "draining": s.draining,
                    "quarantined": s.quarantined_until > now,
                    "pid": s.pid,
                    "epoch": s.epoch,
                    "uptime_s": round(s.uptime_s, 3),
                    "p99_ms": s.p99_ms,
                    "sessions": s.sessions,
                    "flaps_recent": len(
                        [t for t in s.flaps if now - t <= FLAP_WINDOW_S]
                    ),
                    "failures": s.failures,
                }
            return {
                "replicas": reps,
                "quarantine_after": self.quarantine_after,
                "quarantine_s": self.quarantine_s,
                "flap_window_s": FLAP_WINDOW_S,
            }

    def _gauges(self) -> Dict[str, Any]:
        snap = self.snapshot()["replicas"].values()
        return {
            "tfs_fleet_replicas": len(snap),
            "tfs_fleet_healthy": sum(1 for s in snap if s["healthy"]),
            "tfs_fleet_draining": sum(1 for s in snap if s["draining"]),
            "tfs_fleet_quarantined": sum(
                1 for s in snap if s["quarantined"]
            ),
        }


# ---------------------------------------------------------------------------
# fleet (replica lifecycle)
# ---------------------------------------------------------------------------


def _free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


class _Replica:
    __slots__ = ("name", "host", "port", "proc", "server", "env", "log")

    def __init__(self, name, host, port):
        self.name = name
        self.host = host
        self.port = port
        self.proc = None  # subprocess.Popen (process mode)
        self.server = None  # BridgeServer (thread mode)
        self.env: Dict[str, str] = {}
        self.log = None


class BridgeFleet:
    """N bridge replicas with shared durable state, plus the levers the
    chaos/restart harnesses need (kill, drain, restart, rolling
    restart).

    ``mode="process"`` (the real topology): each replica is
    ``python -m tensorframes_tpu.bridge.replica`` — its own interpreter,
    killable with a real SIGKILL, drained with SIGTERM.  The spawn env
    is ``os.environ`` overlaid with ``base_env`` (where the caller puts
    the SHARED state: ``TFS_JOURNAL_DIR``, ``TFS_COMPILE_CACHE``,
    ``TFS_FLEET_REGISTRY``, ``TFS_BRIDGE_PIPELINE_PATHS``...) overlaid
    with ``fault_env[name]`` (per-replica chaos, e.g. a
    ``replica_kill`` spec on exactly one replica).  Replica stdout/err
    go to ``<log_dir>/<name>.log`` when ``log_dir`` is given.

    ``mode="thread"``: the replicas are in-process ``BridgeServer``s
    (``server_kw`` forwarded) — no process isolation, no SIGKILL, but
    routing/drain/failover semantics are identical and tests stay
    cheap."""

    def __init__(
        self,
        size: Optional[int] = None,
        mode: str = "process",
        host: str = "127.0.0.1",
        base_env: Optional[Dict[str, str]] = None,
        fault_env: Optional[Dict[str, str]] = None,
        log_dir: Optional[str] = None,
        name_prefix: str = "r",
        ready_timeout_s: float = 30.0,
        **server_kw,
    ):
        if mode not in ("process", "thread"):
            raise ValueError(f"unknown fleet mode {mode!r}")
        self.size = (
            envutil.env_int(ENV_FLEET_SIZE, 0) if size is None else int(size)
        )
        if self.size <= 0:
            raise ValueError(
                f"fleet size must be positive (got {self.size}; set "
                f"{ENV_FLEET_SIZE} or pass size=)"
            )
        self.mode = mode
        self.host = host
        self.base_env = dict(base_env or {})
        self.fault_env = dict(fault_env or {})
        self.log_dir = log_dir
        self.ready_timeout_s = float(ready_timeout_s)
        self.server_kw = server_kw
        self._replicas: "collections.OrderedDict[str, _Replica]" = (
            collections.OrderedDict()
        )
        for i in range(self.size):
            name = f"{name_prefix}{i}"
            self._replicas[name] = _Replica(name, host, 0)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "BridgeFleet":
        for rep in self._replicas.values():
            self._spawn(rep)
        for rep in self._replicas.values():
            self._wait_ready(rep)
        return self

    def _spawn(self, rep: _Replica) -> None:
        rep.port = rep.port or _free_port(self.host)
        if self.mode == "thread":
            from .server import serve

            env_overlay = dict(self.base_env)
            env_overlay.update(self.fault_env.get(rep.name, {}) or {})
            if env_overlay:
                raise ValueError(
                    "thread-mode replicas share this process's env; "
                    "base_env/fault_env need mode='process'"
                )
            rep.server = serve(
                host=self.host, port=rep.port, **self.server_kw
            )
            rep.port = rep.server.address[1]
            return
        env = dict(os.environ)
        env.update(self.base_env)
        fault = self.fault_env.get(rep.name)
        if fault is not None:
            env["TFS_FAULT_INJECT"] = fault
        env[ENV_FLEET_REPLICA] = rep.name
        # the replica module imports the tree under test even when the
        # package is not installed (tests, benches): repo root first
        env["PYTHONPATH"] = os.pathsep.join(
            p
            for p in (_repo_root(), env.get("PYTHONPATH", ""))
            if p
        )
        rep.env = env
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            rep.log = open(
                os.path.join(self.log_dir, f"{rep.name}.log"), "ab"
            )
        rep.proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "tensorframes_tpu.bridge.replica",
                "--host",
                self.host,
                "--port",
                str(rep.port),
                "--name",
                rep.name,
            ],
            env=env,
            stdout=rep.log or subprocess.DEVNULL,
            stderr=rep.log or subprocess.DEVNULL,
            cwd=_repo_root(),
        )

    def _wait_ready(self, rep: _Replica) -> Dict[str, Any]:
        deadline = time.monotonic() + self.ready_timeout_s
        last_exc: Optional[Exception] = None
        while time.monotonic() < deadline:
            if rep.proc is not None and rep.proc.poll() is not None:
                raise RuntimeError(
                    f"fleet replica {rep.name} exited rc="
                    f"{rep.proc.returncode} before becoming healthy"
                )
            try:
                return _fetch_health(rep.host, rep.port, timeout_s=1.0)
            except Exception as exc:  # noqa: BLE001 — keep waiting
                last_exc = exc
                time.sleep(0.05)
        raise RuntimeError(
            f"fleet replica {rep.name} not healthy after "
            f"{self.ready_timeout_s}s: {last_exc}"
        )

    def replicas(self) -> List[Tuple[str, str, int]]:
        """(name, host, port) triples — :class:`FleetRouter` input."""
        return [
            (r.name, r.host, r.port) for r in self._replicas.values()
        ]

    def router(self, **kw) -> FleetRouter:
        """A started router over this fleet's replicas."""
        r = FleetRouter(self.replicas(), **kw)
        r.poll_once()
        return r.start()

    # -- chaos / restart levers ----------------------------------------------

    def kill(self, name: str) -> None:
        """Real SIGKILL — no drain, no journal handoff, no goodbyes.
        The death the chaos acceptance test recovers from."""
        rep = self._replicas[name]
        if rep.proc is None:
            raise RuntimeError(
                "kill() needs a process-mode fleet (thread replicas "
                "share this process)"
            )
        import signal

        rep.proc.send_signal(signal.SIGKILL)
        rep.proc.wait(timeout=10)

    def drain(self, name: str, timeout_s: float = 30.0) -> None:
        """Graceful drain: SIGTERM (process mode — the replica main
        runs ``server.close(drain_s)`` and exits) or ``close()``
        (thread mode).  In-flight requests finish; durable stragglers
        hand off via the journal on their next adoption."""
        rep = self._replicas[name]
        if rep.server is not None:
            rep.server.close()
            rep.server = None
            return
        if rep.proc is None or rep.proc.poll() is not None:
            return
        import signal

        rep.proc.send_signal(signal.SIGTERM)
        rep.proc.wait(timeout=timeout_s)

    def restart(self, name: str) -> None:
        """Respawn a (dead or drained) replica on its OWN port and wait
        until it polls healthy — warm by construction when
        ``TFS_COMPILE_CACHE`` is shared.  Counted in
        ``fleet_replica_restarts``."""
        rep = self._replicas[name]
        if rep.proc is not None and rep.proc.poll() is None:
            raise RuntimeError(
                f"replica {name} is still running; drain or kill first"
            )
        self._spawn(rep)
        self._wait_ready(rep)
        observability.note_fleet_replica_restart()

    def rolling_restart(
        self,
        router: Optional[FleetRouter] = None,
        drain_timeout_s: float = 30.0,
    ) -> None:
        """Zero-downtime rolling restart: one replica at a time — route
        away, drain, restart, rejoin — so the fleet never loses more
        than one replica of capacity and rejoining replicas serve their
        first request from the shared compile cache."""
        for name in list(self._replicas):
            if router is not None:
                router.mark_draining(name)
            self.drain(name, timeout_s=drain_timeout_s)
            self.restart(name)
            if router is not None:
                router.set_addr(
                    name,
                    self._replicas[name].host,
                    self._replicas[name].port,
                )
                router.mark_draining(name, False)
                router.poll_once()

    def stop(self) -> None:
        for rep in self._replicas.values():
            try:
                if rep.server is not None:
                    rep.server.close(drain_s=0.5)
                    rep.server = None
                if rep.proc is not None and rep.proc.poll() is None:
                    rep.proc.terminate()
                    try:
                        rep.proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        rep.proc.kill()
                        rep.proc.wait(timeout=10)
            finally:
                if rep.log is not None:
                    rep.log.close()
                    rep.log = None

    def __enter__(self) -> "BridgeFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# failover client
# ---------------------------------------------------------------------------


class FleetClient:
    """A :class:`BridgeClient` bound to the replica that owns ``key``,
    with the router wired in: ``Draining``, dead connections, and
    ``SessionLost`` fail over to a healthy peer inside the client's own
    retry loop (``fleet_failovers``), and a durable ``run_pipeline``
    that comes back ``resumed`` from a different replica counts in
    ``fleet_jobs_migrated``.

    Failover reattaches a FRESH session: registered frames do not
    follow (re-upload them); durable jobs do — the journal is the
    migration medium, so a re-issued ``job_id`` resumes from its last
    window boundary on whichever replica answers."""

    def __init__(self, router: FleetRouter, key: Optional[str] = None,
                 **client_kw):
        from .client import BridgeClient

        self.router = router
        self.key = key if key is not None else uuid.uuid4().hex
        st = router.route(self.key)
        self.client = BridgeClient(
            st.host, st.port, router=router, **client_kw
        )

    def call(self, method: str, **params) -> Any:
        return self.client.call(method, **params)

    def ping(self) -> bool:
        return self.client.ping()

    def health(self) -> Dict[str, Any]:
        return self.client.health()

    def job_status(self, job_id: str) -> Dict[str, Any]:
        return self.client.job_status(job_id)

    def create_frame(self, *a, **kw):
        return self.client.create_frame(*a, **kw)

    def run_pipeline(self, *a, **kw) -> Dict[str, Any]:
        origin = (self.client._host, self.client._port)
        before = self.client.failovers
        r = self.client.run_pipeline(*a, **kw)
        if (
            kw.get("job_id") is not None
            and r.get("resumed")
            and (
                self.client.failovers > before
                or (self.client._host, self.client._port) != origin
            )
        ):
            observability.note_fleet_job_migrated()
        return r

    def close(self) -> None:
        self.client.close()

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
