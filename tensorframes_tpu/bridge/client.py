"""Bridge client: the reference-shaped front-end handle.

``RemoteFrame`` plays the role the JVM DataFrame handle plays for the
reference's Python API (``core.py``): a thin id-carrying proxy whose verbs
ship GraphDef bytes + builder state to the engine and return new handles.
"""

from __future__ import annotations

import socket
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

from .protocol import decode_value, encode_value, read_message, write_message


class BridgeError(RuntimeError):
    """A server-side failure, re-raised client-side with the remote type."""

    def __init__(self, type_name: str, message: str):
        super().__init__(f"{type_name}: {message}")
        self.remote_type = type_name


class BridgeClient:
    """Connects to a :class:`~tensorframes_tpu.bridge.server.BridgeServer`."""

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")
        self._next_id = 0

    # -- plumbing ------------------------------------------------------------

    def call(self, method: str, **params) -> Any:
        self._next_id += 1
        bins: list = []
        write_message(
            self._wfile,
            {
                "id": self._next_id,
                "method": method,
                "params": encode_value(params, bins),
            },
            bins,
        )
        resp, rbins = read_message(self._rfile)
        if "error" in resp:
            err = resp["error"]
            raise BridgeError(err["type"], err["message"])
        return decode_value(resp["result"], rbins)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- frontend API --------------------------------------------------------

    def ping(self) -> bool:
        return bool(self.call("ping")["pong"])

    def create_frame(
        self, columns: Mapping[str, Any], num_blocks: int = 1
    ) -> "RemoteFrame":
        r = self.call(
            "create_frame",
            columns={k: np.asarray(v) if not isinstance(v, list) else v
                     for k, v in columns.items()},
            num_blocks=num_blocks,
        )
        return RemoteFrame(self, r["frame_id"], r["schema"])


class RemoteFrame:
    """Handle to a frame living in the bridge server."""

    def __init__(self, client: BridgeClient, frame_id: int, schema):
        self._c = client
        self.frame_id = frame_id
        self.schema = schema

    def analyze(self) -> "RemoteFrame":
        self.schema = self._c.call("analyze", frame_id=self.frame_id)["schema"]
        return self

    def _df_verb(self, verb: str, graph: bytes, **kw) -> "RemoteFrame":
        r = self._c.call(verb, frame_id=self.frame_id, graph=graph, **kw)
        return RemoteFrame(self._c, r["frame_id"], r["schema"])

    def map_blocks(
        self,
        graph: bytes,
        fetches: Sequence[str],
        inputs: Optional[Mapping[str, str]] = None,
        shapes: Optional[Mapping[str, Sequence[int]]] = None,
        trim: bool = False,
    ) -> "RemoteFrame":
        return self._df_verb(
            "map_blocks", graph, fetches=list(fetches),
            inputs=dict(inputs or {}), shapes=dict(shapes or {}), trim=trim,
        )

    def map_rows(
        self,
        graph: bytes,
        fetches: Sequence[str],
        inputs: Optional[Mapping[str, str]] = None,
        shapes: Optional[Mapping[str, Sequence[int]]] = None,
    ) -> "RemoteFrame":
        return self._df_verb(
            "map_rows", graph, fetches=list(fetches),
            inputs=dict(inputs or {}), shapes=dict(shapes or {}),
        )

    def aggregate(
        self, keys: Sequence[str], graph: bytes, fetches: Sequence[str]
    ) -> "RemoteFrame":
        return self._df_verb(
            "aggregate", graph, keys=list(keys), fetches=list(fetches)
        )

    def _row_verb(self, verb: str, graph: bytes, fetches) -> Dict[str, Any]:
        r = self._c.call(
            verb, frame_id=self.frame_id, graph=graph, fetches=list(fetches)
        )
        return r["row"]

    def reduce_blocks(self, graph: bytes, fetches: Sequence[str]):
        return self._row_verb("reduce_blocks", graph, fetches)

    def reduce_rows(self, graph: bytes, fetches: Sequence[str]):
        return self._row_verb("reduce_rows", graph, fetches)

    def collect(self, columns: Optional[List[str]] = None) -> Dict[str, Any]:
        return self._c.call(
            "collect", frame_id=self.frame_id, columns=columns
        )["columns"]

    def release(self) -> None:
        self._c.call("release", frame_id=self.frame_id)
