"""Bridge client: the reference-shaped front-end handle.

``RemoteFrame`` plays the role the JVM DataFrame handle plays for the
reference's Python API (``core.py``): a thin id-carrying proxy whose verbs
ship GraphDef bytes + builder state to the engine and return new handles.

Client-side resilience (round 11):

* **Thread safety**: one lock serialises each call's write+read pair, so
  threads sharing a client can no longer interleave frames on the socket
  and desync the protocol.  The lock makes the client correct, not
  parallel — concurrent callers queue on it (and on the server's
  admission gate behind it); for real client-side parallelism open one
  ``BridgeClient`` (= one connection, one session) per thread instead.
* **Deadlines**: ``deadline_ms`` (per call, or a client-wide default)
  rides the request envelope; the server cancels the verb at the next
  block boundary past it and returns a structured ``deadline_exceeded``
  error, raised here as :class:`DeadlineExceeded`.  The session and its
  frames remain fully usable afterwards.
* **Reconnect + safe retry**: a connection failure (dropped socket, read
  timeout) tears the connection down and retries with decorrelated-
  jitter backoff (``resilience.FailureDetector``) — transparently for
  cheap side-effect-free methods (``ping``/``schema``/``health``/
  ``release``), and for every gated method (``collect`` included) under
  an **idempotency token** the server dedups, so a retried request
  after a dropped *reply* is served the first execution's outcome and
  never double-executes (a retry racing its still-running original
  WAITS for that outcome instead of occupying a second admission slot).  Sessions are
  token-addressed server-side (``hello``), so the reconnected client
  reattaches to the same frames.
* **Structured refusals**: admission sheds raise :class:`ServerBusy`
  (carrying ``retry_after_ms``) or :class:`Draining`.  With
  ``busy_retries`` (``TFS_BRIDGE_CLIENT_BUSY_RETRIES``, default 0) set,
  the retry loop HONORS the server's ``retry_after_ms`` hint (round-16
  satellite): a shed gated call sleeps exactly the hinted backoff and
  re-sends — never past the call's deadline, and never for ``Draining``
  (a draining server wants you gone, not back).  At 0 the pre-round-16
  behavior stands: sheds surface immediately and routing is the
  caller's policy.  Round 21 caps the hint
  (``TFS_BRIDGE_CLIENT_BUSY_CAP_MS``) and decorrelates it with jitter
  (:func:`busy_backoff_s`) so a fleet's shed clients never re-arrive in
  lockstep.
* **Fleet failover** (round 21): with ``router=`` wired in (a
  :class:`~tensorframes_tpu.bridge.fleet.FleetRouter`), connection
  failures, ``Draining``, and ``SessionLost`` re-route the call to a
  healthy peer instead of surfacing — a fresh session there (frames do
  not follow; re-upload), with durable jobs migrating via the journal
  when their re-sent request carries its ``job_id``.  Budget: one
  reroute per known peer per call.
"""

from __future__ import annotations

import logging
import random
import socket
import threading
import time
import uuid
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

from .. import observability, resilience
from ..envutil import env_float, env_int, env_opt_float
from .protocol import decode_value, encode_value, read_message, write_message

logger = logging.getLogger("tensorframes_tpu.bridge.client")

ENV_CLIENT_TIMEOUT_S = "TFS_BRIDGE_CLIENT_TIMEOUT_S"
ENV_CLIENT_RETRIES = "TFS_BRIDGE_CLIENT_RETRIES"
ENV_CLIENT_BUSY_RETRIES = "TFS_BRIDGE_CLIENT_BUSY_RETRIES"
ENV_CLIENT_BUSY_CAP_MS = "TFS_BRIDGE_CLIENT_BUSY_CAP_MS"

DEFAULT_RECONNECT_RETRIES = 3
DEFAULT_BACKOFF_S = 0.05
DEFAULT_BUSY_CAP_MS = 1000.0

# when a call has a deadline but the client has NO configured socket
# timeout, the reply read is still bounded at deadline + a grace (the
# server legitimately replies a structured deadline_exceeded up to one
# block's compute AFTER the deadline — cutting the read exactly at the
# deadline would lose that reply).  The grace SCALES with the deadline
# (2x, floored/capped below) so a 100ms-SLO call never waits 30s for a
# wedged server, while a long-deadline call keeps room for a
# boundary-late reply; a wedged server costs at most deadline + grace.
DEADLINE_READ_GRACE_MIN_S = 1.0
DEADLINE_READ_GRACE_MAX_S = 30.0


def _read_grace_s(remaining_s: float) -> float:
    return min(
        DEADLINE_READ_GRACE_MAX_S,
        max(DEADLINE_READ_GRACE_MIN_S, 2.0 * remaining_s),
    )


def busy_backoff_s(
    hint_ms: float,
    cap_ms: float = DEFAULT_BUSY_CAP_MS,
    attempt: int = 0,
    rng=None,
) -> float:
    """The busy-retry sleep, in seconds (round 21).

    The server's ``retry_after_ms`` hint is deterministic per shed — so
    a fleet's worth of clients shed in the same overload wave would all
    re-arrive in lockstep, a thundering herd the admission gate sheds
    again, forever.  Cap the hint at ``cap_ms`` (a server under duress
    can hint arbitrarily far; the CLIENT owns how long it is willing to
    stall), grow it per ``attempt`` (2x, still capped), and draw
    uniformly from [half, full] of that target — decorrelated enough
    that re-arrivals spread across half a window, while every draw
    still respects at least half the server's hint."""
    capped = min(max(float(hint_ms), 1.0), float(cap_ms))
    target = min(capped * (2.0 ** max(0, int(attempt))), float(cap_ms))
    lo = target / 2.0
    draw = rng.random() if rng is not None else random.random()
    return (lo + draw * (target - lo)) / 1e3

# methods whose re-execution is harmless AND cheap: control-plane reads
# plus ``release`` (a pop that ignores unknown ids — naturally
# idempotent; the server's UNGATED surface never consults idem tokens,
# so every ungated method must be on this list or naturally idempotent).
# They retry without an idempotency token.  Every GATED method —
# including the read-only but EXPENSIVE ``collect`` — gets a token the
# server dedups: a retry never races a still-running original into a
# duplicate admission slot (it waits for the original's outcome).
_SAFE_METHODS = frozenset(
    {"ping", "schema", "health", "hello", "release", "metrics",
     "attribution", "check", "job_status"}
)


class BridgeError(RuntimeError):
    """A server-side failure, re-raised client-side with the remote type
    (and, when the server sent one, the structured ``code`` plus the
    full error payload)."""

    def __init__(
        self,
        type_name: str,
        message: str,
        code: Optional[str] = None,
        payload: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(f"{type_name}: {message}")
        self.remote_type = type_name
        self.code = code
        self.payload = dict(payload or {})


class DeadlineExceeded(BridgeError):
    """The verb exceeded its ``deadline_ms`` and was cancelled at a
    block boundary; the session's frames are intact and usable."""


class Cancelled(BridgeError):
    """The request was cooperatively cancelled (e.g. the server's
    graceful drain cancelled a straggler)."""


class ServerBusy(BridgeError):
    """Admission control shed this request; ``retry_after_ms`` is the
    server's deterministic backoff hint."""

    @property
    def retry_after_ms(self) -> int:
        return int(self.payload.get("retry_after_ms", 50))


class Draining(BridgeError):
    """The server is draining for shutdown; route elsewhere."""


class SessionLost(BridgeError):
    """The session token no longer names server-side state — the
    session TTL'd out, or the server RESTARTED (round 20).  Frames are
    gone; durable jobs are not: reattach with a fresh session, re-upload
    inputs, and re-issue durable requests with their ``job_id`` — the
    journal resumes them from the last completed window (and a job that
    already completed returns its journaled result without executing).
    ``job_status(job_id)`` shows what survives."""


class JobActive(BridgeError):
    """A resume raced the original request: the job is still executing
    server-side.  Never a concurrent duplicate — poll ``job_status``
    (or just retry after it finishes)."""


_CODED_ERRORS: Dict[str, type] = {
    "deadline_exceeded": DeadlineExceeded,
    "cancelled": Cancelled,
    "server_busy": ServerBusy,
    "draining": Draining,
    "unknown_session": SessionLost,
    "job_active": JobActive,
}


def _raise_remote(err: Dict[str, Any]) -> None:
    cls = _CODED_ERRORS.get(err.get("code") or "", BridgeError)
    raise cls(
        err.get("type", "Error"),
        err.get("message", ""),
        code=err.get("code"),
        payload=err,
    )


class BridgeClient:
    """Connects to a :class:`~tensorframes_tpu.bridge.server.BridgeServer`.

    One client = one connection = one server session (reattached across
    reconnects via the session token ``hello`` returns).  Thread-safe
    (calls serialise on an internal lock); use one client per thread for
    client-side parallelism.

    * ``timeout_s`` — socket read/connect timeout (default
      ``TFS_BRIDGE_CLIENT_TIMEOUT_S``, else None = block forever; set it
      for serving paths so a wedged server becomes a retryable failure).
    * ``deadline_ms`` — client-wide default request deadline (per-call
      ``deadline_ms=`` overrides).
    * ``reconnect_retries`` / ``backoff_s`` / ``jitter`` / ``rng`` —
      reconnect policy: decorrelated-jitter exponential backoff via
      ``resilience.FailureDetector`` (``jitter=0`` is the exact
      exponential sequence; ``rng`` injectable for deterministic tests).
    """

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: Optional[float] = None,
        deadline_ms: Optional[float] = None,
        reconnect_retries: Optional[int] = None,
        backoff_s: float = DEFAULT_BACKOFF_S,
        jitter: float = 1.0,
        rng=None,
        tenant: Optional[str] = None,
        busy_retries: Optional[int] = None,
        router=None,
    ):
        self._host = host
        self._port = int(port)
        # round 21 — fleet failover: with a router wired in, connection
        # failures, ``Draining``, and ``SessionLost`` re-route this
        # client to a healthy peer (fresh session there; durable jobs
        # migrate via the journal when their request is re-sent with
        # its job_id).  ``failovers`` counts reroutes on this client;
        # ``server_replica`` is the identity dict the last successful
        # hello returned (None on pre-round-21 servers).
        self.router = router
        self.failovers = 0
        self.server_replica: Optional[Dict[str, Any]] = None
        # request-scoped telemetry (round 15): every GATED call is
        # stamped with a fresh correlation id (STABLE across that
        # call's reconnect retries, so a retried request attributes to
        # one request server-side; safe/ungated methods are never
        # attributed and carry none); ``tenant`` rides the envelope too
        # and labels the server's bounded-cardinality tfs_request_*
        # metrics.  ``last_correlation_id`` is the most recent GATED
        # call's cid — the handle ``attribution()`` looks up.
        self._tenant = tenant
        self.last_correlation_id: Optional[str] = None
        self._timeout_s = (
            timeout_s
            if timeout_s is not None
            else env_opt_float(ENV_CLIENT_TIMEOUT_S)
        )
        self._deadline_ms = deadline_ms
        if reconnect_retries is None:
            reconnect_retries = env_int(
                ENV_CLIENT_RETRIES, DEFAULT_RECONNECT_RETRIES
            )
        self._retries = int(reconnect_retries)
        if busy_retries is None:
            busy_retries = env_int(ENV_CLIENT_BUSY_RETRIES, 0)
        self._busy_retries = int(busy_retries)
        self._busy_cap_ms = env_float(
            ENV_CLIENT_BUSY_CAP_MS, DEFAULT_BUSY_CAP_MS
        )
        self._backoff_s = float(backoff_s)
        self._jitter = float(jitter)
        self._rng = rng
        self._lock = threading.Lock()
        self._next_id = 0
        self._client_id = uuid.uuid4().hex[:12]
        self.session_token: Optional[str] = None
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._wfile = None
        self._closed = False
        with self._lock:
            # the construction handshake honours the client deadline
            # too: a wedged server must not hang __init__ forever when
            # the caller expressed an SLO
            self._connect_locked(
                timeout_s=(
                    float(self._deadline_ms) / 1000.0
                    if self._deadline_ms is not None
                    else None
                )
            )

    # -- connection management (callers hold self._lock) ---------------------

    def _teardown_locked(self) -> None:
        # shutdown BEFORE closing the file objects: a reader blocked in
        # readline holds the buffer lock, so rfile.close() would block
        # behind it — shutdown is a plain syscall that forces that read
        # to return EOF first (this is what lets close() unblock a call
        # stuck on a wedged server instead of deadlocking on it)
        try:
            if self._sock is not None:
                self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        for f in (self._rfile, self._wfile):
            try:
                if f is not None:
                    f.close()
            except OSError:
                pass
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = self._rfile = self._wfile = None

    def _connect_locked(self, timeout_s: Optional[float] = None) -> None:
        """(Re)connect + hello.  ``timeout_s`` bounds the connect AND the
        handshake roundtrip (a deadline-bound call passes its remaining
        budget so reconnects cannot blow past the deadline); afterwards
        the socket reverts to the client's configured timeout."""
        self._teardown_locked()
        effective = self._timeout_s
        if timeout_s is not None and (
            effective is None or timeout_s < effective
        ):
            effective = timeout_s
        sock = socket.create_connection(
            (self._host, self._port), timeout=effective
        )
        self._sock = sock
        self._rfile = sock.makefile("rb")
        self._wfile = sock.makefile("wb")
        # session handshake: create on first connect, reattach after —
        # the server keeps the frame registry alive across the drop
        self._next_id += 1
        params: Dict[str, Any] = {}
        if self.session_token is not None:
            params["session"] = self.session_token
        resp = self._roundtrip_locked(
            {"id": self._next_id, "method": "hello", "params": params}
        )
        if "error" in resp:
            err = resp["error"]
            if (
                err.get("type") == "AttributeError"
                and self.session_token is None
            ):
                # pre-round-11 server: no ``hello`` method.  Degrade to
                # the legacy sessionless mode (no reattach after a drop;
                # only safe methods survive reconnects) instead of
                # refusing to talk — the round-11 envelope keys stay
                # additive, handshake included.
                logger.warning(
                    "bridge server does not speak hello; running "
                    "sessionless (no reattach across reconnects)"
                )
                sock.settimeout(self._timeout_s)
                return
            if err.get("code") == "unknown_session":
                # the session TTL'd out server-side: its frames are gone,
                # so silently starting a fresh session would turn every
                # handle stale — surface it (the token is cleared so a
                # NEW client call can start clean)
                self.session_token = None
            self._teardown_locked()
            _raise_remote(err)
        self.session_token = resp["result"]["session"]
        self.server_replica = resp["result"].get("replica")
        sock.settimeout(self._timeout_s)

    def _failover_locked(self, reason: str, failed: bool) -> bool:
        """Re-point this client at a healthy peer (round 21): tell the
        router what happened to the current address (``failed`` = dead
        connection, else draining/restarted-but-alive), pick the
        rendezvous choice among the OTHER replicas, and drop the
        session token — the reattach is a fresh session on the new
        replica (frames do not follow; durable jobs do, via the
        journal, when call() re-sends their request).  False when no
        router or no other replica is known."""
        if self.router is None:
            return False
        addr = (self._host, self._port)
        try:
            if failed:
                self.router.note_failed(addr)
            else:
                self.router.note_draining(addr)
            nxt = self.router.pick(exclude=addr)
        except Exception:  # noqa: BLE001 — a sick router must not mask
            logger.warning("bridge: fleet router errored", exc_info=True)
            return False
        if nxt is None or tuple(nxt) == addr:
            return False
        self._teardown_locked()
        self._host, self._port = nxt
        self.session_token = None
        self.failovers += 1
        observability.note_fleet_failover()
        logger.warning(
            "bridge: failing over to %s:%d (%s at %s:%d)",
            nxt[0], nxt[1], reason, addr[0], addr[1],
        )
        return True

    def _roundtrip_locked(self, msg: dict, bins: Optional[list] = None):
        write_message(self._wfile, msg, bins)
        try:
            resp, rbins = read_message(self._rfile)
        except ValueError as exc:
            # a ValueError from the READ side is a truncated/corrupt
            # reply line (server died mid-write, connection RST) — a
            # connection failure for retry purposes, unlike
            # write_message's size-cap ValueErrors, which are raised
            # before any bytes hit the socket and stay caller errors
            raise ConnectionError(
                f"corrupt or truncated bridge reply: {exc}"
            ) from exc
        return dict(resp, _bins=rbins)

    # -- plumbing ------------------------------------------------------------

    def call(
        self, method: str, deadline_ms: Optional[float] = None, **params
    ) -> Any:
        """One RPC round trip.  ``deadline_ms`` (or the client default)
        rides the envelope; connection failures reconnect + retry per
        the policy above; structured server errors raise their typed
        :class:`BridgeError` subclass."""
        deadline = (
            deadline_ms if deadline_ms is not None else self._deadline_ms
        )
        # the deadline bounds the CALL, not each attempt: pin the end
        # now and send only the REMAINING budget on every (re)send, so
        # retries cannot silently multiply an SLO-bound caller's wait
        deadline_end = (
            time.monotonic() + float(deadline) / 1000.0
            if deadline is not None
            else None
        )
        safe = method in _SAFE_METHODS
        detector: Optional[resilience.FailureDetector] = None
        # one correlation id per LOGICAL gated call: reconnect retries
        # re-send the same cid (like the idem token), so server-side
        # attribution and trace events string the whole call together.
        # Safe methods are ungated server-side — never attributed — so
        # minting/recording a cid for them would clobber
        # ``last_correlation_id`` with an id the ``attribution`` RPC
        # can never find (e.g. the attribution lookup itself)
        cid = None if safe else observability.new_correlation_id()
        busy_left = 0 if safe else self._busy_retries
        busy_attempt = 0
        # one reroute per known peer: a call may walk the fleet once,
        # but a fully-dead fleet still surfaces promptly
        failover_left = (
            self.router.failover_budget() if self.router is not None else 0
        )
        with self._lock:
            if cid is not None:
                self.last_correlation_id = cid
            self._next_id += 1
            mid = self._next_id
            idem = None if safe else f"{self._client_id}:{mid}"
            while True:
                if self._closed:
                    # close() ran (possibly force-closing under our
                    # feet): never silently reconnect a closed client
                    raise ConnectionError("bridge client is closed")
                remaining_s: Optional[float] = None
                if deadline_end is not None:
                    # checked BEFORE any reconnect work, and threaded
                    # into the connect/handshake as its timeout: the
                    # deadline bounds the whole call, reconnects
                    # included
                    remaining_s = deadline_end - time.monotonic()
                    if remaining_s <= 0:
                        raise DeadlineExceeded(
                            "DeadlineExceeded",
                            f"{method}: deadline exhausted across "
                            f"retries (never re-sent)",
                            code="deadline_exceeded",
                        )
                try:
                    if self._sock is None:
                        self._connect_locked(timeout_s=remaining_s)
                        if self._closed:
                            # close() ran while we were inside the
                            # connect (its force path found no socket to
                            # tear down) — drop the fresh connection
                            # instead of completing a call on a closed
                            # client and leaking it
                            self._teardown_locked()
                            raise ConnectionError(
                                "bridge client is closed"
                            )
                        self._next_id += 1
                        mid = self._next_id  # ids stay monotonic per wire
                    bins: list = []
                    msg: Dict[str, Any] = {
                        "id": mid,
                        "method": method,
                        "params": encode_value(params, bins),
                    }
                    if cid is not None:
                        msg["cid"] = cid
                        if self._tenant is not None:
                            msg["tenant"] = self._tenant
                    if deadline_end is not None:
                        # re-computed AFTER any reconnect work: the
                        # server must be granted only what truly remains
                        remaining_s = deadline_end - time.monotonic()
                        if remaining_s <= 0:
                            raise DeadlineExceeded(
                                "DeadlineExceeded",
                                f"{method}: deadline exhausted during "
                                f"reconnect (never re-sent)",
                                code="deadline_exceeded",
                            )
                        msg["deadline_ms"] = 1e3 * remaining_s
                        # bound the reply read too: a wedged server must
                        # not turn a deadline-bound call into a wait for
                        # the full (or absent) socket timeout; the grace
                        # covers the server's boundary-late structured
                        # reply
                        bound = remaining_s + _read_grace_s(remaining_s)
                        if self._timeout_s is not None:
                            bound = min(self._timeout_s, bound)
                        self._sock.settimeout(bound)
                    if idem is not None:
                        msg["idem"] = idem
                    resp = self._roundtrip_locked(msg, bins)
                    if deadline_end is not None and self._sock is not None:
                        self._sock.settimeout(self._timeout_s)
                except (OSError, ConnectionError, TimeoutError) as exc:
                    # the connection is in an unknown state: tear it
                    # down and resend — safe because every method is
                    # either side-effect-free (_SAFE_METHODS) or
                    # idempotency-tokened (the server dedups completed
                    # outcomes and makes a retry racing its
                    # still-running original WAIT for that outcome)
                    self._teardown_locked()
                    if self._closed:
                        raise ConnectionError(
                            "bridge client is closed"
                        ) from None
                    if self.session_token is None and (
                        not safe or "frame_id" in params
                    ):
                        # legacy sessionless server: no reattach, so a
                        # resent non-safe method could double-execute
                        # and a frame-addressed read (collect/schema)
                        # would hit a fresh empty session and fail with
                        # a misleading unknown-frame-id — surface the
                        # real connection failure instead
                        raise
                    if failover_left > 0 and self._failover_locked(
                        f"{type(exc).__name__}: {exc}", failed=True
                    ):
                        # round 21: a dead connection with a router
                        # configured reroutes NOW instead of burning the
                        # reconnect budget on a corpse; the new replica
                        # gets a fresh detector budget of its own
                        failover_left -= 1
                        detector = None
                        continue
                    if detector is None:
                        detector = resilience.FailureDetector(
                            max_restarts=self._retries,
                            backoff_s=self._backoff_s,
                            jitter=self._jitter,
                            rng=self._rng,
                        )
                    # every exception the tuple above catches IS a
                    # connection-phase failure worth the reconnect
                    # budget — but the detector classifies plain
                    # OSErrors (ENETUNREACH, EHOSTDOWN...) by message
                    # and would surface them with zero retries, so
                    # normalise to a ConnectionError carrying the
                    # original as its cause before metering
                    if not detector.is_transient(exc):
                        wrapped = ConnectionError(
                            f"{type(exc).__name__}: {exc}"
                        )
                        wrapped.__cause__ = exc
                        exc = wrapped
                    delay = detector.on_failure(exc)  # raises when spent
                    observability.note_bridge_retry()
                    logger.warning(
                        "bridge call %s failed (%s: %s); reconnecting "
                        "after %.3fs (retry %d/%d)",
                        method,
                        type(exc).__name__,
                        exc,
                        delay,
                        detector.restarts,
                        self._retries,
                    )
                    time.sleep(delay)
                    continue
                except SessionLost:
                    # the reattach found a restarted (or TTL-reaped)
                    # server: frames are gone either way.  With a
                    # router, reroute the reattach to a peer (round 21)
                    # — the re-sent request runs on a fresh session
                    # there, and a durable ``job_id`` adopts its journal
                    # fence and resumes.  Without one, round-20
                    # semantics stand: surface it.
                    if failover_left > 0 and self._failover_locked(
                        "session lost", failed=False
                    ):
                        failover_left -= 1
                        continue
                    raise
                rbins = resp.pop("_bins")
                if "error" in resp:
                    err = resp["error"]
                    if (
                        err.get("code") == "draining"
                        and failover_left > 0
                        and self._failover_locked(
                            "server draining", failed=False
                        )
                    ):
                        # round 21: Draining is a failover signal when a
                        # router is configured — the drained request was
                        # never executed or cached, so re-sending the
                        # SAME idem token + cid on a peer is still one
                        # logical call
                        failover_left -= 1
                        continue
                    if (
                        err.get("code") == "server_busy"
                        and busy_left > 0
                    ):
                        # honor the server's retry_after_ms hint (round
                        # 16) — capped and decorrelated (round 21: raw
                        # deterministic hints synchronize a fleet's shed
                        # clients into thundering herds): the shed was
                        # never executed or cached, so re-sending the
                        # SAME idem token + cid keeps the retry a
                        # continuation of this logical call.  Never
                        # sleep past the deadline — surfacing the shed
                        # beats converting it into a silent
                        # deadline_exceeded.
                        delay = busy_backoff_s(
                            float(err.get("retry_after_ms", 50)),
                            cap_ms=self._busy_cap_ms,
                            attempt=busy_attempt,
                            rng=self._rng,
                        )
                        busy_attempt += 1
                        if deadline_end is not None and (
                            time.monotonic() + delay >= deadline_end
                        ):
                            _raise_remote(err)
                        busy_left -= 1
                        logger.debug(
                            "bridge call %s shed (server_busy); "
                            "honoring retry_after_ms=%s (%d busy "
                            "retries left)",
                            method,
                            err.get("retry_after_ms"),
                            busy_left,
                        )
                        time.sleep(delay)
                        continue
                    _raise_remote(err)
                return decode_value(resp["result"], rbins)

    def close(self) -> None:
        """End the server session (best effort) and close the socket.

        The ``end_session`` round trip runs under a short socket
        timeout regardless of the client's configured ``timeout_s`` —
        ``close()``/``__exit__`` must never hang on a wedged server
        (teardown is best effort; the session TTL reaps it anyway)."""
        self._closed = True  # call()'s retry loop must never reconnect
        if not self._lock.acquire(timeout=2.0):
            # a stuck call() holds the lock (wedged server, no read
            # timeout): force-close the socket WITHOUT the lock — the
            # blocked read raises in the stuck thread, which sees
            # _closed and surfaces instead of reconnecting.  Skipping
            # end_session is fine; the server's session TTL reaps it.
            self._teardown_locked()
            return
        try:
            self._close_locked()
        finally:
            self._lock.release()

    def _close_locked(self) -> None:
        if self._wfile is not None and self.session_token is not None:
            try:
                self._sock.settimeout(1.0)
                self._next_id += 1
                self._roundtrip_locked(
                    {
                        "id": self._next_id,
                        "method": "end_session",
                        "params": {},
                    }
                )
            except Exception:  # noqa: BLE001 — teardown is best effort
                pass
        self._teardown_locked()
        self.session_token = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- frontend API --------------------------------------------------------

    def ping(self) -> bool:
        return bool(self.call("ping")["pong"])

    def health(self) -> Dict[str, Any]:
        """The server's health snapshot: admission depth, drain state,
        quarantined devices, HBM budget occupancy, and (round 13) the
        gauge snapshot — live/peak host bytes, flight-recorder
        depth/drops (ungated — works on a saturated server)."""
        return self.call("health")

    def metrics(self) -> str:
        """The server's Prometheus text exposition (round 13): counters,
        gauges, and the verb/bridge-method latency histograms with
        p50/p95/p99 — the scrape surface for deployments without the
        ``TFS_METRICS_PORT`` HTTP endpoint (ungated, like ``health``)."""
        return self.call("metrics")["text"]

    def attribution(
        self, correlation_id: Optional[str] = None
    ) -> Dict[str, Any]:
        """Per-request cost attribution (round 15, ungated).  With a
        ``correlation_id`` (e.g. :attr:`last_correlation_id` after a
        verb call) returns that request's ledger snapshot — counters
        delta, blocks/rows per device, per-verb latency, wall time;
        without one returns the server's recent ledgers, newest last."""
        return self.call("attribution", correlation_id=correlation_id)

    def warm(
        self,
        graph: bytes,
        fetches: Sequence[str],
        columns: Mapping[str, Any],
        rows: Optional[Sequence[int]] = None,
        verb: str = "map_rows",
        inputs: Optional[Mapping[str, str]] = None,
        shapes: Optional[Mapping[str, Sequence[int]]] = None,
        trim: bool = False,
        deadline_ms: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Register + AOT-prime a program on the server (round 16):
        the warm pool keeps it resident and ``Executor.warmup`` compiles
        its ``(bucket, device)`` executable grid for the given block row
        counts, so the first real request is a jit-cache hit.
        ``columns`` maps column name -> a small sample array (dtype +
        cell shape are read; values are ignored)."""
        return self.call(
            "warm",
            deadline_ms=deadline_ms,
            graph=graph,
            fetches=list(fetches),
            inputs=dict(inputs or {}),
            shapes=dict(shapes or {}),
            trim=trim,
            verb=verb,
            columns={k: np.asarray(v) for k, v in columns.items()},
            rows=[int(r) for r in (rows or [])],
        )

    def run_pipeline(
        self,
        source: Mapping[str, Any],
        stages: Sequence[Mapping[str, Any]],
        sink: Optional[Mapping[str, Any]] = None,
        deadline_ms: Optional[float] = None,
        job_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Execute a whole source -> map -> join -> aggregate -> sink
        streaming pipeline server-side as ONE gated request (round 18).
        ``source``/``stages``/``sink`` follow the
        ``relational/pipeline.py`` spec grammar (``graph`` values are
        GraphDef bytes; join stages reference registered frames by
        ``build_frame_id``).  The reply carries the result frame's id +
        schema (aggregate / collect sinks), the parquet sink summary,
        and one ledger snapshot PER WINDOW — per-window attribution
        that sums to this request's ``attribution()`` ledger (past 512
        windows the tail folds into one synthetic ``folded_windows``
        entry, so the sum stays exact).  Path-based parquet
        sources/sinks touch the SERVER's filesystem and are refused
        unless under a ``TFS_BRIDGE_PIPELINE_PATHS`` root; registered
        frames (``frame_id``) always work.  The request's
        ``deadline_ms`` cancels the pipeline at the next window
        boundary; complete windows (and a parquet sink's finalized
        file) survive.  ``job_id`` makes the pipeline DURABLE: the
        server journals every window boundary, a re-issued spec with
        the same id resumes from the last completed window (after a
        server restart too — catch :class:`SessionLost`, reattach,
        re-upload frames, re-issue), and a completed job replays its
        journaled result exactly once."""
        r = self.call(
            "pipeline",
            deadline_ms=deadline_ms,
            source=dict(source),
            stages=[dict(s) for s in stages],
            sink=dict(sink) if sink else None,
            job_id=job_id,
        )
        if "frame_id" in r:
            r["frame"] = RemoteFrame(self, r["frame_id"], r["schema"])
        return r

    def decode(
        self,
        prompt: Sequence[int],
        max_new: int = 16,
        speculative: bool = False,
        gamma: int = 4,
        stop_token: Optional[int] = None,
        deadline_ms: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Stream up to ``max_new`` greedy tokens continuing ``prompt``
        through the server's paged decode scheduler (round 22).  The
        request joins the RUNNING slot batch at the next step boundary
        and retires the moment its stream finishes (``max_new`` reached
        or ``stop_token`` emitted), freeing its KV pages immediately;
        ``deadline_ms`` cancels at a step boundary.  Per-request
        attribution applies: generated tokens bill this client's tenant.
        ``speculative=True`` opts into the draft/verify path (needs a
        draft model server-side; verified bit-exactly by the target
        model).  Page-pool or slot exhaustion raises
        :class:`ServerBusy` whose ``retry_after_ms`` says when to come
        back — admission is refused up front, never OOMed mid-stream.
        Returns ``{"tokens": [...], "generated": n, "speculative":
        bool}``."""
        return self.call(
            "decode",
            deadline_ms=deadline_ms,
            prompt=[int(t) for t in prompt],
            max_new=int(max_new),
            speculative=bool(speculative),
            gamma=int(gamma),
            stop_token=None if stop_token is None else int(stop_token),
        )

    def job_status(self, job_id: str) -> Dict[str, Any]:
        """Status of a durable job (round 20, ungated): whether the
        server's journal holds it, its completed-window boundary, and
        whether its owner is alive (``running``) or dead
        (``interrupted`` — resumable by re-issuing the request with the
        same ``job_id``).  ``complete`` jobs return their journaled
        result on resume without executing anything."""
        return self.call("job_status", job_id=job_id)

    def create_frame(
        self,
        columns: Mapping[str, Any],
        num_blocks: int = 1,
        deadline_ms: Optional[float] = None,
    ) -> "RemoteFrame":
        r = self.call(
            "create_frame",
            deadline_ms=deadline_ms,
            columns={k: np.asarray(v) if not isinstance(v, list) else v
                     for k, v in columns.items()},
            num_blocks=num_blocks,
        )
        return RemoteFrame(self, r["frame_id"], r["schema"])


class RemoteFrame:
    """Handle to a frame living in the bridge server.

    Every verb takes an optional ``deadline_ms``; a verb that exceeds it
    raises :class:`DeadlineExceeded` and leaves this frame (and the
    session) fully usable — re-running the same verb afterwards
    produces the undisturbed result."""

    def __init__(self, client: BridgeClient, frame_id: int, schema):
        self._c = client
        self.frame_id = frame_id
        self.schema = schema

    def analyze(self, deadline_ms: Optional[float] = None) -> "RemoteFrame":
        self.schema = self._c.call(
            "analyze", frame_id=self.frame_id, deadline_ms=deadline_ms
        )["schema"]
        return self

    def _df_verb(
        self, verb: str, graph: bytes, deadline_ms=None, **kw
    ) -> "RemoteFrame":
        r = self._c.call(
            verb,
            frame_id=self.frame_id,
            graph=graph,
            deadline_ms=deadline_ms,
            **kw,
        )
        return RemoteFrame(self._c, r["frame_id"], r["schema"])

    def map_blocks(
        self,
        graph: bytes,
        fetches: Sequence[str],
        inputs: Optional[Mapping[str, str]] = None,
        shapes: Optional[Mapping[str, Sequence[int]]] = None,
        trim: bool = False,
        deadline_ms: Optional[float] = None,
    ) -> "RemoteFrame":
        return self._df_verb(
            "map_blocks", graph, fetches=list(fetches),
            inputs=dict(inputs or {}), shapes=dict(shapes or {}), trim=trim,
            deadline_ms=deadline_ms,
        )

    def map_rows(
        self,
        graph: bytes,
        fetches: Sequence[str],
        inputs: Optional[Mapping[str, str]] = None,
        shapes: Optional[Mapping[str, Sequence[int]]] = None,
        deadline_ms: Optional[float] = None,
    ) -> "RemoteFrame":
        return self._df_verb(
            "map_rows", graph, fetches=list(fetches),
            inputs=dict(inputs or {}), shapes=dict(shapes or {}),
            deadline_ms=deadline_ms,
        )

    def aggregate(
        self,
        keys: Sequence[str],
        graph: bytes,
        fetches: Sequence[str],
        deadline_ms: Optional[float] = None,
    ) -> "RemoteFrame":
        return self._df_verb(
            "aggregate", graph, keys=list(keys), fetches=list(fetches),
            deadline_ms=deadline_ms,
        )

    def check(
        self,
        verb: str,
        graph: Optional[bytes] = None,
        fetches: Optional[Sequence[str]] = None,
        inputs: Optional[Mapping[str, str]] = None,
        shapes: Optional[Mapping[str, Sequence[int]]] = None,
        keys: Optional[Sequence[str]] = None,
        trim: bool = False,
        right: Optional["RemoteFrame"] = None,
        how: str = "inner",
        deadline_ms: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """Pre-dispatch contract verification (round 17): statically
        validate ``graph`` against this frame for ``verb`` and return
        the ``TFSxxx`` diagnostics — UNGATED server-side, so a tenant
        can validate while the server is saturated, before burning an
        admission slot (and a retry budget) on a request the verb would
        refuse.  Round 18: ``verb`` may be ``join``/``shuffle`` (no
        graph; ``keys`` names the key column, ``right`` the build-side
        handle), returning the relational ``TFS14x`` contracts."""
        r = self._c.call(
            "check",
            frame_id=self.frame_id,
            verb=verb,
            graph=graph,
            fetches=list(fetches or []),
            inputs=dict(inputs or {}),
            shapes=dict(shapes or {}),
            keys=list(keys or []),
            trim=trim,
            right_frame_id=right.frame_id if right is not None else None,
            how=how,
            deadline_ms=deadline_ms,
        )
        return r["diagnostics"]

    def _row_verb(
        self, verb: str, graph: bytes, fetches, inputs=None, shapes=None,
        deadline_ms=None,
    ) -> Dict[str, Any]:
        # inputs=/shapes= ride through like the df verbs (the server's
        # _builder always accepted them; the client used to drop them —
        # round-11 satellite fix), so remote reduces can rename
        # placeholders and hint shapes too
        r = self._c.call(
            verb,
            frame_id=self.frame_id,
            graph=graph,
            fetches=list(fetches),
            inputs=dict(inputs or {}),
            shapes=dict(shapes or {}),
            deadline_ms=deadline_ms,
        )
        return r["row"]

    def reduce_blocks(
        self,
        graph: bytes,
        fetches: Sequence[str],
        inputs: Optional[Mapping[str, str]] = None,
        shapes: Optional[Mapping[str, Sequence[int]]] = None,
        deadline_ms: Optional[float] = None,
    ):
        return self._row_verb(
            "reduce_blocks", graph, fetches, inputs, shapes, deadline_ms
        )

    def reduce_rows(
        self,
        graph: bytes,
        fetches: Sequence[str],
        inputs: Optional[Mapping[str, str]] = None,
        shapes: Optional[Mapping[str, Sequence[int]]] = None,
        deadline_ms: Optional[float] = None,
    ):
        return self._row_verb(
            "reduce_rows", graph, fetches, inputs, shapes, deadline_ms
        )

    def collect(
        self,
        columns: Optional[List[str]] = None,
        deadline_ms: Optional[float] = None,
    ) -> Dict[str, Any]:
        return self._c.call(
            "collect",
            frame_id=self.frame_id,
            columns=columns,
            deadline_ms=deadline_ms,
        )["columns"]

    def release(self) -> None:
        self._c.call("release", frame_id=self.frame_id)
