"""External-process front-end bridge (the L2 interop layer).

The reference's L2 is a Py4J socket protocol: the Python front-end drives a
JVM engine through ``PythonOpBuilder`` accessors
(``/root/reference/src/main/scala/org/tensorframes/impl/PythonInterface.scala:46-170``),
shipping programs as serialized GraphDef bytes (via temp files,
``core.py:38-49``).  Here the roles invert — the engine IS Python/JAX — but
the seam survives for the same reason: an external front-end (a Spark
driver, a JVM service, another language) needs a wire protocol to hand
frames and tensor programs to the TPU engine.

* ``serve`` / ``BridgeServer`` — localhost TCP server executing the verb
  protocol against in-process TensorFrames (frames live server-side in a
  registry; only programs, schemas, and requested results cross the wire).
* ``BridgeClient`` — the reference-shaped client: ``create_frame``,
  ``analyze``, builder-style verb calls taking **GraphDef bytes** (the same
  transport the reference uses), ``collect``.

Transport: newline-delimited JSON with base64 tensors — deliberately
dependency-free and implementable from any language in an afternoon, like
the Py4J text protocol it replaces.

Round 11 makes the seam serving-grade: per-request deadlines cancelled
cooperatively at block boundaries, bounded admission with ``ServerBusy``
shedding, token-addressed sessions with idempotent retry after dropped
replies, graceful drain, and an ungated ``health`` RPC (see
``docs/RESILIENCE.md``).

Round 16 adds the multi-tenant THROUGHPUT layer (``docs/SERVING.md``):
request coalescing into bucket-canonical micro-batches over a warm
program pool (``Coalescer`` / ``WarmPool``), SLO-aware fair-share
admission (``SloScheduler``), and continuous decode batching
(``ContinuousBatcher``).

Round 21 scales the seam OUT: ``fleet`` runs N replicas behind a
rendezvous-hashing ``FleetRouter`` (health-polled, flap-quarantining),
``BridgeClient`` grows router-driven failover (``Draining`` /
connection death / ``SessionLost`` reroute to a healthy peer; durable
jobs migrate via the round-20 journal), and ``BridgeFleet`` provides
the kill/drain/restart/rolling-restart levers plus the shared
compile-cache topology that makes a rejoining replica warm
(``docs/SERVING.md`` fleet section, ``docs/RESILIENCE.md``).
"""

from .client import (
    BridgeClient,
    BridgeError,
    Cancelled,
    DeadlineExceeded,
    Draining,
    RemoteFrame,
    ServerBusy,
    SessionLost,
    busy_backoff_s,
)
from .fleet import BridgeFleet, FleetClient, FleetRouter
from .coalescer import (
    Coalescer,
    ContinuousBatcher,
    SloScheduler,
    WarmPool,
    WarmSpec,
)
from .server import BridgeServer, serve

__all__ = [
    "BridgeClient",
    "BridgeError",
    "BridgeFleet",
    "BridgeServer",
    "Cancelled",
    "Coalescer",
    "ContinuousBatcher",
    "DeadlineExceeded",
    "Draining",
    "FleetClient",
    "FleetRouter",
    "RemoteFrame",
    "ServerBusy",
    "SessionLost",
    "SloScheduler",
    "WarmPool",
    "WarmSpec",
    "busy_backoff_s",
    "serve",
]
