"""Persistent XLA executable cache wiring (``TFS_COMPILE_CACHE``).

The in-process jit cache (``Program.jitted`` and friends) amortizes
compiles within one process, and shape-canonical bucketing
(``ops/bucketing.py``) keeps the signature count O(log shape) — but
nothing survived the process: every cold start of a serving replica or a
bench run paid full XLA compile for every program (docs/PERF.md's
1.4-18 rows/s cold-start numbers).  jax ships a content-addressed
persistent compilation cache keyed by (HLO, compile options, backend);
this module is the one place it gets wired:

* ``configure(path=None)`` — point jax's compilation cache at ``path``
  (default: the ``TFS_COMPILE_CACHE`` env var; no-op when neither is
  set).  The min-compile-time floor is dropped to 0 so the small block
  programs the verbs build are persisted too, not just multi-second
  model compiles.  Idempotent; called automatically at package import
  when ``TFS_COMPILE_CACHE`` is set, so every entry point honors the
  knob.
* hit/miss accounting rides :mod:`tensorframes_tpu.observability`'s
  jax-monitoring listeners (``counters()["persistent_cache_hits"]``),
  which is how the bench proves a second process skipped XLA instead of
  asserting it.

With the cache configured, ``Program.aot_compile`` (the
``lower().compile()`` path) in a fresh process deserializes the
executable from disk — compile cost per (program, bucket signature)
becomes O(1) across process restarts, not per run.
"""

from __future__ import annotations

import os
from . import envutil
from typing import Optional

ENV_VAR = "TFS_COMPILE_CACHE"

_configured_dir: Optional[str] = None


def configure(path: Optional[str] = None) -> bool:
    """Enable jax's persistent compilation cache at ``path`` (or
    ``$TFS_COMPILE_CACHE``).  Returns True when a cache is active.

    Safe to call repeatedly; re-pointing at a new path reconfigures."""
    global _configured_dir
    path = path or envutil.env_raw(ENV_VAR) or None
    if not path:
        return _configured_dir is not None
    path = os.path.abspath(path)
    if _configured_dir == path:
        return True
    import jax

    from . import observability

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # default floor (1s) would skip every small verb program — the exact
    # executables whose per-restart recompiles this cache exists to kill
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # flag absent on this jax: keep its default
    # jax latches cache-enabled-ness at the FIRST compile of the process
    # (compilation_cache.is_cache_used's one-shot check): if anything
    # compiled before configure(), the latch reads "disabled" forever.
    # reset_cache() clears the latch (and the in-memory cache object) so
    # a mid-process configure takes effect.
    _reset_jax_cache()
    observability.install_counters()
    _configured_dir = path
    return True


def _reset_jax_cache() -> None:
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()
    except Exception:
        pass  # older jax: no latch to clear


def cache_dir() -> Optional[str]:
    """The active persistent cache directory, or None."""
    return _configured_dir


def deconfigure() -> None:
    """Turn the persistent cache back off (tests)."""
    global _configured_dir
    if _configured_dir is None:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    _reset_jax_cache()
    _configured_dir = None
