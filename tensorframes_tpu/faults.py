"""Deterministic fault injection for the block execution stack.

The reference tests its failure story with Spark's own chaos levers —
kill an executor, let task retry replay the partition from lineage
(SURVEY.md §5).  A TPU host has no such lever: XLA faults (`UNAVAILABLE`
preemptions, `RESOURCE_EXHAUSTED` OOMs) come from real hardware state
that a test cannot provoke on demand.  This module supplies the lever:
``TFS_FAULT_INJECT`` describes an *exact, reproducible* failure schedule
and the engine's dispatch boundary (``ops/fault_tolerance.py``) consults
it before every block (and split sub-range) dispatch.

Spec grammar — ``;``-separated specs, each ``kind:key=value:...``::

    TFS_FAULT_INJECT="transient:block=3:attempt=0"
    TFS_FAULT_INJECT="oom:device=1:rate=0.25:seed=7"
    TFS_FAULT_INJECT="delay:ms=50;transient:rate=0.25:seed=7"

Kinds:

* ``transient`` — raise :class:`InjectedTransient` (message opens with
  ``UNAVAILABLE:`` so ``resilience.FailureDetector`` classifies it
  transient, exactly like a real preemption);
* ``oom`` — raise :class:`InjectedOOM` (opens with
  ``RESOURCE_EXHAUSTED:``, the real XLA OOM status — drives the engine's
  block-splitting degradation, not the retry loop);
* ``delay`` — sleep ``ms`` milliseconds at the dispatch boundary
  (staging-skew chaos without failing anything).

Selectors (all optional; a spec fires when every given selector
matches):

* ``block=N`` — only block index N;
* ``device=N`` — only dispatches bound for pool device index N (the
  serial path dispatches as device 0);
* ``attempt=N`` — only retry attempt N of a block dispatch (``0`` = the
  first try, so retry 1 succeeds).  Attempt-selected specs never fire on
  OOM-split sub-dispatches — those are recovery work, not fresh
  attempts;
* ``rate=F`` + ``seed=S`` — fire with probability F, decided by a
  *counter-free deterministic draw* hashed from ``(seed, block,
  attempt)``: the same spec over the same frame produces the same
  schedule in every process, which is what lets the chaos tests assert
  bit-identity instead of flakiness;
* ``minrows=N`` — only dispatches covering >= N rows (the way to make an
  injected OOM *stop* firing once the engine has split the block small
  enough).

Injection is wired through ONE choke point (:func:`maybe_inject`), off
by default (unset/empty env), and counted in
``observability.counters()['faults_injected']`` so a chaos bench record
can prove how much adversity it actually ran under.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import time
from typing import List, Optional, Tuple

from . import observability

logger = logging.getLogger("tensorframes_tpu.faults")

ENV_VAR = "TFS_FAULT_INJECT"

_KINDS = ("transient", "oom", "delay")
_INT_KEYS = ("block", "device", "attempt", "minrows", "seed")
_FLOAT_KEYS = ("rate", "ms")


class InjectedTransient(RuntimeError):
    """An injected runtime-infrastructure failure (classifies transient)."""


class InjectedOOM(RuntimeError):
    """An injected device out-of-memory (classifies RESOURCE_EXHAUSTED)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str
    block: Optional[int] = None
    device: Optional[int] = None
    attempt: Optional[int] = None
    minrows: Optional[int] = None
    rate: Optional[float] = None
    seed: int = 0
    ms: float = 0.0
    index: int = 0  # position in the spec list (decorrelates rate draws)

    def matches(
        self,
        block: int,
        attempt: int,
        device: Optional[int],
        n_rows: Optional[int],
        site: str,
    ) -> bool:
        if self.block is not None and self.block != block:
            return False
        if self.device is not None and self.device != device:
            return False
        if self.attempt is not None:
            # attempt selectors describe the RETRY schedule of a block
            # dispatch; split sub-dispatches are recovery, not attempts
            if site != "dispatch" or self.attempt != attempt:
                return False
        if self.minrows is not None and (
            n_rows is None or n_rows < self.minrows
        ):
            return False
        if self.rate is not None:
            draw = random.Random(
                f"{self.seed}:{self.index}:{self.kind}:{block}:{attempt}"
            ).random()
            if draw >= self.rate:
                return False
        return True


_warned: set = set()


def _warn_once(raw: str, why: str) -> None:
    if raw not in _warned:
        _warned.add(raw)
        logger.warning(
            "%s spec %r ignored: %s (grammar: kind:key=value:... with "
            "kind in %s)",
            ENV_VAR,
            raw,
            why,
            "/".join(_KINDS),
        )


def _parse_one(raw: str, index: int) -> Optional[FaultSpec]:
    parts = [p for p in raw.strip().split(":") if p]
    if not parts:
        return None
    kind = parts[0].strip().lower()
    if kind not in _KINDS:
        _warn_once(raw, f"unknown kind {kind!r}")
        return None
    fields = {"kind": kind, "index": index}
    for part in parts[1:]:
        if "=" not in part:
            _warn_once(raw, f"selector {part!r} is not key=value")
            return None
        key, _, val = part.partition("=")
        key = key.strip().lower()
        try:
            if key in _INT_KEYS:
                fields[key] = int(val)
            elif key in _FLOAT_KEYS:
                fields[key] = float(val)
            else:
                _warn_once(raw, f"unknown selector {key!r}")
                return None
        except ValueError:
            _warn_once(raw, f"selector {key}={val!r} is not numeric")
            return None
    return FaultSpec(**fields)


_cache: Tuple[str, List[FaultSpec]] = ("", [])


def specs() -> List[FaultSpec]:
    """The parsed ``TFS_FAULT_INJECT`` plan (cached per env value; read
    per call so tests and bench legs can flip it mid-process)."""
    global _cache
    raw = os.environ.get(ENV_VAR, "").strip()
    if raw == _cache[0]:
        return _cache[1]
    parsed = []
    if raw:
        for i, part in enumerate(raw.split(";")):
            spec = _parse_one(part, i)
            if spec is not None:
                parsed.append(spec)
    _cache = (raw, parsed)
    return parsed


def active() -> bool:
    """Whether any injection spec is live."""
    return bool(specs())


def maybe_inject(
    block: int,
    attempt: int,
    device: Optional[int] = None,
    n_rows: Optional[int] = None,
    site: str = "dispatch",
) -> None:
    """The dispatch-boundary hook: sleep for every matching ``delay``
    spec, then raise for the first matching ``transient``/``oom`` spec.
    A no-op (one truthiness check) when ``TFS_FAULT_INJECT`` is unset."""
    plan = specs()
    if not plan:
        return
    for spec in plan:
        if not spec.matches(block, attempt, device, n_rows, site):
            continue
        if spec.kind == "delay":
            time.sleep(spec.ms / 1000.0)
            continue
        observability.note_fault_injected()
        where = (
            f"block={block} attempt={attempt} device={device} "
            f"rows={n_rows} site={site}"
        )
        if spec.kind == "transient":
            raise InjectedTransient(
                f"UNAVAILABLE: injected transient fault ({where})"
            )
        raise InjectedOOM(
            f"RESOURCE_EXHAUSTED: injected out-of-memory ({where})"
        )


_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory")


def is_oom(exc: BaseException, _depth: int = 0) -> bool:
    """Whether ``exc`` (or its ``__cause__`` chain) is a device
    out-of-memory — real XLA ``RESOURCE_EXHAUSTED`` statuses and
    :class:`InjectedOOM` alike."""
    text = str(exc).lower()
    if any(m in text for m in _OOM_MARKERS):
        return True
    if _depth < 4 and exc.__cause__ is not None:
        return is_oom(exc.__cause__, _depth + 1)
    return False
