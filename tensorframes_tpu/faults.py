"""Deterministic fault injection for the block execution stack.

The reference tests its failure story with Spark's own chaos levers —
kill an executor, let task retry replay the partition from lineage
(SURVEY.md §5).  A TPU host has no such lever: XLA faults (`UNAVAILABLE`
preemptions, `RESOURCE_EXHAUSTED` OOMs) come from real hardware state
that a test cannot provoke on demand.  This module supplies the lever:
``TFS_FAULT_INJECT`` describes an *exact, reproducible* failure schedule
and the engine's dispatch boundary (``ops/fault_tolerance.py``) consults
it before every block (and split sub-range) dispatch.

Spec grammar — ``;``-separated specs, each ``kind:key=value:...``::

    TFS_FAULT_INJECT="transient:block=3:attempt=0"
    TFS_FAULT_INJECT="oom:device=1:rate=0.25:seed=7"
    TFS_FAULT_INJECT="delay:ms=50;transient:rate=0.25:seed=7"

Kinds:

* ``transient`` — raise :class:`InjectedTransient` (message opens with
  ``UNAVAILABLE:`` so ``resilience.FailureDetector`` classifies it
  transient, exactly like a real preemption);
* ``oom`` — raise :class:`InjectedOOM` (opens with
  ``RESOURCE_EXHAUSTED:``, the real XLA OOM status — drives the engine's
  block-splitting degradation, not the retry loop);
* ``delay`` — sleep ``ms`` milliseconds at the dispatch boundary
  (staging-skew chaos without failing anything).

Bridge kinds (round 11, consumed by ``bridge/server.py`` via
:func:`maybe_inject_bridge`, selectors ``method=NAME``/``call=N`` plus
``rate``/``seed``): ``bridge_stall:ms=`` (sleep inside the request's
cancel scope before execution — a wedged verb), ``bridge_delay:ms=``
(sleep before writing the reply — a slow link), ``bridge_drop``
(execute, then sever the connection without replying — the dropped-reply
case the idempotent client retry exists for)::

    TFS_FAULT_INJECT="bridge_drop:method=map_blocks:call=0"

Fleet kind (round 21, consumed by ``bridge/server.py`` like the other
bridge kinds): ``replica_kill:ms=`` SIGKILLs the SERVER process ``ms``
milliseconds after the matched request starts dispatching — the
replica-death lever the fleet chaos harness (``bridge/fleet.py``,
``tests/test_fleet.py``) drives: the request is mid-execution when the
process dies, so the client sees a severed connection, reroutes to a
healthy replica, and the durable job resumes from its last journal
boundary.  ``ms=0`` (the default) kills before execution begins.
Selectors are the bridge ones (``method=``/``call=``/``rate``/``seed``)::

    TFS_FAULT_INJECT="replica_kill:method=pipeline:call=0:ms=400"

Bridge injection targets SESSION-BOUND RPC methods (the gated verbs plus
ping/schema/release); the connection control plane — ``hello``,
``health``, ``end_session`` — dispatches before the injection hook and
cannot be targeted (``method=hello`` parses but never fires: those paths
must stay reliable so chaos tests can still attach, observe, and clean
up around the faults they inject).

Boundary kind (round 20, consumed by ``recovery/journal.py`` via
:func:`maybe_kill_boundary`): ``proc_kill`` SIGKILLs the process at a
durable job's journal boundary — selectors ``window=N`` (boundary
index) and ``phase=pre|mid|post`` (before the state write / between
state write and manifest replace / after the manifest replace; default
``pre``), plus ``rate``/``seed``.  This is the process-death lever the
crash-resume harness (``tests/test_recovery.py``, the ``recovery`` CI
tier) drives from a parent process::

    TFS_FAULT_INJECT="proc_kill:window=2:phase=mid"

Selectors (all optional; a spec fires when every given selector
matches):

* ``block=N`` — only block index N;
* ``device=N`` — only dispatches bound for pool device index N (the
  serial path dispatches as device 0);
* ``attempt=N`` — only retry attempt N of a block dispatch (``0`` = the
  first try, so retry 1 succeeds).  Attempt-selected specs never fire on
  OOM-split sub-dispatches — those are recovery work, not fresh
  attempts;
* ``rate=F`` + ``seed=S`` — fire with probability F, decided by a
  *counter-free deterministic draw* hashed from ``(seed, block,
  attempt)``: the same spec over the same frame produces the same
  schedule in every process, which is what lets the chaos tests assert
  bit-identity instead of flakiness;
* ``minrows=N`` — only dispatches covering >= N rows (the way to make an
  injected OOM *stop* firing once the engine has split the block small
  enough).

Injection is wired through ONE choke point (:func:`maybe_inject`), off
by default (unset/empty env), and counted in
``observability.counters()['faults_injected']`` so a chaos bench record
can prove how much adversity it actually ran under.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import time
from typing import List, Optional, Tuple

from . import observability
from . import envutil

logger = logging.getLogger("tensorframes_tpu.faults")

ENV_VAR = "TFS_FAULT_INJECT"

# engine kinds fire at the block-dispatch boundary; bridge kinds fire in
# the bridge server's request path (round 11): ``bridge_stall`` sleeps
# INSIDE the verb's cancel scope before execution (a wedged program —
# the sleep is sliced so cooperative deadlines still fire),
# ``bridge_delay`` sleeps after execution before the reply is written
# (a slow link), ``bridge_drop`` executes the request then severs the
# connection without replying (the dropped-reply case idempotent retry
# exists for).  Selectors ``method=NAME`` and ``call=N`` (the N-th
# invocation of that method in the session, 0-based) target them.
_ENGINE_KINDS = ("transient", "oom", "delay")
# ``replica_kill`` (round 21) is bridge-SCOPED (method=/call= selectors,
# fired from the server's per-request injection hook) but its action is
# the boundary kind's: SIGKILL this process.  The distinction from
# ``proc_kill``: it targets a REQUEST (the replica dies mid-job while
# serving it), not a journal boundary index — the death the fleet's
# journal-backed migration exists to survive.
_BRIDGE_KINDS = ("bridge_stall", "bridge_delay", "bridge_drop",
                 "replica_kill")
# boundary kinds (round 20) fire at the durable-job journal's
# window/epoch boundary choke point (``recovery/journal.py``
# ``JournalWriter.append``): ``proc_kill`` SIGKILLs THIS process — the
# process-death lever the crash-resume harness drives, the analog of
# Spark's kill-an-executor chaos (SURVEY.md §5).  Selectors:
# ``window=N`` (the boundary index), ``phase=pre|mid|post`` (before the
# state write / after the state write but before the manifest replace /
# after the manifest replace — the three distinct crash cells of the
# RESILIENCE.md process-death table; default ``pre``), plus
# ``rate``/``seed`` with the same counter-free deterministic draw.
_BOUNDARY_KINDS = ("proc_kill",)
_KINDS = _ENGINE_KINDS + _BRIDGE_KINDS + _BOUNDARY_KINDS
_INT_KEYS = ("block", "device", "attempt", "minrows", "seed", "call",
             "window")
_FLOAT_KEYS = ("rate", "ms")
_STR_KEYS = ("method", "phase")


class InjectedTransient(RuntimeError):
    """An injected runtime-infrastructure failure (classifies transient)."""


class InjectedOOM(RuntimeError):
    """An injected device out-of-memory (classifies RESOURCE_EXHAUSTED)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str
    block: Optional[int] = None
    device: Optional[int] = None
    attempt: Optional[int] = None
    minrows: Optional[int] = None
    rate: Optional[float] = None
    seed: int = 0
    ms: float = 0.0
    index: int = 0  # position in the spec list (decorrelates rate draws)
    method: Optional[str] = None  # bridge kinds: RPC method selector
    call: Optional[int] = None  # bridge kinds: per-session call index
    window: Optional[int] = None  # boundary kinds: journal boundary index
    phase: Optional[str] = None  # boundary kinds: pre|mid|post (default pre)

    def matches(
        self,
        block: int,
        attempt: int,
        device: Optional[int],
        n_rows: Optional[int],
        site: str,
    ) -> bool:
        if self.block is not None and self.block != block:
            return False
        if self.device is not None and self.device != device:
            return False
        if self.attempt is not None:
            # attempt selectors describe the RETRY schedule of a block
            # dispatch; split sub-dispatches are recovery, not attempts
            if site != "dispatch" or self.attempt != attempt:
                return False
        if self.minrows is not None and (
            n_rows is None or n_rows < self.minrows
        ):
            return False
        if self.rate is not None:
            draw = random.Random(
                f"{self.seed}:{self.index}:{self.kind}:{block}:{attempt}"
            ).random()
            if draw >= self.rate:
                return False
        return True

    def matches_boundary(self, window: int, phase: str) -> bool:
        """Whether this (boundary-kind) spec fires at journal boundary
        ``window`` in crash cell ``phase``.  An unset ``phase`` selector
        means ``pre`` (the kill lands before any durability action, so
        the whole window re-runs on resume — the default cell the
        harness sweeps)."""
        if self.window is not None and self.window != window:
            return False
        if (self.phase or "pre") != phase:
            return False
        if self.rate is not None:
            draw = random.Random(
                f"{self.seed}:{self.index}:{self.kind}:{window}"
            ).random()
            if draw >= self.rate:
                return False
        return True

    def matches_bridge(self, method: str, call: int) -> bool:
        """Whether this (bridge-kind) spec fires for the ``call``-th
        invocation of ``method`` in a bridge session.  Rate draws hash
        from ``(seed, index, kind, method, call)`` — the same counter-
        free determinism the dispatch-boundary draws use."""
        if self.method is not None and self.method != method:
            return False
        if self.call is not None and self.call != call:
            return False
        if self.rate is not None:
            draw = random.Random(
                f"{self.seed}:{self.index}:{self.kind}:{method}:{call}"
            ).random()
            if draw >= self.rate:
                return False
        return True


_warned: set = set()


def _warn_once(raw: str, why: str) -> None:
    if raw not in _warned:
        _warned.add(raw)
        logger.warning(
            "%s spec %r ignored: %s (grammar: kind:key=value:... with "
            "kind in %s)",
            ENV_VAR,
            raw,
            why,
            "/".join(_KINDS),
        )


def _parse_one(raw: str, index: int) -> Optional[FaultSpec]:
    parts = [p for p in raw.strip().split(":") if p]
    if not parts:
        return None
    kind = parts[0].strip().lower()
    if kind not in _KINDS:
        _warn_once(raw, f"unknown kind {kind!r}")
        return None
    fields = {"kind": kind, "index": index}
    for part in parts[1:]:
        if "=" not in part:
            _warn_once(raw, f"selector {part!r} is not key=value")
            return None
        key, _, val = part.partition("=")
        key = key.strip().lower()
        try:
            if key in _INT_KEYS:
                fields[key] = int(val)
            elif key in _FLOAT_KEYS:
                fields[key] = float(val)
            elif key in _STR_KEYS:
                fields[key] = val.strip()
            else:
                _warn_once(raw, f"unknown selector {key!r}")
                return None
        except ValueError:
            _warn_once(raw, f"selector {key}={val!r} is not numeric")
            return None
    # selectors are kind-scoped: an engine-kind spec with method=/call=
    # (or a bridge-kind spec with block=/device=/attempt=/minrows=, or
    # either with window=/phase=) would PARSE but never be consulted by
    # the matching side — firing unscoped process-wide instead of where
    # the selector pointed.  Warn-and-drop, like every other malformed
    # spec.
    _SCOPED = {
        "engine": ("block", "device", "attempt", "minrows"),
        "bridge": ("method", "call"),
        "boundary": ("window", "phase"),
    }
    scope = (
        "engine"
        if kind in _ENGINE_KINDS
        else ("bridge" if kind in _BRIDGE_KINDS else "boundary")
    )
    for other, keys in _SCOPED.items():
        if other == scope:
            continue
        bad = [k for k in keys if k in fields]
        if bad:
            _warn_once(
                raw,
                f"selector(s) {bad} only apply to {other} kinds, not "
                f"{kind!r}",
            )
            return None
    if fields.get("phase") not in (None, "pre", "mid", "post"):
        _warn_once(raw, f"phase={fields['phase']!r} is not pre/mid/post")
        return None
    return FaultSpec(**fields)


_cache: Tuple[str, List[FaultSpec]] = ("", [])


def specs() -> List[FaultSpec]:
    """The parsed ``TFS_FAULT_INJECT`` plan (cached per env value; read
    per call so tests and bench legs can flip it mid-process)."""
    global _cache
    raw = envutil.env_raw(ENV_VAR)
    if raw == _cache[0]:
        return _cache[1]
    parsed = []
    if raw:
        for i, part in enumerate(raw.split(";")):
            spec = _parse_one(part, i)
            if spec is not None:
                parsed.append(spec)
    _cache = (raw, parsed)
    return parsed


def active() -> bool:
    """Whether any ENGINE-level injection spec is live (gates the
    dispatch-boundary fault layer; bridge-only specs must not flip the
    engine onto its retry-session path — that would perturb the trace
    fences of a request that only wanted bridge chaos)."""
    return any(s.kind in _ENGINE_KINDS for s in specs())


def bridge_active() -> bool:
    """Whether any bridge-level injection spec is live."""
    return any(s.kind in _BRIDGE_KINDS for s in specs())


def boundary_active() -> bool:
    """Whether any journal-boundary injection spec is live."""
    return any(s.kind in _BOUNDARY_KINDS for s in specs())


def maybe_kill_boundary(window: int, phase: str) -> None:
    """The journal-boundary hook (``recovery/journal.py``): SIGKILL this
    process for the first matching ``proc_kill`` spec — no cleanup, no
    atexit, no flushed buffers, exactly the death the crash-resume
    contract must survive.  A no-op (one truthiness check) when
    ``TFS_FAULT_INJECT`` is unset."""
    plan = specs()
    if not plan:
        return
    for spec in plan:
        if spec.kind not in _BOUNDARY_KINDS:
            continue
        if not spec.matches_boundary(window, phase):
            continue
        import signal

        logger.warning(
            "faults: proc_kill firing at boundary window=%d phase=%s",
            window,
            phase,
        )
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_inject(
    block: int,
    attempt: int,
    device: Optional[int] = None,
    n_rows: Optional[int] = None,
    site: str = "dispatch",
) -> None:
    """The dispatch-boundary hook: sleep for every matching ``delay``
    spec, then raise for the first matching ``transient``/``oom`` spec.
    A no-op (one truthiness check) when ``TFS_FAULT_INJECT`` is unset."""
    plan = specs()
    if not plan:
        return
    for spec in plan:
        if spec.kind not in _ENGINE_KINDS:
            continue  # bridge kinds fire in the bridge server, not here
        if not spec.matches(block, attempt, device, n_rows, site):
            continue
        if spec.kind == "delay":
            time.sleep(spec.ms / 1000.0)
            continue
        observability.note_fault_injected()
        where = (
            f"block={block} attempt={attempt} device={device} "
            f"rows={n_rows} site={site}"
        )
        if spec.kind == "transient":
            raise InjectedTransient(
                f"UNAVAILABLE: injected transient fault ({where})"
            )
        raise InjectedOOM(
            f"RESOURCE_EXHAUSTED: injected out-of-memory ({where})"
        )


class BridgeFaultPlan:
    """The aggregated bridge-injection actions for one request:
    ``stall_ms`` (sleep before execution, inside the request's cancel
    scope), ``delay_ms`` (sleep after execution, before the reply),
    ``drop`` (sever the connection instead of replying), and
    ``kill_after_ms`` (round 21: SIGKILL the server process that many
    milliseconds after dispatch begins — ``None`` = no kill)."""

    __slots__ = ("stall_ms", "delay_ms", "drop", "kill_after_ms")

    def __init__(self):
        self.stall_ms = 0.0
        self.delay_ms = 0.0
        self.drop = False
        self.kill_after_ms: Optional[float] = None

    def __bool__(self) -> bool:
        return bool(
            self.stall_ms
            or self.delay_ms
            or self.drop
            or self.kill_after_ms is not None
        )


def maybe_inject_bridge(method: str, call: int) -> Optional[BridgeFaultPlan]:
    """The bridge server's injection hook: the combined
    :class:`BridgeFaultPlan` for the ``call``-th invocation of
    ``method`` in this session, or None (one truthiness check when
    ``TFS_FAULT_INJECT`` is unset).  A ``bridge_drop`` that actually
    severs a connection counts in ``faults_injected`` — the SERVER
    bumps the counter at the drop site, not here, because a request
    refused before its reply (shed, draining) never reaches the drop
    and an uncounted plan must not read as a fired fault.  Stalls and
    delays are adversity, not failures, and stay uncounted like the
    dispatch-boundary ``delay`` kind."""
    plan = specs()
    if not plan:
        return None
    out = BridgeFaultPlan()
    for spec in plan:
        if spec.kind not in _BRIDGE_KINDS:
            continue
        if not spec.matches_bridge(method, call):
            continue
        if spec.kind == "bridge_stall":
            out.stall_ms += spec.ms
        elif spec.kind == "bridge_delay":
            out.delay_ms += spec.ms
        elif spec.kind == "replica_kill":
            out.kill_after_ms = spec.ms
        else:
            out.drop = True
    return out if out else None


def schedule_replica_kill(after_ms: float) -> None:
    """Arm a ``replica_kill``: SIGKILL this process ``after_ms``
    milliseconds from now, from a daemon timer so the matched request
    keeps executing and dies MID-flight — no cleanup, no flushed
    buffers, the same death :func:`maybe_kill_boundary` deals (and the
    same one a real replica eviction deals).  ``after_ms<=0`` kills
    synchronously, before the request executes at all."""
    import signal
    import threading

    def _die():
        logger.warning(
            "faults: replica_kill firing (%.0fms after dispatch)",
            after_ms,
        )
        os.kill(os.getpid(), signal.SIGKILL)

    if after_ms <= 0:
        _die()
        return
    t = threading.Timer(after_ms / 1000.0, _die)
    t.daemon = True
    t.start()


_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory")


def is_oom(exc: BaseException, _depth: int = 0) -> bool:
    """Whether ``exc`` (or its ``__cause__`` chain) is a device
    out-of-memory — real XLA ``RESOURCE_EXHAUSTED`` statuses and
    :class:`InjectedOOM` alike."""
    text = str(exc).lower()
    if any(m in text for m in _OOM_MARKERS):
        return True
    if _depth < 4 and exc.__cause__ is not None:
        return is_oom(exc.__cause__, _depth + 1)
    return False
