"""Checkpoint / resume for training state.

Net-new relative to the reference (it has NO checkpointing — model state is
frozen into the graph as constants and iterative algorithms rebuild graphs
per step, SURVEY.md §5 "Checkpoint/resume").  The TPU-native design uses
orbax: async-capable, sharding-aware (each host writes its own param shards;
restore re-shards to the current mesh), the standard JAX pod checkpoint
mechanism.

State layout: ``{"params": ..., "opt_state": ..., "step": int}`` — any
pytree works.  Restore takes an optional target (a pytree of
``jax.ShapeDtypeStruct`` or concrete arrays) to re-impose shardings.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import orbax.checkpoint as ocp


class Checkpointer:
    """Thin lifecycle wrapper over an orbax ``CheckpointManager``.

    ``keep``: retain at most N checkpoints (oldest pruned).
    """

    def __init__(self, directory: str, keep: int = 3):
        self._dir = os.path.abspath(os.fspath(directory))
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        """Save ``state`` under ``step``.  Async by default; ``wait=True``
        blocks until the write is durable."""
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, step: Optional[int] = None, target: Any = None) -> Any:
        """Restore a checkpoint (latest when ``step`` is None).

        ``target``: pytree of arrays or ``jax.ShapeDtypeStruct`` with
        shardings — restored arrays are placed/re-sharded to match (the
        resume-onto-a-different-mesh path)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self._dir}"
                )
        if target is not None:
            args = ocp.args.StandardRestore(target)
        else:
            args = ocp.args.StandardRestore()
        return self._mgr.restore(step, args=args)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
