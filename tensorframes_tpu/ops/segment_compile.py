"""Generalized keyed-aggregation recognizer: compile a block-reduction
program into a *segment plan* (pre-reduce row stage -> device segment
reductions -> per-group post stage).

Round 4's fast path recognized only bare ``reduce_{sum,min,max,prod}``
applied directly to ``<base>_input`` (``engine._recognize_monoids``), so
``mean``, sum-of-squares, weighted sums and friends fell back to the host
``np.unique`` shuffle replacement even on a mesh (VERDICT r4 weak #5).
This module decomposes the program's jaxpr into:

* a ROW stage — any *elementwise* (per-row, cross-column allowed)
  computation of the inputs feeding each reduce, e.g. the ``x*x`` in a
  sum-of-squares or the ``x*w`` in a weighted sum;
* one device segment reduction per ``reduce_*`` over the block axis
  (``jax.ops.segment_{sum,min,max,prod}``);
* a GROUP stage — any elementwise post-processing of the reduced values,
  vmapped over the group axis, e.g. the ``/ n`` of a mean or the
  ``sqrt`` of a norm.

The block-size literal problem: a program like ``mean`` bakes the block's
row count into the jaxpr as a *literal* (``reduce_sum(x) / 3.0`` when
traced on 3 rows), and per-group semantics require that literal to become
the per-group COUNT.  We trace the program at three probe sizes
(n = 2, 3, 5) and compare: literals (and shape params) that are identical
across traces are true constants; ones that track n as ``k*n``, ``k/n``,
``k*(n-1)`` or ``k/(n-1)`` (mean, variance - biased and unbiased) are
replaced with the same function of the per-group count, which is exactly
the value they would take if the program were re-traced on each group the
way the general bucketed path effectively does.  Anything else — data-
dependent control flow, cross-row primitives (sort, cumsum, gather),
row-position dependence (iota over the block axis), reduce results fed
back into row computation (two-pass forms like ``jnp.var``'s internal
centering) — makes recognition return None and the exact general paths
run instead.

Reference parity: this widens SURVEY.md P5 (shuffle-grouped aggregation,
``DebugRowOps.scala:601-695``) — the reference's UDAF runs the user graph
per group buffer, so *every* algebraic program gets its one semantics;
here the common algebraic families additionally get the single-dispatch
scatter-reduce form.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.extend.core  # noqa: F401 - jax.extend needs an explicit import
import jax.numpy as jnp
import numpy as np

from .. import envutil

logger = logging.getLogger(__name__)

_Literal = jax.extend.core.Literal

# Recognition probe sizes.  Three small sizes pin the count-literal
# families (two fit a 2-parameter family, the rest verify); the large
# outlier catches programs whose PYTHON control flow branches on the
# block size at small thresholds.  Residual assumption, documented: a
# program whose trace structure changes only beyond the largest probe is
# outside the recognizer's envelope — such size-branching reductions also
# violate the aggregate verb's algebraic re-applicability contract
# (``Operations.scala:110-126``), under which the general combine paths
# would be wrong for them too.  (Pad+mask and streaming do NOT rely on
# this: they verify at their exact executed sizes via
# :func:`rows_independent_at`.)
_PROBES = (2, 3, 5, 97)

_REDUCE_KINDS = {
    "reduce_sum": "sum",
    "reduce_min": "min",
    "reduce_max": "max",
    "reduce_prod": "prod",
}

# Primitives that apply independently per row (lead axis preserved, no
# cross-row mixing) with n-independent params.  Conservative whitelist:
# anything outside it rejects the plan.
_ELEMENTWISE = frozenset(
    {
        "add", "sub", "mul", "div", "rem", "pow", "integer_pow", "atan2",
        "exp", "log", "log1p", "expm1", "sqrt", "rsqrt", "square", "cbrt",
        "tanh", "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
        "logistic", "abs", "neg", "sign", "floor", "ceil", "round",
        "is_finite", "max", "min", "and", "or", "xor", "not",
        "eq", "ne", "lt", "le", "gt", "ge", "select_n",
        "convert_element_type", "nextafter", "erf", "erfc", "erf_inv",
        "clamp", "stop_gradient", "copy", "exp2",
        "shift_left", "shift_right_logical", "shift_right_arithmetic",
        "population_count", "clz",
    }
)

# Shape-bearing primitives whose int params may legitimately track the
# probe size (substituted with the live row count in the ROW replay).
_SHAPEY = frozenset({"broadcast_in_dim", "reshape", "squeeze", "transpose",
                     "concatenate", "rev", "expand_dims"})

# Inlined call-like equations (sub-jaxprs flattened into the parent).
_CALL_PRIMS = {
    "jit": "jaxpr",
    "pjit": "jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
}

_N = object()  # sentinel: "the live row count" in a substituted param


@dataclasses.dataclass(frozen=True)
class SegmentPlan:
    """A compiled keyed-reduction: see the module docstring.

    ``pre(cols, params) -> tuple of [N, *cell] arrays`` (jit-traceable),
    one per segment reduction, in ``reduce_kinds`` order; ``post`` is the
    PER-GROUP function ``(seg_cells, count_scalar, params) -> {base:
    cell}`` — callers vmap it over the group axis.  ``trivial_kinds`` is
    the bare-monoid special case (identity pre and post): the per-base
    kind dict, for compatibility with the strict round-3 recognizer."""

    reduce_kinds: Tuple[str, ...]
    needs_count: bool
    pre: Callable[..., Tuple[Any, ...]]
    post: Callable[..., Dict[str, Any]]
    trivial_kinds: Optional[Dict[str, str]]


class _Bail(Exception):
    pass


@dataclasses.dataclass
class _FlatEqn:
    prim: Any                      # the jax Primitive (from the n=2 trace)
    invals: List[Any]              # int var-id | ("lit", slot)
    outvars: List[int]
    params: Dict[str, Any]


def _match_param(vals: Sequence[Any], sizes: Sequence[int]):
    """-> (template, tracks_n): ``vals`` are one param's values aligned
    across the traces at ``sizes``; the template equals the first value
    with every position that tracks the trace size replaced by the _N
    sentinel."""
    v0 = vals[0]
    if isinstance(v0, tuple):
        if not all(
            isinstance(v, tuple) and len(v) == len(v0) for v in vals[1:]
        ):
            raise _Bail()
        parts = [
            _match_param([v[i] for v in vals], sizes)
            for i in range(len(v0))
        ]
        return tuple(p[0] for p in parts), any(p[1] for p in parts)
    if isinstance(v0, int) and not isinstance(v0, bool):
        if all(v == v0 for v in vals[1:]):
            return v0, False
        if tuple(vals) == tuple(sizes):
            return _N, True
        raise _Bail()
    # non-int leaves must agree exactly (dtypes, strings, None, bools...)
    if all(v == v0 for v in vals[1:]):
        return v0, False
    raise _Bail()


def _subst_param(template, n: int):
    if template is _N:
        return n
    if isinstance(template, tuple):
        return tuple(_subst_param(t, n) for t in template)
    return template


def _fit_family(vals, sizes) -> Optional[Tuple[str, float]]:
    """Fit a probe-size-tracking literal to k*n | k/n | k*(n-1) | k/(n-1),
    verified against EVERY probe size."""
    try:
        fv = [float(v) for v in vals]
    except (TypeError, ValueError):
        return None
    fams = (
        ("mul_n", lambda n: float(n)),
        ("div_n", lambda n: 1.0 / n),
        ("mul_nm1", lambda n: n - 1.0),
        ("div_nm1", lambda n: 1.0 / (n - 1.0)),
    )
    for name, f in fams:
        if f(sizes[0]) == 0:
            continue
        k = fv[0] / f(sizes[0])
        if all(
            np.isclose(v, k * f(n), rtol=1e-6, atol=0)
            for v, n in zip(fv[1:], sizes[1:])
        ):
            return name, k
    return None


def _family_value(fam: str, k: float, count):
    c = count.astype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    if fam == "mul_n":
        return k * c
    if fam == "div_n":
        return k / c
    if fam == "mul_nm1":
        return k * (c - 1.0)
    return k / (c - 1.0)


def _flatten(closed, var_ids: Dict[int, int], shapes: Dict[int, tuple],
             consts: List[Any], lits: List[Any],
             eqns: List[_FlatEqn]) -> List[int]:
    """Inline call-like eqns and record every var's shape; returns the
    outvar ids.  ``var_ids`` maps id(Var) -> small int; sub-jaxpr vars get
    fresh ids bridged to the caller's at the call boundary."""

    def vid(v) -> int:
        key = id(v)
        if key not in var_ids:
            var_ids[key] = len(var_ids)
            shapes[var_ids[key]] = tuple(v.aval.shape)
        return var_ids[key]

    def walk(jaxpr, const_vals, invar_ids: List[int]) -> List[int]:
        env: Dict[int, int] = {}
        for v, i in zip(jaxpr.invars, invar_ids):
            env[id(v)] = i
        for v, cval in zip(jaxpr.constvars, const_vals):
            env[id(v)] = vid(v)
            consts.append((env[id(v)], cval))

        def read(v) -> Any:
            if isinstance(v, _Literal):
                lits.append(v.val)
                return ("lit", len(lits) - 1)
            return env[id(v)]

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _CALL_PRIMS:
                inner = eqn.params[_CALL_PRIMS[name]]
                inner_ids = []
                for v in eqn.invars:
                    r = read(v)
                    if isinstance(r, tuple):  # literal into a call: give
                        # it a var id so the inner mapping stays uniform
                        raise _Bail()
                    inner_ids.append(r)
                out_ids = walk(inner.jaxpr, inner.consts, inner_ids)
                if len(out_ids) != len(eqn.outvars):
                    raise _Bail()
                for v, i in zip(eqn.outvars, out_ids):
                    env[id(v)] = i
                continue
            fe = _FlatEqn(
                prim=eqn.primitive,
                invals=[read(v) for v in eqn.invars],
                outvars=[],
                params=dict(eqn.params),
            )
            for v in eqn.outvars:
                env[id(v)] = vid(v)
                fe.outvars.append(env[id(v)])
            eqns.append(fe)
        out = []
        for v in jaxpr.outvars:
            r = read(v)
            if isinstance(r, tuple):
                raise _Bail()  # constant-literal output: let the general
                # path handle this degenerate program
            out.append(r)
        return out

    top_ids = [vid(v) for v in closed.jaxpr.invars]
    return walk(closed.jaxpr, closed.consts, top_ids)


def _trace(program, specs, param_specs):
    from .. import observability

    with observability.suppress_trace_count():
        closed, out_shape = jax.make_jaxpr(
            lambda kw, pr: program.call(kw, pr), return_shape=True
        )(specs, param_specs)
    var_ids: Dict[int, int] = {}
    shapes: Dict[int, tuple] = {}
    consts: List[Any] = []
    lits: List[Any] = []
    eqns: List[_FlatEqn] = []
    outs = _flatten(closed, var_ids, shapes, consts, lits, eqns)
    n_in = len(closed.jaxpr.invars)
    return {
        "shapes": shapes, "consts": consts, "lits": lits, "eqns": eqns,
        "outs": outs, "n_invars": n_in, "out_shape": out_shape,
    }


def recognize(program, input_specs: Dict[str, Any],
              bases: Sequence[str]) -> Optional[SegmentPlan]:
    """Compile ``program`` (a block-reduction over ``<base>_input``
    columns) into a :class:`SegmentPlan`, or None if it is not expressible
    as elementwise-pre -> segment-reduce -> elementwise-post.

    ``input_specs``: name -> ShapeDtypeStruct with a PROBE-SIZED lead dim;
    the lead size is replaced internally (the plan itself is row-count
    agnostic)."""
    try:
        return _recognize(program, input_specs, bases)
    except _Bail:
        return None
    except Exception:
        return None


def _probe_match(program, input_specs, sizes, allow_families: bool = True):
    """Shared prologue of the jaxpr analyses: trace at every size in
    ``sizes``, require structural identity across ALL traces, classify
    literals (constant vs count family; families only when
    ``allow_families``) and build the shape-based var classifier.  Raises
    ``_Bail`` on any mismatch."""
    sizes = tuple(sizes)
    names = sorted(input_specs)
    cells = {
        nm: (tuple(s.shape[1:]), s.dtype) for nm, s in input_specs.items()
    }
    param_specs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(jnp.shape(a), jnp.asarray(a).dtype),
        program.params,
    )
    traces = []
    for n in sizes:
        specs = {
            nm: jax.ShapeDtypeStruct((n,) + cell, dt)
            for nm, (cell, dt) in cells.items()
        }
        traces.append(_trace(program, specs, param_specs))
    t0 = traces[0]

    # ---- structural match across all probes --------------------------------
    for t in traces[1:]:
        if len(t["eqns"]) != len(t0["eqns"]) or t["outs"] != t0["outs"]:
            raise _Bail()
        if len(t["consts"]) != len(t0["consts"]):
            raise _Bail()
        for (i0, c0), (i, c) in zip(t0["consts"], t["consts"]):
            if i0 != i or not np.array_equal(
                np.asarray(c0), np.asarray(c)
            ):
                raise _Bail()
        if len(t["lits"]) != len(t0["lits"]):
            raise _Bail()

    # literal slots: equal across probes -> constant; probe-tracking ->
    # count family (when allowed); anything else -> bail
    lit_const: Dict[int, Any] = {}
    lit_family: Dict[int, Tuple[str, float, Any]] = {}  # slot->(fam,k,dtype)
    for slot in range(len(t0["lits"])):
        vals = [np.asarray(t["lits"][slot]) for t in traces]
        v0 = vals[0]
        if all(
            v.shape == v0.shape and np.array_equal(v0, v)
            for v in vals[1:]
        ):
            lit_const[slot] = t0["lits"][slot]
            continue
        if allow_families and all(v.ndim == 0 for v in vals):
            fit = _fit_family(vals, sizes)
            if fit is not None:
                lit_family[slot] = (fit[0], fit[1], v0.dtype)
                continue
        raise _Bail()

    # ---- per-var row/group classification ----------------------------------
    all_shapes = [t["shapes"] for t in traces]

    def var_class(i: int) -> str:
        ss = [sh[i] for sh in all_shapes]
        if not all(len(s) == len(ss[0]) for s in ss[1:]):
            raise _Bail()
        n_dims = []
        for d in range(len(ss[0])):
            dims = tuple(s[d] for s in ss)
            if all(x == dims[0] for x in dims[1:]):
                continue
            if dims == sizes:
                n_dims.append(d)
            else:
                raise _Bail()
        if not n_dims:
            return "group"
        if n_dims == [0]:
            return "row"
        raise _Bail()

    return {
        "names": names,
        "sizes": sizes,
        "traces": traces,
        "lit_const": lit_const,
        "lit_family": lit_family,
        "var_class": var_class,
    }


def _recognize(program, input_specs, bases) -> Optional[SegmentPlan]:
    m = _probe_match(program, input_specs, _PROBES)
    names = m["names"]
    traces = m["traces"]
    t2 = traces[0]
    lit_const, lit_family = m["lit_const"], m["lit_family"]
    var_class = m["var_class"]

    n_invars = t2["n_invars"]
    kw_leaf_count = len(names)  # each input is one array leaf
    # invar ids are 0..n_invars-1 in flatten order: kw dict leaves (sorted
    # by name) then param leaves
    var_cls: Dict[int, str] = {}
    reduce_dep: Dict[int, bool] = {}
    count_dep: Dict[int, bool] = {}  # transitively touches a count literal
    for i in range(n_invars):
        var_cls[i] = var_class(i)
        reduce_dep[i] = False
        count_dep[i] = False
        if i < kw_leaf_count and var_cls[i] != "row":
            raise _Bail()
    for i, _c in t2["consts"]:
        var_cls[i] = var_class(i)
        if var_cls[i] != "group":
            raise _Bail()
        reduce_dep[i] = False
        count_dep[i] = False

    # ---- eqn classification -------------------------------------------------
    # each eqn gets: cls in {"row","group"}; reduce eqns become segment
    # nodes; params matched across probes for n-tracking
    eqn_cls: List[str] = []
    eqn_tmpl: List[Dict[str, Any]] = []
    eqn_count_dep: List[bool] = []
    seg_nodes: List[Tuple[str, Any, tuple]] = []  # (kind, inval, cell_axes)
    seg_var: Dict[int, int] = {}  # outvar id -> segment slot
    for ei, e2 in enumerate(t2["eqns"]):
        ealigned = [t["eqns"][ei] for t in traces]
        for e in ealigned[1:]:
            if (
                e.prim.name != e2.prim.name
                or e.invals != e2.invals
                or e.outvars != e2.outvars
            ):
                raise _Bail()
        name = e2.prim.name
        keys = sorted(e2.params)
        if any(sorted(e.params) != keys for e in ealigned[1:]):
            raise _Bail()
        tmpl: Dict[str, Any] = {}
        tracks = False
        for k in keys:
            vals = [e.params[k] for e in ealigned]
            try:
                tmpl[k], tk = _match_param(vals, m["sizes"])
            except _Bail:
                # non-comparable param payloads (shardings...) must at
                # least be reference-equal-ish; give up otherwise
                if all(v is None for v in vals):
                    tmpl[k], tk = None, False
                else:
                    raise
            tracks = tracks or tk

        in_classes = []
        dep = False
        cdep = False  # this eqn (transitively) consumes a count literal
        for iv in e2.invals:
            if isinstance(iv, tuple):  # literal
                in_classes.append("group")
                cdep = cdep or iv[1] in lit_family
            else:
                in_classes.append(var_cls.get(iv) or _bail())
                dep = dep or reduce_dep[iv]
                cdep = cdep or count_dep[iv]

        out_classes = [var_class(ov) for ov in e2.outvars]

        if name in _REDUCE_KINDS and in_classes == ["row"] and 0 in tmpl.get(
            "axes", ()
        ):
            # segment-reduction node (optionally cell-reducing first)
            axes = tmpl["axes"]
            if any(a is _N for a in axes):
                raise _Bail()
            cell_axes = tuple(a for a in axes if a != 0)
            if dep or cdep:
                # a segment input may not depend on a reduce result (two-
                # pass) nor on the per-group count (only known post-index)
                raise _Bail()
            if any(oc != "group" for oc in out_classes):
                raise _Bail()
            for ov in e2.outvars:
                var_cls[ov] = "group"
                reduce_dep[ov] = True
                count_dep[ov] = False
                seg_var[ov] = len(seg_nodes)
            seg_nodes.append((_REDUCE_KINDS[name], e2.invals[0], cell_axes))
            eqn_cls.append("seg")
            eqn_tmpl.append(tmpl)
            eqn_count_dep.append(False)
            continue

        cls = "row" if "row" in in_classes else "group"
        if cls == "row":
            if dep:
                raise _Bail()  # reduce result fed back into row compute
            if cdep:
                raise _Bail()  # count-(transitively-)dependent value
                # inside the row stage: the count is only known after the
                # group index is built, which needs the row stage first
            if name in _REDUCE_KINDS:
                axes = tmpl.get("axes", ())
                if 0 in axes or any(a is _N for a in axes):
                    raise _Bail()
                if any(oc != "row" for oc in out_classes):
                    raise _Bail()
            elif name in _ELEMENTWISE:
                if tracks:
                    raise _Bail()
                if any(oc != "row" for oc in out_classes):
                    raise _Bail()
            elif name in _SHAPEY:
                if name == "rev" and 0 in e2.params.get(
                    "dimensions", ()
                ):
                    # a block-axis reversal in the ROW stage would
                    # misalign rows with their per-row group ids before
                    # the segment reduction (round-17 soundness fix,
                    # same hole as rows_independent_at's)
                    raise _Bail()
                if any(oc != "row" for oc in out_classes):
                    raise _Bail()
            else:
                raise _Bail()
        else:  # group eqn
            if tracks:
                raise _Bail()  # an n-tracking param with no row axis to
                # carry it (e.g. integer_pow y=n) has no per-group form
            if name in _REDUCE_KINDS:
                if 0 in tmpl.get("axes", ()):
                    # axes are cell axes here; 0 is a cell dim for group
                    # vars, fine — nothing special
                    pass
            elif name not in _ELEMENTWISE and name not in _SHAPEY:
                raise _Bail()
        for ov, oc in zip(e2.outvars, out_classes):
            var_cls[ov] = oc if cls == "row" else "group"
            reduce_dep[ov] = dep
            count_dep[ov] = cdep
        eqn_cls.append(cls)
        eqn_tmpl.append(tmpl)
        eqn_count_dep.append(cdep)

    # ---- outputs ------------------------------------------------------------
    out_names = sorted(t2["out_shape"])
    if out_names != sorted(bases):
        raise _Bail()
    out_ids = t2["outs"]
    if len(out_ids) != len(out_names):
        raise _Bail()
    for ov in out_ids:
        if var_cls.get(ov) != "group":
            raise _Bail()

    needs_count = bool(lit_family)
    eqns = t2["eqns"]

    # trivial (bare-monoid) detection, for the strict legacy surface:
    # identity pre (each segment input IS its base's kw leaf) and identity
    # post (each output IS its segment result), one reduce per base
    trivial = None
    if (
        not needs_count
        and len(seg_nodes) == len(out_names)
        and all(ov in seg_var for ov in out_ids)
        and sorted(seg_var[ov] for ov in out_ids)
        == list(range(len(seg_nodes)))
    ):
        ok = True
        for base, ov in zip(out_names, out_ids):
            kind, iv, cell_axes = seg_nodes[seg_var[ov]]
            if (
                cell_axes
                or isinstance(iv, tuple)
                or iv >= kw_leaf_count
                or names[iv] != f"{base}_input"
            ):
                ok = False
        if ok:
            trivial = {
                base: seg_nodes[seg_var[ov]][0]
                for base, ov in zip(out_names, out_ids)
            }

    const_env = {i: jnp.asarray(c) for i, c in t2["consts"]}

    def _replay(env, n, classes, count=None):
        """Execute the flat eqns whose class is in ``classes``; ``n`` is
        the live row count for ROW param substitution (None in post)."""
        for fe, cls, tmpl, cdep in zip(
            eqns, eqn_cls, eqn_tmpl, eqn_count_dep
        ):
            if cls not in classes:
                continue
            if cdep and count is None:
                # count-dependent group eqns are post-only (the pre phase
                # has no per-group counts yet); classification guarantees
                # nothing in the row stage needs their outputs
                continue
            vals = []
            missing = False
            for iv in fe.invals:
                if isinstance(iv, tuple):
                    slot = iv[1]
                    if slot in lit_family:
                        fam, k, dt = lit_family[slot]
                        vals.append(
                            _family_value(fam, k, count).astype(dt)
                        )
                    else:
                        vals.append(lit_const[slot])
                elif iv in env:
                    vals.append(env[iv])
                else:
                    missing = True
                    break
            if missing:
                # a group-const eqn whose operands were not materialised
                # in this phase (e.g. depends on a segment result during
                # pre) — skip; the post replay will run it
                continue
            params = {
                k: _subst_param(v, n) if n is not None else v
                for k, v in tmpl.items()
            }
            out = fe.prim.bind(*vals, **params)
            outs = out if fe.prim.multiple_results else [out]
            for ov, o in zip(fe.outvars, outs):
                env[ov] = o

    def _base_env(cols: Dict[str, Any], params) -> Dict[int, Any]:
        env = dict(const_env)
        for i, nm in enumerate(names):
            env[i] = cols[nm]
        leaves = jax.tree_util.tree_flatten(params)[0]
        for j, leaf in enumerate(leaves):
            env[kw_leaf_count + j] = jnp.asarray(leaf)
        return env

    def pre(cols: Dict[str, Any], params) -> Tuple[Any, ...]:
        n = next(iter(cols.values())).shape[0]
        env = _base_env(cols, params)
        _replay(env, n, ("row", "group"))
        outs = []
        for kind, iv, cell_axes in seg_nodes:
            if isinstance(iv, tuple):
                raise AssertionError("segment input cannot be a literal")
            v = env[iv]
            if cell_axes:
                # reduce the cell axes first (commutative monoid: order
                # between cell and row reduction does not matter), keeping
                # the row axis for the segment reduction
                red = {
                    "sum": jnp.sum, "min": jnp.min,
                    "max": jnp.max, "prod": jnp.prod,
                }[kind]
                v = red(v, axis=cell_axes)
            outs.append(v)
        return tuple(outs)

    def post(segs: Tuple[Any, ...], count, params) -> Dict[str, Any]:
        env = dict(const_env)
        leaves = jax.tree_util.tree_flatten(params)[0]
        for j, leaf in enumerate(leaves):
            env[kw_leaf_count + j] = jnp.asarray(leaf)
        for ovs, slot in seg_var.items():
            env[ovs] = segs[slot]
        _replay(env, None, ("group",), count=count)
        return {nm: env[ov] for nm, ov in zip(out_names, out_ids)}

    return SegmentPlan(
        reduce_kinds=tuple(k for k, _iv, _c in seg_nodes),
        needs_count=needs_count,
        pre=pre,
        post=post,
        trivial_kinds=trivial,
    )


def _bail():
    raise _Bail()


def rows_independent_at(
    program, input_specs: Dict[str, Any], sizes: Sequence[int]
) -> bool:
    """True iff the program is jaxpr-provably ROW-INDEPENDENT — each
    output row depends only on the same row of the inputs (plus true
    constants) — verified AT THE EXACT SIZES it will run with.

    This is the safety condition for pad+mask sharding of ``map_blocks``
    on uneven row counts and for chunked h2d streaming (VERDICT r4 weak
    #3/#4): padding or chunking a CROSS-ROW program (a reduce/sort/cumsum
    over the block axis, a block-size literal, a row-position dependence)
    would change its semantics.

    ``sizes`` MUST contain the semantic size (the real block row count)
    and every executed size (the padded total / the chunk sizes).  Unlike
    the recognizer's fixed probe set, tracing at the exact executed sizes
    makes the proof sound against Python control flow that branches on
    the row count at ANY threshold: if the structure (or any literal)
    differs between the semantic trace and an executed trace, the
    program is rejected; if they agree and every eqn is whitelisted
    elementwise, per-row behavior is identical by construction.  A size-2
    probe is added when the sizes alone cannot disambiguate row dims from
    cell dims (fewer than two distinct values)."""
    try:
        sizes = tuple(dict.fromkeys(int(s) for s in sizes))
        if len(sizes) < 2:
            sizes = sizes + (2 if 2 not in sizes else 3,)
        return _row_independent(program, input_specs, sizes)
    except _Bail:
        return False  # a structural mismatch IS the proof failing
    except (TypeError, ValueError, ZeroDivisionError):
        # the user program itself refused to trace at a probe size
        # (shape-dependent python errors, concretization failures): a
        # legitimate "not provable", same as a structural mismatch
        return False
    except Exception as e:  # noqa: BLE001 — anything else is OUR bug
        # (or a jax regression), not evidence of cross-row semantics;
        # silently answering False would mask it as "cross-row" forever
        envutil.warn_once(
            logger,
            f"rowindep:{_program_name(program)}:{type(e).__name__}",
            "rows_independent_at: probe failed unexpectedly for "
            "program %s (%s: %s); treating as cross-row — file this, "
            "the probe should either prove or _Bail",
            _program_name(program),
            type(e).__name__,
            e,
        )
        return False


def _program_name(program) -> str:
    fn = getattr(program, "_fn", None)
    return getattr(fn, "__name__", None) or repr(fn)


def cached_rows_independent(program, input_specs, sizes) -> bool:
    """Memoized :func:`rows_independent_at` (on ``program._derived``,
    keyed by input signature + sizes) — the one shared entry point for
    the pad+mask and streaming call sites."""
    key = (
        "rowindep",
        tuple(
            sorted(
                (n, s.shape, str(s.dtype)) for n, s in input_specs.items()
            )
        ),
        tuple(sorted(set(int(s) for s in sizes))),
    )
    cache = program._derived
    if key not in cache:
        cache[key] = rows_independent_at(program, input_specs, sizes)
    return cache[key]


def _row_independent(program, input_specs, sizes) -> bool:
    m = _probe_match(program, input_specs, sizes, allow_families=False)
    traces = m["traces"]
    t0 = traces[0]
    if m["lit_family"]:
        return False  # unreachable with allow_families=False; belt+braces
    var_class = m["var_class"]
    n_invars = t0["n_invars"]
    kw_leaf_count = len(m["names"])
    var_cls: Dict[int, str] = {}
    for i in range(n_invars):
        var_cls[i] = var_class(i)
        if i < kw_leaf_count and var_cls[i] != "row":
            return False
    for i, _c in t0["consts"]:
        var_cls[i] = var_class(i)
        if var_cls[i] != "group":
            return False
    for ei, e0 in enumerate(t0["eqns"]):
        ealigned = [t["eqns"][ei] for t in traces]
        name = e0.prim.name
        for e in ealigned[1:]:
            if (
                e.prim.name != name
                or e.invals != e0.invals
                or e.outvars != e0.outvars
            ):
                return False
        # a param tracking the row count (e.g. integer_pow y=n from a
        # user's x**x.shape[0]) makes every row's VALUE depend on the row
        # count — only the shape-bearing prims may carry n in params
        # (their n is just the executed lead size)
        keys = sorted(e0.params)
        if any(sorted(e.params) != keys for e in ealigned[1:]):
            return False
        for k in keys:
            vals = [e.params[k] for e in ealigned]
            try:
                _t, tk = _match_param(vals, sizes)
            except _Bail:
                if all(v is None for v in vals):
                    tk = False
                else:
                    return False
            if tk and name not in _SHAPEY:
                return False
        in_classes = [
            "group" if isinstance(iv, tuple) else var_cls.get(iv)
            for iv in e0.invals
        ]
        if None in in_classes:
            return False
        out_classes = [var_class(ov) for ov in e0.outvars]
        if "row" in in_classes:
            # reduces over cell axes of a row value are fine (axes cannot
            # include 0: the output would lose its row dim and var_class
            # checks that below); cross-row prims are simply not in the
            # whitelist
            if name in _REDUCE_KINDS:
                if 0 in e0.params.get("axes", ()):
                    return False
            elif name == "rev":
                # rev along the BLOCK axis permutes row positions while
                # preserving the row-shaped class — the one _SHAPEY
                # member whose row-axis form is order-sensitive (found
                # by the round-17 analyzer differential; padding a
                # row-reversal would land the pad rows at the front)
                if 0 in e0.params.get("dimensions", ()):
                    return False
            elif name not in _ELEMENTWISE and name not in _SHAPEY:
                return False
            if any(oc != "row" for oc in out_classes):
                return False
        else:
            if (
                name not in _ELEMENTWISE
                and name not in _SHAPEY
                and name not in _REDUCE_KINDS
            ):
                return False
            if any(oc != "group" for oc in out_classes):
                return False
        for ov, oc in zip(e0.outvars, out_classes):
            var_cls[ov] = oc
    return all(var_cls.get(ov) == "row" for ov in t0["outs"])
