"""Block-level fault tolerance: per-block retry, quarantine, OOM policy.

The reference recovers at the *partition*: a failed Spark task replays
its partition from RDD lineage (SURVEY.md §5) and a flaky executor gets
blacklisted by the scheduler.  Our data plane's unit of work is the
block, and there is no lineage — the source block is still on the host,
so recovery is re-dispatch.  This module is the policy layer the
execution stack (``engine.py``, ``device_pool.py``, ``pipeline.py``)
threads through every block dispatch:

* **per-block retry** (:class:`FrameRetrySession`): a transient failure
  (classified by the SAME ``resilience.FailureDetector`` the step driver
  uses — one classifier, no drift) re-stages and re-dispatches the block
  with exponential backoff.  Two budgets bound it: ``TFS_BLOCK_RETRIES``
  retries per block, and a per-frame total (retries x blocks) metered by
  the shared detector, so a frame-wide brownout cannot retry forever.
  Exhaustion raises ``RestartBudgetExceeded`` carrying the LAST real
  error (``from exc``), never a bare budget message.
* **device quarantine**: pooled dispatches report transient failures to
  their :class:`~tensorframes_tpu.ops.device_pool.PoolRun`; after
  ``TFS_QUARANTINE_AFTER`` failures a device is drained — its remaining
  blocks re-dispatch to the least-loaded healthy device.  Reassembly is
  by block index, so redirection cannot change results; a pool degraded
  to one healthy device is, by construction, the serial path on that
  device.
* **OOM degradation**: a ``RESOURCE_EXHAUSTED`` on a map-verb block
  whose program passes the jaxpr row-independence proof splits the block
  in half recursively (floor ``TFS_MIN_SPLIT_ROWS``) and re-dispatches
  the halves — row independence makes the concatenated halves
  bit-identical to the whole-block dispatch.  Cross-row programs (and
  trimmed / host-staged blocks) surface a
  :class:`BlockExecutionError` naming the block and row range instead.

The retry contract: **retries never change results.**  Every re-dispatch
re-stages fresh buffers from the host frame (a donated-then-failed
buffer is never re-used — the no-use-after-donate rule survives
failures), runs the same executable, and lands in the same block slot.
Tests pin ``TFS_BLOCK_RETRIES=0`` (conftest) so trace-count fences stay
deterministic; the chaos tier turns the knobs on.

Streaming composition (round 12, ``tensorframes_tpu/streaming/``): the
out-of-core verbs run each window through the engine unchanged, so
every window's verb call builds its OWN :class:`FrameRetrySession` via
:func:`frame_session`.  That per-window scoping is deliberate: the
``retries x blocks`` frame budget bounds recovery *per window* — the
unit whose source bytes are still at hand — rather than amortising one
budget over an unbounded stream (where any fixed budget would either
exhaust arbitrarily early or never bind).  It is the same shape as
Spark's per-task retry budgets over a long job, and it keeps a
mid-stream brownout from poisoning windows that have not arrived yet.
Cancellation still preempts everything: a deadline that fires during a
window's retries surfaces at the next attempt checkpoint and the sink
stays at a window boundary (docs/RESILIENCE.md).

Knobs:

* ``TFS_BLOCK_RETRIES`` — retries per block (default 2; 0 disables the
  whole layer unless fault injection is active).
* ``TFS_BLOCK_BACKOFF_S`` — base backoff between block retries
  (default 0.05; block retries are cheap re-dispatches, not process
  restarts, so the base is far below ``FailureDetector``'s 1 s default).
* ``TFS_MIN_SPLIT_ROWS`` — OOM split floor (default 16): a range
  smaller than twice the floor never splits further.
* ``TFS_QUARANTINE_AFTER`` — transient failures before a pool device is
  drained (default 3).
* ``TFS_FAULT_INJECT`` — the deterministic fault-injection plan
  (``tensorframes_tpu/faults.py``).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Optional, Tuple

from .. import cancellation, faults, observability, resilience
from ..envutil import env_float as _env_float, env_int as _env_int

logger = logging.getLogger("tensorframes_tpu.fault_tolerance")

ENV_RETRIES = "TFS_BLOCK_RETRIES"
ENV_BACKOFF = "TFS_BLOCK_BACKOFF_S"
ENV_MIN_SPLIT = "TFS_MIN_SPLIT_ROWS"
ENV_QUARANTINE = "TFS_QUARANTINE_AFTER"

DEFAULT_RETRIES = 2
DEFAULT_BACKOFF_S = 0.05
DEFAULT_MIN_SPLIT_ROWS = 16
DEFAULT_QUARANTINE_AFTER = 3


def block_retries() -> int:
    """Retries per block dispatch (``TFS_BLOCK_RETRIES``, >= 0)."""
    return _env_int(ENV_RETRIES, DEFAULT_RETRIES)


def block_backoff_s() -> float:
    """Base backoff between block retries (``TFS_BLOCK_BACKOFF_S``)."""
    return _env_float(ENV_BACKOFF, DEFAULT_BACKOFF_S)


def min_split_rows() -> int:
    """OOM-degradation split floor (``TFS_MIN_SPLIT_ROWS``, >= 1)."""
    return _env_int(ENV_MIN_SPLIT, DEFAULT_MIN_SPLIT_ROWS, floor=1)


def quarantine_after() -> int:
    """Transient failures before a pool device drains
    (``TFS_QUARANTINE_AFTER``, >= 1)."""
    return _env_int(ENV_QUARANTINE, DEFAULT_QUARANTINE_AFTER, floor=1)


class BlockExecutionError(RuntimeError):
    """A block's dispatch failed irrecoverably; the message names the
    block index and row range so a frame-scale failure points at data."""


def frame_session(
    num_blocks: int, verb: str = "", pool=None
) -> Optional["FrameRetrySession"]:
    """A :class:`FrameRetrySession` for one verb invocation, or ``None``
    when the layer is fully off (``TFS_BLOCK_RETRIES=0`` and no fault
    injection) — the None fast path keeps the default dispatch loops
    byte-for-byte identical to the pre-round-9 engine, which is what the
    suite's trace/compile fences pin."""
    retries = block_retries()
    if retries <= 0 and not faults.active():
        return None
    return FrameRetrySession(num_blocks, retries, verb=verb, pool=pool)


class FrameRetrySession:
    """One verb invocation's retry bookkeeping: the per-block attempt
    loop, the shared per-frame detector budget, quarantine reporting,
    and the counters the verb span records."""

    def __init__(
        self,
        num_blocks: int,
        retries: Optional[int] = None,
        verb: str = "",
        pool=None,
        detector: Optional[resilience.FailureDetector] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.per_block = block_retries() if retries is None else int(retries)
        self.verb = verb
        self.pool = pool
        # ONE detector per frame: classification lives in resilience (no
        # duplicated tables) and its restart budget is the frame-level
        # bound — per_block retries for every block is the ceiling
        self.detector = detector or resilience.FailureDetector(
            max_restarts=max(self.per_block, 1) * max(num_blocks, 1),
            backoff_s=block_backoff_s(),
        )
        self._sleep = sleep
        self.retries = 0
        self.oom_splits = 0
        # sharded-cache recovery (round 10): blocks whose resident shard
        # could not be used (home device quarantined / shard evicted
        # mid-run) and were rebuilt from the authoritative host copy
        self.cache_restages = 0

    # -- per-block loop ------------------------------------------------------

    def run(
        self,
        bi: int,
        n_rows: int,
        attempt_fn: Callable[[int, Optional[int]], Any],
        device=None,
        oom_split: Optional[Callable[[BaseException], Any]] = None,
        row_range: Optional[Tuple[int, int]] = None,
    ):
        """Run ``attempt_fn(attempt, device_index)`` for block ``bi``
        with injection, classification, backoff, and budgets applied.

        ``attempt_fn`` MUST re-stage its inputs on every attempt past the
        first (the donation-safety half of the retry contract: a buffer
        handed to a donating executable is dead whether the dispatch
        succeeded or not).  ``device`` is an int pool-device index, a
        zero-arg callable returning the current effective index (the
        quarantine-aware pools pass this), or None.  ``oom_split`` is the
        verb's degradation closure: called with the OOM exception, it
        either returns the block's outputs computed from split
        sub-ranges or raises :class:`BlockExecutionError`.
        """
        lo, hi = row_range if row_range is not None else (0, n_rows)
        attempt = 0
        while True:
            # cooperative cancellation: every attempt (first try and
            # every retry) is a checkpoint, so a request whose deadline
            # passed during a block's compute or backoff sleep surfaces
            # DeadlineExceeded here instead of burning retry budget
            cancellation.checkpoint()
            dev_i = device() if callable(device) else device
            try:
                faults.maybe_inject(bi, attempt, dev_i, n_rows)
                return attempt_fn(attempt, dev_i)
            except BaseException as exc:  # noqa: BLE001 - classified below
                if isinstance(exc, cancellation.Cancelled):
                    raise  # a cancel is an instruction, not a failure
                if faults.is_oom(exc):
                    if oom_split is not None:
                        return oom_split(exc)
                    raise BlockExecutionError(
                        f"{self.verb}: block {bi} rows [{lo}, {hi}) "
                        f"exhausted device memory and this dispatch "
                        f"cannot degrade by splitting ({exc})"
                    ) from exc
                if not self.detector.is_transient(exc):
                    raise
                if self.pool is not None and dev_i is not None:
                    # quarantine decisions must see every failure,
                    # including the one that exhausts the budget
                    self.pool.note_block_failure(dev_i)
                if attempt >= self.per_block:
                    if self.per_block <= 0:
                        raise  # retries pinned off: surface untouched
                    raise resilience.RestartBudgetExceeded(
                        f"{self.verb}: block {bi} rows [{lo}, {hi}) failed "
                        f"{attempt + 1} times ({ENV_RETRIES}="
                        f"{self.per_block}); last error: {exc!r}"
                    ) from exc
                delay = self.detector.on_failure(exc)
                # the detector's exponent grows with FRAME-cumulative
                # restarts (right for one restarted step, wrong for many
                # independent blocks: unrelated blocks would inherit each
                # other's backoff).  Bound the sleep by the BLOCK's own
                # attempt index — per-task backoff, Spark-style — while
                # the detector keeps metering the frame budget.
                delay = min(
                    delay,
                    self.detector.backoff_s
                    * self.detector.backoff_factor ** attempt,
                )
                self.retries += 1
                observability.note_block_retry()
                observability.trace_instant(
                    "retry",
                    "faults",
                    verb=self.verb,
                    block=bi,
                    attempt=attempt + 1,
                    device=dev_i,
                )
                logger.warning(
                    "%s: block %d (device %s) transient failure, retry "
                    "%d/%d after %.3fs: %r",
                    self.verb,
                    bi,
                    dev_i,
                    attempt + 1,
                    self.per_block,
                    delay,
                    exc,
                )
                # never sleep a backoff for a request that is already
                # cancelled / past deadline (the loop-top checkpoint
                # would catch it anyway, but only after the sleep)
                cancellation.checkpoint()
                self._sleep(delay)
                attempt += 1

    # -- accounting ----------------------------------------------------------

    def note_split(self, bi: int) -> None:
        """One binary OOM split performed for block ``bi``."""
        self.oom_splits += 1
        observability.note_oom_split()
        observability.trace_instant(
            "oom_split", "faults", verb=self.verb, block=bi
        )

    def note_cache_restage(self) -> None:
        """One cached block rebuilt from its authoritative host copy
        because its resident shard was unusable (quarantined home
        device, or evicted between scheduling and dispatch)."""
        self.cache_restages += 1

    def events(self) -> bool:
        """Whether anything recovery-worthy happened (gates the span
        annotation so fault-free spans keep their exact prior shape)."""
        return bool(
            self.retries
            or self.oom_splits
            or self.cache_restages
            or (self.pool is not None and self.pool.quarantined)
        )

    def record(self) -> dict:
        """The ``fault_tolerance`` span annotation."""
        rec: dict = {
            "retries": self.retries,
            "oom_splits": self.oom_splits,
            "retry_budget_per_block": self.per_block,
        }
        if self.cache_restages:
            rec["cache_restages"] = self.cache_restages
        if self.pool is not None:
            rec["failures_per_device"] = list(self.pool.failures)
            rec["quarantined_devices"] = sorted(self.pool.quarantined)
        return rec
