"""The execution engine: the six verbs, single-device XLA edition.

Re-design of the reference engine ``DebugRowOps``
(``/root/reference/src/main/scala/org/tensorframes/impl/DebugRowOps.scala:281-970``).
The mapping, per SURVEY.md §2.7:

* per-partition TF sessions (P1) -> one jit-compiled XLA executable reused for
  every block with the same signature (jax's jit cache *is* the program
  broadcast, P6);
* partition blocks (P2) -> contiguous columnar arrays, a single ``device_put``
  each instead of per-row ``TensorConverter`` appends;
* ``map_rows`` -> ``vmap`` of the cell-level program over the block's lead
  axis (instead of one session.run per row, ``DebugRowOps.scala:819-857``);
* ``reduce_rows``'s sequential pairwise fold (``performReducePairwise``,
  ``DebugRowOps.scala:930-969``) -> a balanced binary tree of ``vmap``-ed
  pairwise applications, traced with static sizes (deterministic; a
  ``mode="sequential"`` ``lax.scan`` fold reproduces the reference's exact
  left-fold order for non-associative programs);
* ``reduce_blocks``'s two phases (``DebugRowOps.scala:503-526``) -> per-block
  reduce, then ONE re-application of the same block program to the stacked
  partials (the contract already requires the program to reduce any-size
  blocks, so no pairwise driver loop is needed);
* ``aggregate``'s shuffle + buffered UDAF (``DebugRowOps.scala:547-695``) ->
  host group-index build + size-bucketed ``vmap`` of the block program over
  all groups of equal cardinality (no buffer-size-10 compaction artifact).

The ``Executor`` here is single-device; ``tensorframes_tpu.parallel`` provides
the mesh/``shard_map`` executor with collective cross-shard reduction.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes
from ..frame import Column, TensorFrame
from ..program import Program
from ..schema import ColumnInfo, Schema
from ..shape import Shape, UNKNOWN
from . import validation
from .validation import ValidationError


def _np(x) -> np.ndarray:
    return np.asarray(jax.device_get(x))


class GroupedFrame:
    """Result of ``group_by`` — the ``RelationalGroupedDataset`` analog."""

    def __init__(self, frame: TensorFrame, keys: Sequence[str]):
        if not keys:
            raise ValidationError("group_by needs at least one key column")
        for k in keys:
            ci = frame.schema[k]
            if ci.cell_shape.rank != 0:
                raise ValidationError(
                    f"group_by: key column {k!r} must be scalar, has cell "
                    f"shape {ci.cell_shape}"
                )
        self.frame = frame
        self.keys = list(keys)


def group_by(frame: TensorFrame, *keys: str) -> GroupedFrame:
    return GroupedFrame(frame, keys)


class Executor:
    """Single-device verb executor."""

    # ---------------------------------------------------------------- map --

    def _device_inputs(
        self,
        program: Program,
        block: Mapping[str, Any],
        infos: Mapping[str, ColumnInfo],
    ) -> Dict[str, jnp.ndarray]:
        inputs = {}
        for n in program.input_names:
            ci = infos[n]
            st = dtypes.coerce(ci.scalar_type)
            arr = np.asarray(block[program.column_for_input(n)])
            if arr.dtype != st.np_dtype:
                arr = arr.astype(st.np_dtype)
            inputs[n] = jnp.asarray(arr)
        return inputs

    def _run_block_program(self, program: Program, inputs) -> Dict[str, Any]:
        return program.jitted()(inputs)

    def map_blocks(
        self, program: Program, frame: TensorFrame, trim: bool = False
    ) -> TensorFrame:
        """``mapBlocks`` (``DebugRowOps.scala:290-393``) /
        ``mapBlocksTrimmed`` (trim=True: output row count may differ, no
        passthrough columns — ``Operations.scala:61-80``)."""
        infos = validation.check_map_inputs(program, frame, "map_blocks")
        out_blocks: List[Dict[str, np.ndarray]] = []
        for bi in range(frame.num_blocks):
            block = frame.block(bi)
            n_rows = len(next(iter(block.values())))
            inputs = self._device_inputs(program, block, infos)
            outs = self._run_block_program(program, inputs)
            host = {k: _np(v) for k, v in outs.items()}
            if not trim:
                for name, v in host.items():
                    if v.ndim == 0 or v.shape[0] != n_rows:
                        raise ValidationError(
                            f"map_blocks: output {name!r} has shape "
                            f"{v.shape} but the input block has {n_rows} "
                            f"rows; a non-trimmed map must preserve the row "
                            f"count (use map_blocks_trimmed to change it)."
                        )
            else:
                counts = {v.shape[0] if v.ndim else None for v in host.values()}
                if len(counts) != 1 or None in counts:
                    raise ValidationError(
                        f"map_blocks_trimmed: outputs disagree on row count: "
                        f"{ {k: v.shape for k, v in host.items()} }"
                    )
            out_blocks.append(host)
        return self._build_map_output(frame, out_blocks, trim)

    def map_rows(
        self, program: Program, frame: TensorFrame
    ) -> TensorFrame:
        """``mapRows`` (``DebugRowOps.scala:396-477``): the program is written
        at *cell* level and vmapped over the block's rows."""
        infos = validation.check_map_inputs(program, frame, "map_rows")
        vmapped = program.vmapped()
        out_blocks: List[Dict[str, np.ndarray]] = []
        for bi in range(frame.num_blocks):
            block = frame.block(bi)
            inputs = self._device_inputs(program, block, infos)
            outs = vmapped(inputs)
            out_blocks.append({k: _np(v) for k, v in outs.items()})
        return self._build_map_output(frame, out_blocks, trim=False)

    def _column_array(
        self, frame: TensorFrame, col_name: str, ci: ColumnInfo
    ) -> np.ndarray:
        """Load a column as a contiguous host array in its device dtype."""
        st = dtypes.coerce(ci.scalar_type)
        return np.asarray(frame.column(col_name).data).astype(
            st.np_dtype, copy=False
        )

    def _build_map_output(
        self,
        frame: TensorFrame,
        out_blocks: List[Dict[str, np.ndarray]],
        trim: bool,
        offsets: Optional[Sequence[int]] = None,
    ) -> TensorFrame:
        out_frame = TensorFrame.from_blocks(out_blocks)
        if trim:
            return out_frame
        # non-trimmed: append original columns not shadowed by outputs
        # (reference output schema: outputs ++ original, DebugRowOps.scala:
        # 349-372).  Divergence, by design: Spark tolerates duplicate column
        # names so the reference can emit both; our schema forbids duplicates,
        # so an output *shadows* the same-named passthrough column.
        shadowed = set(out_frame.column_names)
        cols = list(out_frame.columns)
        for cname in frame.column_names:
            if cname not in shadowed:
                cols.append(frame.column(cname))
        return TensorFrame(
            cols, offsets if offsets is not None else out_frame.offsets
        )

    # ------------------------------------------------------------- reduce --

    def _pair_call(self, program: Program, bases: Sequence[str]):
        def pairfn(left: Dict[str, Any], right: Dict[str, Any], params):
            inputs = {}
            for b in bases:
                inputs[f"{b}_1"] = left[b]
                inputs[f"{b}_2"] = right[b]
            return program.call(inputs, params)

        return pairfn

    def _tree_fold(
        self, pairfn, arrays: Dict[str, jnp.ndarray], params
    ) -> Dict[str, jnp.ndarray]:
        """Balanced deterministic tree fold over the lead axis (static size)."""
        vpair = jax.vmap(pairfn, in_axes=(0, 0, None))

        def fold(arrs: Dict[str, jnp.ndarray]):
            n = next(iter(arrs.values())).shape[0]
            if n == 0:
                raise ValidationError("cannot pairwise-fold zero rows")
            if n == 1:
                return {k: v[0] for k, v in arrs.items()}
            half = n // 2
            left = {k: v[:half] for k, v in arrs.items()}
            right = {k: v[half : 2 * half] for k, v in arrs.items()}
            combined = vpair(left, right, params)
            if n % 2:
                combined = {
                    k: jnp.concatenate([v, arrs[k][2 * half :]])
                    for k, v in combined.items()
                }
            return fold(combined)

        return fold(arrays)

    def _seq_fold(
        self, pairfn, arrays: Dict[str, jnp.ndarray], params
    ) -> Dict[str, jnp.ndarray]:
        """Left fold in row order — bit-exact reproduction of the reference's
        sequential pairwise reduction (``performReducePairwise``,
        ``DebugRowOps.scala:930-969``)."""
        init = {k: v[0] for k, v in arrays.items()}
        rest = {k: v[1:] for k, v in arrays.items()}

        def step(carry, row):
            return pairfn(carry, row, params), None

        out, _ = jax.lax.scan(step, init, rest)
        return out

    def _reduce_rows_setup(
        self, program: Program, frame: TensorFrame, mode: str
    ):
        """Shared pre-flight for reduce_rows (single-device and mesh): checks
        the pairwise contract and returns ``(bases, reduced, run)`` where
        ``run`` jit-folds a dict of block arrays down to one cell each."""
        if frame.num_rows == 0:
            raise ValidationError(
                "reduce_rows: cannot reduce an empty frame (no identity "
                "element is available for an arbitrary pairwise program)"
            )
        reduced = validation.check_reduce_rows(program, frame)
        bases = sorted(reduced)
        summaries = program.analyze(
            {
                f"{b}_{i}": (
                    dtypes.coerce(reduced[b].scalar_type),
                    tuple(reduced[b].cell_shape),
                )
                for b in bases
                for i in (1, 2)
            }
        )
        validation.check_reduce_rows_outputs(reduced, summaries)
        if mode not in ("tree", "sequential"):
            raise ValidationError(
                f"reduce_rows: unknown mode {mode!r}; use 'tree' or "
                f"'sequential'"
            )
        pairfn = self._pair_call(program, bases)
        fold = self._tree_fold if mode == "tree" else self._seq_fold

        run = program.cached_jit(
            ("reduce_rows", mode, tuple(bases)),
            lambda: lambda arrs, params: fold(pairfn, arrs, params),
        )
        return bases, reduced, run

    def reduce_rows(
        self, program: Program, frame: TensorFrame, mode: str = "tree"
    ) -> Dict[str, np.ndarray]:
        """``reduceRows`` (``DebugRowOps.scala:479-501``): pairwise-fold all
        rows of the named columns down to one row."""
        bases, reduced, run = self._reduce_rows_setup(program, frame, mode)
        partials: List[Dict[str, jnp.ndarray]] = []
        for bi in range(frame.num_blocks):
            if frame.block_sizes[bi] == 0:
                continue  # empty-partition guard (DebugRowOps.scala:489-499)
            block = frame.block(bi)
            arrays = {}
            for b in bases:
                ci = reduced[b]
                st = dtypes.coerce(ci.scalar_type)
                arrays[b] = jnp.asarray(
                    np.asarray(block[b]).astype(st.np_dtype, copy=False)
                )
            partials.append(run(arrays))
        if len(partials) == 1:
            final = partials[0]
        else:
            stacked = {
                b: jnp.stack([p[b] for p in partials]) for b in bases
            }
            final = run(stacked)
        return {b: _np(final[b]) for b in bases}

    def _reduce_blocks_setup(
        self, program: Program, frame: TensorFrame, verb: str = "reduce_blocks"
    ):
        """Shared pre-flight for reduce_blocks/aggregate-style programs:
        checks the x_input contract and returns ``(bases, reduced, run)``
        where ``run`` jit-applies the block program to a dict of block
        arrays keyed by base column name."""
        if frame.num_rows == 0:
            raise ValidationError(
                f"{verb}: cannot reduce an empty frame (no identity "
                f"element is available for an arbitrary block program)"
            )
        reduced = validation.check_reduce_blocks(program, frame, verb=verb)
        bases = sorted(reduced)
        # analyze at an arbitrary static block size to validate the contract
        probe = max(frame.block_sizes) or 1
        summaries = program.analyze(
            {
                f"{b}_input": (
                    dtypes.coerce(reduced[b].scalar_type),
                    (probe,) + tuple(reduced[b].cell_shape),
                )
                for b in bases
            }
        )
        validation.check_reduce_blocks_outputs(reduced, summaries, verb=verb)

        run = program.cached_jit(
            (verb, tuple(bases)),
            lambda: lambda arrs, params: program.call(
                {f"{b}_input": arrs[b] for b in bases}, params
            ),
        )
        return bases, reduced, run

    def reduce_blocks(
        self, program: Program, frame: TensorFrame
    ) -> Dict[str, np.ndarray]:
        """``reduceBlocks`` (``DebugRowOps.scala:503-526``): phase 1 reduces
        each block to one row with the user's block program; phase 2 re-applies
        the same program once to the stacked per-block partials."""
        bases, reduced, run = self._reduce_blocks_setup(program, frame)
        partials: List[Dict[str, jnp.ndarray]] = []
        for bi in range(frame.num_blocks):
            if frame.block_sizes[bi] == 0:
                continue  # empty-partition guard (DebugRowOps.scala:512-522)
            block = frame.block(bi)
            arrays = {}
            for b in bases:
                ci = reduced[b]
                st = dtypes.coerce(ci.scalar_type)
                arrays[b] = jnp.asarray(
                    np.asarray(block[b]).astype(st.np_dtype, copy=False)
                )
            partials.append(run(arrays))
        if len(partials) == 1:
            final = partials[0]
        else:
            stacked = {b: jnp.stack([p[b] for p in partials]) for b in bases}
            final = run(stacked)
        return {b: _np(final[b]) for b in bases}

    # ---------------------------------------------------------- aggregate --

    def _run_groups(
        self, vrun, batch: Dict[str, np.ndarray]
    ) -> Dict[str, jnp.ndarray]:
        """Run the vmapped block program over one [groups, size, *cell]
        bucket.  The mesh executor overrides this to shard (and pad) the
        groups axis — groups are independent under vmap, so padding is
        semantics-safe there, unlike frame rows."""
        return vrun({b: jnp.asarray(v) for b, v in batch.items()})

    def aggregate(
        self, program: Program, grouped: GroupedFrame
    ) -> TensorFrame:
        """``aggregate`` (``DebugRowOps.scala:547-592`` + ``TensorFlowUDAF``
        L601-695): apply the x_input block program once per key group.

        Groups are bucketed by cardinality and each bucket runs as ONE
        ``vmap``-ed device call over all its groups — the TPU-shaped
        replacement for Spark's shuffle + row-buffered UDAF."""
        frame = grouped.frame
        reduced = validation.check_reduce_blocks(program, frame, verb="aggregate")
        bases = sorted(reduced)
        for k in grouped.keys:
            if k in reduced:
                raise ValidationError(
                    f"aggregate: column {k!r} is both a grouping key and a "
                    f"reduced column"
                )

        # --- host-side group index build (the shuffle replacement) ---
        key_cells = [np.asarray(frame.column(k).data) for k in grouped.keys]
        n = frame.num_rows
        if len(key_cells) == 1:
            uniq, inverse = np.unique(key_cells[0], return_inverse=True)
            uniq_cols = [uniq]
        else:
            stacked = np.rec.fromarrays(key_cells)
            uniq, inverse = np.unique(stacked, return_inverse=True)
            uniq_cols = [np.asarray(uniq[name]) for name in uniq.dtype.names]
        num_groups = len(uniq_cols[0])
        order = np.argsort(inverse, kind="stable")
        counts = np.bincount(inverse, minlength=num_groups)
        starts = np.zeros(num_groups, dtype=np.int64)
        np.cumsum(counts[:-1], out=starts[1:])

        # validate the block-reduction contract at the largest group size
        # (same check reduce_blocks performs; a program that does not reduce
        # its block to one cell must fail loudly, not mis-shape the output)
        probe = int(counts.max())
        summaries = program.analyze(
            {
                f"{b}_input": (
                    dtypes.coerce(reduced[b].scalar_type),
                    (probe,) + tuple(reduced[b].cell_shape),
                )
                for b in bases
            }
        )
        validation.check_reduce_blocks_outputs(
            reduced, summaries, verb="aggregate"
        )

        # --- data columns, reordered so groups are contiguous ---
        data = {}
        for b in bases:
            ci = reduced[b]
            st = dtypes.coerce(ci.scalar_type)
            data[b] = np.asarray(frame.column(b).data).astype(
                st.np_dtype, copy=False
            )[order]

        vrun = program.cached_jit(
            ("aggregate_v", tuple(bases)),
            lambda: lambda arrs, params: jax.vmap(
                lambda a: program.call(
                    {f"{b}_input": a[b] for b in bases}, params
                ),
                in_axes=(0,),
            )(arrs),
        )

        # --- size-bucketed vmap over groups ---
        out_cells: Dict[str, List[Tuple[int, np.ndarray]]] = {b: [] for b in bases}
        by_size: Dict[int, List[int]] = {}
        for g in range(num_groups):
            by_size.setdefault(int(counts[g]), []).append(g)
        for size, gids in sorted(by_size.items()):
            gather = np.empty((len(gids), size), dtype=np.int64)
            for i, g in enumerate(gids):
                gather[i] = np.arange(starts[g], starts[g] + size)
            batch = {b: data[b][gather] for b in bases}
            outs = self._run_groups(vrun, batch)  # dict base -> [num_gids, *cell]
            for b in bases:
                host = _np(outs[b])
                for i, g in enumerate(gids):
                    out_cells[b].append((g, host[i]))

        # --- assemble one-block result: keys ++ outputs, one row per group ---
        cols: List[Column] = []
        for kname, kvals in zip(grouped.keys, uniq_cols):
            st = dtypes.from_numpy(kvals.dtype)
            info = ColumnInfo(kname, st, Shape(kvals.shape).with_lead(UNKNOWN))
            cols.append(Column(info, kvals))
        for b in bases:
            cells = [c for _, c in sorted(out_cells[b], key=lambda t: t[0])]
            arr = np.stack(cells)
            st = dtypes.from_numpy(arr.dtype)
            info = ColumnInfo(b, st, Shape(arr.shape).with_lead(UNKNOWN))
            cols.append(Column(info, arr))
        return TensorFrame(cols)


_DEFAULT = Executor()


def _resolve(engine: Optional[Executor]) -> Executor:
    return engine if engine is not None else _DEFAULT


# ---------------------------------------------------------------------------
# public verb API (the tfs.* surface, core.py:10-11)
# ---------------------------------------------------------------------------


def map_blocks(
    fn,
    frame: TensorFrame,
    trim: bool = False,
    fetches: Optional[Sequence[str]] = None,
    feed_dict: Optional[Mapping[str, str]] = None,
    engine: Optional[Executor] = None,
) -> TensorFrame:
    """Apply a block-level program to every block (``tfs.map_blocks``,
    reference ``core.py:213-253``)."""
    program = Program.wrap(fn, fetches, feed_dict)
    return _resolve(engine).map_blocks(program, frame, trim=trim)


def map_rows(
    fn,
    frame: TensorFrame,
    fetches: Optional[Sequence[str]] = None,
    feed_dict: Optional[Mapping[str, str]] = None,
    engine: Optional[Executor] = None,
) -> TensorFrame:
    """Apply a row-level program to every row (``tfs.map_rows``,
    reference ``core.py:175-211``)."""
    program = Program.wrap(fn, fetches, feed_dict)
    return _resolve(engine).map_rows(program, frame)


def reduce_rows(
    fn,
    frame: TensorFrame,
    fetches: Optional[Sequence[str]] = None,
    mode: str = "tree",
    engine: Optional[Executor] = None,
) -> Dict[str, np.ndarray]:
    """Pairwise-reduce all rows to one (``tfs.reduce_rows``,
    reference ``core.py:138-173``)."""
    program = Program.wrap(fn, fetches)
    return _resolve(engine).reduce_rows(program, frame, mode=mode)


def reduce_blocks(
    fn,
    frame: TensorFrame,
    fetches: Optional[Sequence[str]] = None,
    engine: Optional[Executor] = None,
) -> Dict[str, np.ndarray]:
    """Block-reduce then combine across blocks (``tfs.reduce_blocks``,
    reference ``core.py:255-291``)."""
    program = Program.wrap(fn, fetches)
    return _resolve(engine).reduce_blocks(program, frame)


def aggregate(
    fn,
    grouped: GroupedFrame,
    fetches: Optional[Sequence[str]] = None,
    engine: Optional[Executor] = None,
) -> TensorFrame:
    """Keyed algebraic aggregation (``tfs.aggregate``,
    reference ``core.py:319-336``)."""
    program = Program.wrap(fn, fetches)
    return _resolve(engine).aggregate(program, grouped)
