"""The execution engine: the six verbs, single-device XLA edition.

Re-design of the reference engine ``DebugRowOps``
(``/root/reference/src/main/scala/org/tensorframes/impl/DebugRowOps.scala:281-970``).
The mapping, per SURVEY.md §2.7:

* per-partition TF sessions (P1) -> one jit-compiled XLA executable reused for
  every block with the same signature (jax's jit cache *is* the program
  broadcast, P6);
* partition blocks (P2) -> contiguous columnar arrays, a single ``device_put``
  each instead of per-row ``TensorConverter`` appends;
* ``map_rows`` -> ``vmap`` of the cell-level program over the block's lead
  axis (instead of one session.run per row, ``DebugRowOps.scala:819-857``);
* ``reduce_rows``'s sequential pairwise fold (``performReducePairwise``,
  ``DebugRowOps.scala:930-969``) -> a balanced binary tree of ``vmap``-ed
  pairwise applications, traced with static sizes (deterministic; a
  ``mode="sequential"`` ``lax.scan`` fold reproduces the reference's exact
  left-fold order for non-associative programs);
* ``reduce_blocks``'s two phases (``DebugRowOps.scala:503-526``) -> per-block
  reduce, then ONE re-application of the same block program to the stacked
  partials (the contract already requires the program to reduce any-size
  blocks, so no pairwise driver loop is needed);
* ``aggregate``'s shuffle + buffered UDAF (``DebugRowOps.scala:547-695``) ->
  host group-index build + size-bucketed ``vmap`` of the block program over
  all groups of equal cardinality (no buffer-size-10 compaction artifact).

The ``Executor`` here is single-PROGRAM; on a multi-chip host the
device-pool scheduler (``ops/device_pool.py``, ``TFS_DEVICE_POOL``)
spreads a host-fresh frame's independent blocks across all local devices
— the reference's per-partition data parallelism (SURVEY P1/P4) at
single-host scale, bit-identical to the serial path.
``tensorframes_tpu.parallel`` provides the mesh/``shard_map`` executor
with collective cross-shard reduction for the GSPMD form.
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import cancellation, dtypes, envutil, faults, observability
from ..frame import Column, TensorFrame
from ..program import Program
from ..schema import ColumnInfo, Schema
from ..shape import Shape, ShapeError, UNKNOWN
from . import (
    bucketing,
    device_pool,
    fault_tolerance,
    frame_cache,
    prefetch,
    segment_compile,
    validation,
)
from ..analysis import rowdep as analysis
from .validation import ValidationError

_log = logging.getLogger("tensorframes_tpu.engine")


def _check_shape_hints(
    program: Program, outs: Mapping[str, Any], verb: str, cell_level: bool
) -> None:
    """Check real outputs against the program's shape hints (the run-time
    half of the ``ShapeDescription`` contract: a hint the engine cannot
    satisfy is an error, not a silent discard — VERDICT r1 weak #6).

    ``cell_level``: map_rows hints describe per-row cell shapes; block-verb
    hints describe whole block shapes (reference ``core.py:52-72``)."""
    hints = program.shape_hints
    if not hints:
        return
    for name, hint in hints.items():
        if name not in outs:
            raise ValidationError(
                f"{verb}: shape hint given for {name!r}, which is not a "
                f"program output; outputs are {sorted(outs)}."
            )
        actual = Shape(outs[name].shape)
        if cell_level:
            actual = actual.tail() if actual.rank else actual
        try:
            actual.check_more_precise_than(hint, f"{verb} output {name!r}")
        except ShapeError as e:
            raise ValidationError(
                f"{verb}: output {name!r} has shape {actual}, which "
                f"contradicts the declared shape hint {hint}."
            ) from e


def _with_prelude(program: Program, host_stage):
    """Merge the program's ``host_prelude`` (e.g. the GraphDef importer's
    in-graph Decode* stages) under any caller-supplied ``host_stage`` —
    an explicit stage wins per input."""
    prelude = getattr(program, "host_prelude", None)
    if not prelude:
        return host_stage
    merged = dict(prelude)
    merged.update(host_stage or {})
    return merged


def _np(x) -> np.ndarray:
    return np.asarray(jax.device_get(x))


class GroupedFrame:
    """Result of ``group_by`` — the ``RelationalGroupedDataset`` analog."""

    def __init__(self, frame: TensorFrame, keys: Sequence[str]):
        if not keys:
            raise ValidationError("group_by needs at least one key column")
        for k in keys:
            ci = frame.schema[k]
            if ci.cell_shape.rank != 0:
                raise ValidationError(
                    f"group_by: key column {k!r} must be scalar, has cell "
                    f"shape {ci.cell_shape}"
                )
        self.frame = frame
        self.keys = list(keys)


def group_by(frame: TensorFrame, *keys: str) -> GroupedFrame:
    if getattr(frame, "_tfs_lazy", False):
        # LazyFrame: materialise the plan (aggregate's group structure
        # is data-dependent), counting the grouping as one consumer
        return frame.group_by(*keys)
    return GroupedFrame(frame, keys)


class Executor:
    """Single-device verb executor.

    Data-plane design (SURVEY.md §7 hard part 3 — the throughput term the
    reference lost to per-row ``TensorConverter`` appends and per-partition
    session syncs): every verb *dispatches* all blocks without synchronising —
    ``device_put`` and jitted execution are asynchronous, so the host->HBM
    transfer of block N+1 overlaps the compute of block N — and outputs stay
    on device (``jax.Array`` columns).  The only host syncs are the user's own
    materialisation calls (``collect``/``to_arrays``/``np.asarray``) and the
    single-cell results of the reduce verbs.

    Exception, by design: when the device POOL engages (``TFS_DEVICE_POOL``,
    host-fresh multi-block frame, >=2 local devices) the map verbs return
    host-assembled columns — per-block D2H starts as each block completes
    (overlapped with later blocks' compute) and the verb syncs on the last
    block.  See ``ops/device_pool.py`` for the scope rules.
    """

    # monoid aggregates may run as one device segment reduction; the mesh
    # executor shards the same path over its data axis via _place_rows
    supports_segment_aggregate = True

    # host-fresh multi-block frames may dispatch blocks across ALL local
    # devices (ops/device_pool.py, TFS_DEVICE_POOL); the mesh executor
    # opts out — its GSPMD sharding is its own multi-device story
    supports_device_pool = True

    def _place_rows(self, arr: jnp.ndarray) -> jnp.ndarray:
        """Device placement for a row-axis array in the segment-aggregate
        path.  The mesh executor overrides this to shard the rows over the
        data axis, turning the device sort + segment reduction into a
        GSPMD-distributed one (SURVEY P5 at mesh scale)."""
        return jnp.asarray(arr)

    def _segment_pad_rows(self, n: int) -> int:
        """Rows of identity padding the segment-aggregate path should
        append for a row count of ``n`` — 0 on a single device; the mesh
        executor pads to a data-axis multiple so uneven frames still
        shard over the whole mesh (bare-monoid plans only; see
        ``_aggregate_segment``)."""
        return 0

    # ---------------------------------------------------------------- map --

    def _device_value(self, value: Any, st, device=None) -> jnp.ndarray:
        """One block/column of data -> device array in its compute dtype.

        Device-resident values (chained verb outputs) are used in place —
        at most a device-side cast; host values are cast on host then moved
        with an async ``device_put`` (the single-copy replacement for
        ``datatypes.scala:93-127``).  ``device``: explicit placement for
        the device-pool scheduler's per-device staging lanes (None keeps
        jax's default device)."""
        if isinstance(value, jax.Array):
            if value.dtype != st.np_dtype:
                value = value.astype(st.np_dtype)
            if device is not None:
                value = jax.device_put(value, device)
            return value
        arr = np.asarray(value)
        if arr.dtype != st.np_dtype:
            arr = arr.astype(st.np_dtype)
        observability.note_h2d_bytes(arr.nbytes)
        return jax.device_put(arr, device)

    def _staged_value(self, stage_fn, value, input_name: str) -> np.ndarray:
        """Run one host_stage fn over a block's cells and shape-check the
        result — the host half of the reference's binary-feed contract
        (``read_image.py:164-167`` feeds encoded bytes to an in-graph
        decoder; XLA cannot host strings, so the decode runs here)."""
        n_rows = len(value)
        if isinstance(value, np.ndarray) and value.dtype == object:
            value = list(value)
        out = np.asarray(stage_fn(value))
        if out.ndim == 0 or out.shape[0] != n_rows:
            raise ValidationError(
                f"host_stage for input {input_name!r} returned shape "
                f"{out.shape}; expected lead dimension {n_rows} (one "
                f"preprocessed cell per input row)."
            )
        if out.dtype == object:
            raise ValidationError(
                f"host_stage for input {input_name!r} must return a uniform "
                f"numeric array, got dtype=object (ragged cells)."
            )
        return out

    def _device_inputs(
        self,
        program: Program,
        block: Mapping[str, Any],
        infos: Mapping[str, ColumnInfo],
        host_stage: Optional[Mapping[str, Any]] = None,
        pad_to: Optional[int] = None,
        device=None,
    ) -> Dict[str, jnp.ndarray]:
        """``pad_to``: bucket target for the block's row axis (shape-
        canonical execution).  Host blocks pad in numpy *before* the
        ``device_put``, so the staged transfer already carries the padded
        signature (prefetch worker included); device-resident blocks pad
        with a device-side concat on the consumer thread.  Callers slice
        the outputs back to the true row count.  ``device``: explicit
        target for the device-pool staging lanes."""
        inputs = {}
        for n in program.input_names:
            value = block[program.column_for_input(n)]
            if host_stage and n in host_stage:
                value = self._staged_value(host_stage[n], value, n)
                st = dtypes.coerce(dtypes.from_numpy(value.dtype))
            else:
                st = dtypes.coerce(infos[n].scalar_type)
            if pad_to is not None and not isinstance(value, jax.Array):
                value = bucketing.pad_rows(np.asarray(value), pad_to)
            value = self._device_value(value, st, device=device)
            if pad_to is not None and isinstance(value, jax.Array):
                value = bucketing.pad_rows(value, pad_to)
            inputs[n] = value
        return inputs

    def _run_block_program(self, program: Program, inputs) -> Dict[str, Any]:
        return program.jitted()(inputs)

    # -- donated entries (prefetch path) ------------------------------------
    # A donating executable invalidates its input buffers, letting XLA
    # reuse them for outputs: with the Prefetcher's bounded window the
    # steady-state HBM footprint of uncached ingestion is <= depth input
    # blocks regardless of frame size.  ONLY freshly staged buffers may
    # flow through these (prefetch.py's no-use-after-donate contract);
    # device-resident (cached/chained) columns keep the plain entries.

    def _block_run(self, program: Program, donate: bool):
        if not donate:
            return program.jitted()
        return program.cached_jit(
            ("map_blocks", "donated"),
            lambda: lambda ins, ps: program.call(ins, ps),
            donate_argnums=(0,),
        )

    def _rows_run(self, program: Program, donate: bool):
        if not donate:
            return program.vmapped()
        return program.cached_jit(
            ("map_rows", "donated"),
            lambda: lambda ins, ps: jax.vmap(
                lambda i: program.call(i, ps), in_axes=(0,)
            )(ins),
            donate_argnums=(0,),
        )

    # h2d streaming granularity for uncached blocks (VERDICT r4 weak #3):
    # a block whose host->device transfer exceeds ~2 chunks is split into
    # row slices, each device_put + dispatched separately, so chunk k+1's
    # transfer overlaps chunk k's compute INSIDE the block instead of the
    # whole block's bytes landing before any compute starts.  Applied only
    # to row-independent programs per the shared gate (analysis.
    # rows_independent: static classification first, exact-size probe on
    # UNKNOWN) — cross-row programs need the whole block.
    # Tunable: TFS_STREAM_CHUNK_BYTES (0 disables).
    stream_chunk_bytes = envutil.env_int(
        "TFS_STREAM_CHUNK_BYTES", 64 * 1024 * 1024
    )

    def _stream_plan(
        self,
        program: Program,
        block,
        infos,
        host_stage,
        check_independence: bool = True,
    ) -> Optional[int]:
        """Rows per chunk for streamed ingestion of this block, or None
        to take the unstreamed path (device-resident inputs, small
        blocks, host-staged inputs, cross-row programs)."""
        chunk = self.stream_chunk_bytes
        if not chunk or host_stage:
            return None
        total = 0
        n_rows = None
        for name in program.input_names:
            value = block[program.column_for_input(name)]
            if isinstance(value, jax.Array):
                return None  # already on device: nothing to stream
            arr = np.asarray(value)
            if arr.dtype == object:
                return None
            st = dtypes.coerce(infos[name].scalar_type)
            total += arr.size * np.dtype(st.np_dtype).itemsize
            n_rows = arr.shape[0] if arr.ndim else None
            if n_rows is None:
                return None
        if n_rows is None or total < 2 * chunk:
            return None
        n_chunks = -(-total // chunk)
        per = -(-n_rows // n_chunks)
        if per >= n_rows:
            return None
        if check_independence:
            # statically classified once per program (analysis.rowdep);
            # unclassifiable programs probe at the EXACT executed sizes
            # (semantic block size, chunk size, tail size) — sound
            # against python control flow branching at any threshold
            specs = analysis.input_specs_for(program, infos)
            tail = n_rows % per or per
            if specs is None or not analysis.rows_independent(
                program, specs, (n_rows, per, tail)
            ):
                return None
        return per

    def _run_block_streamed(
        self,
        program: Program,
        block,
        infos,
        per: int,
        rows_level: bool = False,
        pf_stats: Optional[Dict[str, Any]] = None,
        device=None,
        bi: int = 0,
        session=None,
        device_resolver=None,
    ) -> Dict[str, Any]:
        """Chunked h2d + dispatch: equal row slices (last may be short, so
        at most two executables trace), outputs concatenated on device.
        ``device``: chunk staging target under the device-pool scheduler
        (the whole block's chunks stream to the block's assigned device).

        The chunks run through a :class:`prefetch.Prefetcher`: chunk k+1's
        cast + ``device_put`` happen on the staging thread while chunk k's
        compute dispatches, and each chunk's staged buffers are donated to
        the executable (fresh per chunk by construction), so HBM holds at
        most the prefetch window of input chunks.  ``rows_level`` picks the
        vmapped cell entry (map_rows); ``pf_stats`` (a caller-LOCAL dict,
        never a live Prefetcher's stats — the outer staging thread writes
        those concurrently) accumulates the chunk prefetcher's totals for
        the caller's span record.  ``device_resolver``: zero-arg callable
        returning the CURRENT ``(device index, device)`` target under the
        pool — re-resolved per retry attempt so chunk re-dispatches
        follow a quarantine redirect instead of hammering a drained
        device (serial callers leave it None: device 0, ``device``)."""
        names = program.input_names
        arrays = {}
        n_rows = 0
        for nm in names:
            arrays[nm] = np.asarray(block[program.column_for_input(nm)])
            n_rows = arrays[nm].shape[0]
        starts = list(range(0, n_rows, per))
        # shape-canonical chunks: pad the short tail chunk up to ``per``
        # so ONE executable serves every chunk (the independence proof
        # already ran at the tail size; map_rows chunks are independent
        # by construction).  The pad rows are sliced off the concat.
        pad_tail = bucketing.enabled() and n_rows % per != 0

        def stage(k, _dev=None):
            sl = slice(starts[k], min(starts[k] + per, n_rows))
            staged = {
                nm: arrays[nm][sl] for nm in names
            }
            if pad_tail and sl.stop - sl.start < per:
                staged = {
                    nm: bucketing.pad_rows(v, per) for nm, v in staged.items()
                }
            return {
                nm: self._device_value(
                    v,
                    dtypes.coerce(infos[nm].scalar_type),
                    device=_dev if _dev is not None else device,
                )
                for nm, v in staged.items()
            }

        donate = prefetch.donate_inputs()
        run = (
            self._rows_run(program, donate)
            if rows_level
            else self._block_run(program, donate)
        )
        pf = prefetch.Prefetcher(stage, len(starts))
        if session is None:
            # chunk boundary = cancellation checkpoint (the streamed
            # analog of the block-boundary check); a no-op contextvar
            # read without an active scope
            outs: List[Dict[str, Any]] = []
            for inputs in pf:
                cancellation.checkpoint()
                outs.append(run(inputs))
                del inputs
        else:
            # chunk-granular retry: each chunk dispatch is its own
            # attempt unit (fault injection keys on the BLOCK index, so
            # a block-selected spec fires per chunk — deterministic
            # either way).  A retried chunk re-stages on the consumer
            # thread; its fresh buffers stay donation-eligible.  No OOM
            # split here: chunks are already the streaming granularity,
            # so a chunk OOM surfaces with its exact row range.
            outs = []
            for k, inputs in enumerate(pf):
                # chunk boundary = cancellation checkpoint, same as the
                # serial branch above (lint: checkpoint-coverage) — a
                # deadline cuts the streamed dispatch between chunks
                # instead of waiting out the whole block
                cancellation.checkpoint()
                lo = starts[k]
                hi = min(starts[k] + per, n_rows)
                holder = {"v": inputs}
                del inputs

                def attempt(a, dev_i, _k=k, _h=holder):
                    ins = _h.pop("v", None)
                    if a > 0 or ins is None:
                        # re-stage to the CURRENT effective device, so a
                        # retried chunk follows a quarantine redirect
                        dev_now = (
                            device_resolver()[1]
                            if device_resolver is not None
                            else None
                        )
                        ins = stage(_k, dev_now)
                    return run(ins)

                outs.append(
                    session.run(
                        bi,
                        hi - lo,
                        attempt,
                        device=(
                            (lambda: device_resolver()[0])
                            if device_resolver is not None
                            else 0
                        ),
                        row_range=(lo, hi),
                    )
                )
        if pf_stats is not None:
            pf_stats["items"] += pf.stats["items"]
            pf_stats["stage_s"] += pf.stats["stage_s"]
            pf_stats["wait_s"] += pf.stats["wait_s"]
        if (
            session is not None
            and device_resolver is not None
            and session.pool is not None
            and session.pool.quarantined
        ):
            # a mid-block quarantine redirect left chunk outputs on more
            # than one device; co-locate them on the current effective
            # device before the concat (committed arrays on different
            # devices cannot feed one op)
            _, dev_final = device_resolver()
            outs = [
                {k2: jax.device_put(v, dev_final) for k2, v in o.items()}
                for o in outs
            ]
        cat = {k: jnp.concatenate([o[k] for o in outs]) for k in outs[0]}
        if pad_tail:
            cat = {k: v[:n_rows] for k, v in cat.items()}
        return cat

    def _bucket_plan(
        self,
        program: Program,
        frame: TensorFrame,
        infos,
        host_stage,
        rows_level: bool,
        trim: bool,
        stream_plans: Sequence[Optional[int]],
    ) -> List[Optional[int]]:
        """Per-block bucket targets for shape-canonical execution, or None
        per block to run the exact shape.

        ``map_rows`` blocks pad freely — the cell program is vmapped over
        the row axis, so rows are independent by construction.
        ``map_blocks`` padding is gated on the shared row-independence
        gate (``analysis.rows_independent``): the memoized size-generic
        classification answers first, and the exact-size compile probe
        (``segment_compile.cached_rows_independent``) runs on
        ``UNKNOWN`` — together rejecting cross-row programs, block-size
        literals, and size-branching python control flow (for classified
        programs, up to the canonical-probe envelope documented in
        ``analysis/rowdep.py``; ``TFS_ANALYZE_XCHECK=1`` is the fence).
        Refused programs keep exact shapes and their per-size
        executables.  Out of scope, by design: trimmed maps (the output
        row count is program-defined, so sliced-back padding has no
        defined contract), host-staged ``map_blocks`` inputs (the staged
        cell shape is unknown before the stage fn runs, so the proof
        cannot be posed), and blocks already streamed in canonical chunks
        (``stream_plans``)."""
        nb = frame.num_blocks
        none_plan: List[Optional[int]] = [None] * nb
        if trim or not bucketing.enabled():
            return none_plan
        if host_stage and not rows_level:
            return none_plan
        sizes = frame.block_sizes
        targets = [
            bucketing.bucket_for(n)
            if n > 0 and stream_plans[bi] is None
            else None
            for bi, n in enumerate(sizes)
        ]
        targets = [
            t if t is not None and t != sizes[bi] else None
            for bi, t in enumerate(targets)
        ]
        if all(t is None for t in targets):
            return none_plan
        if not rows_level:
            # one structural proof across every (real, padded) size pair
            # this frame will execute
            proof_sizes = sorted(
                {sizes[bi] for bi, t in enumerate(targets) if t is not None}
                | {t for t in targets if t is not None}
            )
            specs = analysis.input_specs_for(program, infos)
            if specs is None or not analysis.rows_independent(
                program, specs, proof_sizes
            ):
                return none_plan
        return targets

    def _frame_fresh(self, frame: TensorFrame) -> bool:
        """The ONE freshness rule behind input donation, shared by the
        dispatch loop and :meth:`warmup` (the warmup executable must
        carry the same donation aliasing the first real dispatch will,
        or the persistent-cache keys diverge).

        Residency is a COLUMN property (one array sliced per block), so
        freshness is decided once per frame, on the consumer thread.  It
        covers EVERY column, not just the program's inputs, because the
        worker's ``frame.block()`` slices all of them — and slicing a
        device column (jax.Array.__getitem__) is a jit entry point,
        which the Prefetcher contract keeps off the worker.  Donation
        eligibility only needs the program's input columns host-side,
        and all-host is a superset of that."""
        return all(
            not frame.column(ci.name).is_device for ci in frame.schema
        )

    def map_blocks(
        self,
        program: Program,
        frame: TensorFrame,
        trim: bool = False,
        host_stage: Optional[Mapping[str, Any]] = None,
    ) -> TensorFrame:
        """``mapBlocks`` (``DebugRowOps.scala:290-393``) /
        ``mapBlocksTrimmed`` (trim=True: output row count may differ, no
        passthrough columns — ``Operations.scala:61-80``).

        All blocks are dispatched asynchronously; no host sync happens here
        (output shapes are static, so row-count validation needs no data).
        ``host_stage``: input name -> host fn(cells) -> [rows, *cell] array,
        run per block before the device program (binary decode, bucketing);
        it executes on ONE prefetch staging thread in block order — under
        the device pool too, where only h2d/compute/readback parallelize —
        so block N+1's host stage AND h2d transfer overlap block N's
        device compute.

        Device pool (``TFS_DEVICE_POOL``, host-fresh multi-block frames on
        a >=2-device host): blocks dispatch across all local devices and
        the verb returns HOST-assembled output columns — each block's D2H
        copy starts as it completes, overlapping later blocks' compute,
        and the verb synchronizes on the last block (the trade the pool
        makes: cross-device parallelism for device residency, so a
        chained verb re-stages its inputs).  The serial single-device
        path keeps the fully async, device-resident contract."""
        host_stage = _with_prelude(program, host_stage)
        with observability.verb_span(
            "map_blocks", frame.num_rows, frame.num_blocks
        ) as span:
            infos = validation.check_map_inputs(
                program, frame, "map_blocks", host_staged=host_stage or ()
            )
            span.mark("validate")
            out_blocks = self._map_dispatch(
                program, frame, infos, host_stage, span,
                rows_level=False, trim=trim,
            )
            span.mark("dispatch")
            return self._build_map_output(frame, out_blocks, trim)

    def _map_dispatch(
        self,
        program: Program,
        frame: TensorFrame,
        infos,
        host_stage,
        span,
        rows_level: bool,
        trim: bool,
    ) -> List[Dict[str, Any]]:
        """Shared block loop of the two map verbs, prefetched: up to
        ``TFS_PREFETCH_BLOCKS`` blocks are staged (host cast + host_stage +
        async ``device_put``) on a worker thread ahead of the compute
        dispatches, and blocks whose every input buffer was freshly staged
        run through a donating executable (``_block_run``/``_rows_run``) so
        steady-state HBM holds at most the prefetch window of input blocks.
        Blocks with device-resident inputs (cached frames, chained verbs)
        keep the plain non-donating entries — donating a shared column
        buffer would corrupt the frame (prefetch.py's safety contract).
        Streamed blocks (``_stream_plan``) prefetch+donate at chunk
        granularity instead."""
        verb = "map_rows" if rows_level else "map_blocks"
        if frame.num_rows == 0 and not trim:
            # empty-frame contract: a non-trimmed map of an empty frame is
            # an empty frame with the program's inferred output schema —
            # no trace, no compile, no program execution.  (A TRIMMED map
            # still applies the program to the empty block below: its
            # output row count is program-defined, e.g. a per-block
            # summary row, and inference cannot fabricate those values.)
            return [
                self._empty_map_outputs(
                    program, frame, infos, host_stage, rows_level
                )
            ]
        # sharded frame cache (round 10, ops/frame_cache.py): when the
        # frame's blocks are resident on their affinity devices, each
        # block dispatches on the device that already holds it — no
        # staging lanes, no H2D, no donation (shards are shared state).
        # This path removes the old "device-resident frames stay serial"
        # restriction for every map verb.
        cache = frame_cache.active_cache(frame)
        if cache is not None:
            return self._map_dispatch_sharded(
                program, frame, infos, host_stage, span, rows_level, trim,
                cache,
            )
        # plan on the caller thread: _stream_plan and _bucket_plan may
        # trace (row-independence proofs); all jit entry points stay off
        # the worker
        plans = [
            self._stream_plan(
                program, frame.block(bi), infos, host_stage,
                check_independence=not rows_level,
            )
            for bi in range(frame.num_blocks)
        ]
        # shape-canonical bucket targets (one executable for every block
        # size of this program); streamed blocks canonicalize at chunk
        # granularity inside _run_block_streamed instead
        pads = self._bucket_plan(
            program, frame, infos, host_stage, rows_level, trim, plans
        )
        donate = prefetch.donate_inputs()
        fresh = self._frame_fresh(frame)
        # device-pool scheduler (ops/device_pool.py): a host-fresh multi-
        # block frame spreads its independent blocks across all local
        # devices — per-device staging lanes, async dispatch, overlapped
        # readback.  Device-resident frames stay serial on their device
        # (splitting a cached column across the pool would shuffle HBM),
        # and the mesh executor opts out (supports_device_pool).
        pool_devs = (
            device_pool.pool_devices()
            if (self.supports_device_pool and fresh and frame.num_blocks > 1)
            else []
        )
        # block-level fault tolerance (ops/fault_tolerance.py): None when
        # TFS_BLOCK_RETRIES=0 and no fault injection — the default — so
        # the dispatch loops below are byte-identical to the retry-free
        # engine and the suite's trace/compile fences stay deterministic
        session = fault_tolerance.frame_session(frame.num_blocks, verb=verb)
        if len(pool_devs) >= 2:
            return self._map_dispatch_pool(
                program, frame, infos, host_stage, span, rows_level, trim,
                plans, pads, donate, pool_devs, session,
            )
        # only spin up a staging thread when some block will actually
        # stage on it; otherwise (device-resident frame, or every block
        # streamed at chunk level) keep the plain consumer loop
        to_stage = fresh and any(p is None for p in plans)

        def stage(bi):
            if plans[bi] is not None:
                return None  # streamed inline, chunk-level prefetch
            return self._device_inputs(
                program, frame.block(bi), infos, host_stage, pad_to=pads[bi]
            )

        pf = prefetch.Prefetcher(stage, frame.num_blocks) if to_stage else None
        # chunk-prefetcher stats accumulate here, NOT into pf.stats: the
        # block staging thread writes pf.stats concurrently with this
        # consumer loop, and += on a shared dict entry would lose updates
        chunk_stats = {"items": 0, "stage_s": 0.0, "wait_s": 0.0}
        block_sizes = frame.block_sizes
        out_blocks: List[Dict[str, Any]] = []
        items = pf if pf is not None else (
            None for _ in range(frame.num_blocks)
        )
        for bi, staged in enumerate(items):
            # cooperative cancellation (bridge deadlines / drain): the
            # block boundary is the check granularity — one contextvar
            # read when no scope is active
            cancellation.checkpoint()
            t_blk = observability.trace_now()  # flight recorder (r13)
            n_rows = block_sizes[bi]
            if plans[bi] is not None:
                outs = self._run_block_streamed(
                    program, frame.block(bi), infos, plans[bi],
                    rows_level=rows_level, pf_stats=chunk_stats,
                    bi=bi, session=session,
                )
            elif session is not None:
                outs = self._run_block_ft(
                    session, program, frame, bi, infos, host_stage,
                    pads[bi], rows_level, trim, donate and fresh, staged,
                )
                del staged
            else:
                inputs = (
                    staged
                    if staged is not None
                    else self._device_inputs(  # device-resident block
                        program, frame.block(bi), infos, host_stage,
                        pad_to=pads[bi],
                    )
                )
                if rows_level:
                    outs = self._rows_run(program, donate and fresh)(inputs)
                elif donate and fresh:
                    outs = self._block_run(program, True)(inputs)
                else:
                    outs = self._run_block_program(program, inputs)
                del inputs, staged  # drop staged refs (donation hygiene)
                if pads[bi] is not None:
                    # bucket-padded execution: slice the pad rows back off
                    # (row-independence guarantees real rows' values are
                    # bit-identical to the exact-shape path)
                    outs = {k: v[:n_rows] for k, v in outs.items()}
            self._check_block_outputs(program, outs, n_rows, rows_level, trim)
            # request attribution (round 15): one contextvar read per
            # block when no ledger is active — the documented hot-path
            # cost of the attribution layer on the serial loop
            observability.note_request_block(0, n_rows)
            observability.trace_complete(
                f"{verb} b{bi}", "serial", t_blk, block=bi, rows=n_rows
            )
            out_blocks.append(outs)
        # the loop consumed every item, so the staging thread has finished
        # (its last stats write happened-before the last queue get): pf.stats
        # is safe to read and merge with the chunk prefetchers' totals.
        # ``items`` counts buffers actually staged ahead: whole blocks the
        # worker staged plus streamed chunks — never the trivial None
        # passes for streamed/device-resident blocks
        staged_blocks = (
            sum(1 for p in plans if p is None) if pf is not None else 0
        )
        stage_s = (pf.stats["stage_s"] if pf else 0.0) + chunk_stats["stage_s"]
        wait_s = (pf.stats["wait_s"] if pf else 0.0) + chunk_stats["wait_s"]
        span.annotate(
            "prefetch",
            {
                "items": staged_blocks + chunk_stats["items"],
                "depth": prefetch.prefetch_depth(),
                "stage_s": round(stage_s, 6),
                "wait_s": round(wait_s, 6),
                "overlap_ratio": round(
                    prefetch.overlap_ratio(stage_s, wait_s), 4
                ),
                # whether donation actually applied to this verb's blocks,
                # not just the knob: a device-resident frame never donates
                "donate": donate and fresh,
            },
        )
        if session is not None and session.events():
            span.annotate("fault_tolerance", session.record())
        return out_blocks

    def _check_block_outputs(
        self, program: Program, outs, n_rows: int, rows_level: bool,
        trim: bool,
    ) -> None:
        """Per-block output validation shared by the serial and pooled
        dispatch loops: the non-trimmed row-count contract, the trimmed
        agreement contract, and the shape-hint check."""
        verb = "map_rows" if rows_level else "map_blocks"
        if rows_level:
            pass  # row programs are per-cell; no block row-count check
        elif not trim:
            for name, v in outs.items():
                if v.ndim == 0 or v.shape[0] != n_rows:
                    raise ValidationError(
                        f"map_blocks: output {name!r} has shape "
                        f"{v.shape} but the input block has {n_rows} "
                        f"rows; a non-trimmed map must preserve the "
                        f"row count (use map_blocks_trimmed to "
                        f"change it)."
                    )
        else:
            counts = {
                v.shape[0] if v.ndim else None for v in outs.values()
            }
            if len(counts) != 1 or None in counts:
                raise ValidationError(
                    f"map_blocks_trimmed: outputs disagree on row "
                    f"count: { {k: v.shape for k, v in outs.items()} }"
                )
        _check_shape_hints(program, outs, verb, cell_level=rows_level)

    # -- fault-tolerant dispatch (round 9, ops/fault_tolerance.py) ----------

    def _lane_next(self, it, lane_dead, li: int, session, pool):
        """Pull the next staged value from a pool lane.  Without a retry
        session, staging failures propagate exactly as before.  With
        one, a failed lane is marked dead (its worker has exited; its
        Prefetcher raises once then StopIterations), the failure counts
        against the lane's device, and the consumer re-stages every
        later block of that lane itself — recovery trades the staging
        overlap for completing the frame."""
        if lane_dead[li]:
            return None
        try:
            return next(it)
        except StopIteration:
            raise
        except BaseException as exc:  # noqa: BLE001 - recovery below
            if session is None:
                raise
            lane_dead[li] = True
            if pool is not None and li < len(pool.devices):
                pool.note_block_failure(li)
            _log.warning(
                "staging lane %d failed (%r); re-staging its remaining "
                "blocks on the consumer thread",
                li,
                exc,
            )
            return None

    def _run_block_ft(
        self,
        session,
        program: Program,
        frame: TensorFrame,
        bi: int,
        infos,
        host_stage,
        pad_to: Optional[int],
        rows_level: bool,
        trim: bool,
        donate: bool,
        staged,
        devices: Optional[Sequence[Any]] = None,
        pool=None,
        di: Optional[int] = None,
    ) -> Dict[str, Any]:
        """One map-verb block dispatch under the retry session: attempt 0
        consumes the prefetched ``staged`` inputs (when they target the
        effective device), every later attempt RE-STAGES from the host
        frame — a donated-then-failed buffer is never re-used, and a
        quarantine redirect lands fresh buffers on the new device.  OOM
        degrades via :meth:`_oom_split_closure`.  Shared by the serial
        loop (``devices``/``pool`` None) and the pooled loop.

        Re-staging re-runs any ``host_stage`` fn for the retried block —
        the same semantics as Spark's lineage replay, which re-executes
        the whole partition pipeline on task retry and therefore
        requires deterministic tasks.  The retry contract requires the
        same of stage fns: deterministic per (block, cells), like the
        decode fns that motivate ``host_stage``.  A stage fn whose
        output depends on invocation order cannot participate in block
        retry (run it with ``TFS_BLOCK_RETRIES=0``, where every error
        surfaces unretried)."""
        n_rows = frame.block_sizes[bi]
        holder = {"staged": staged}

        def attempt(a: int, dev_i: Optional[int]) -> Dict[str, Any]:
            first = holder.pop("staged", None)  # at most once, ever
            inputs = first if (a == 0 and (pool is None or dev_i == di)) else None
            if inputs is None:
                dev = (
                    devices[dev_i]
                    if devices is not None and dev_i is not None
                    else None
                )
                inputs = self._device_inputs(
                    program, frame.block(bi), infos, host_stage,
                    pad_to=pad_to, device=dev,
                )
            if rows_level:
                outs = self._rows_run(program, donate)(inputs)
            elif donate:
                outs = self._block_run(program, True)(inputs)
            else:
                outs = self._run_block_program(program, inputs)
            del inputs
            if pad_to is not None:
                outs = {k: v[:n_rows] for k, v in outs.items()}
            return outs

        device = (
            (lambda: pool.effective_device(di))
            if pool is not None
            else (0 if di is None else di)  # serial dispatch = device 0
        )
        oom_split = self._oom_split_closure(
            session, program, frame, bi, infos, host_stage, rows_level,
            trim, devices, pool, di,
        )
        return session.run(
            bi, n_rows, attempt, device=device, oom_split=oom_split
        )

    def _oom_split_closure(
        self,
        session,
        program: Program,
        frame: TensorFrame,
        bi: int,
        infos,
        host_stage,
        rows_level: bool,
        trim: bool,
        devices,
        pool,
        di,
    ):
        """The OOM-degradation policy for one map-verb block: split the
        block in half and re-dispatch (recursively, floor
        ``TFS_MIN_SPLIT_ROWS``) when that is provably semantics-safe —
        ``map_rows`` is row-independent by construction, ``map_blocks``
        must pass the jaxpr proof at EVERY size the split can reach.
        Trimmed maps (program-defined output row count), host-staged
        blocks (one-unit staging contract), and cross-row programs
        surface a :class:`fault_tolerance.BlockExecutionError` naming
        the block and row range instead."""
        n_rows = frame.block_sizes[bi]
        verb = "map_rows" if rows_level else "map_blocks"

        def refuse(exc: BaseException, why: str):
            raise fault_tolerance.BlockExecutionError(
                f"{verb}: block {bi} rows [0, {n_rows}) exhausted device "
                f"memory and cannot degrade by splitting: {why}"
            ) from exc

        def split(exc: BaseException) -> Dict[str, Any]:
            floor = fault_tolerance.min_split_rows()
            if trim:
                refuse(exc, "trimmed maps define their own output row "
                            "count, so half-block outputs cannot be "
                            "reassembled")
            if host_stage:
                refuse(exc, "host-staged blocks stage as one unit")
            if n_rows < 2 * floor:
                refuse(
                    exc,
                    f"the block is already at the split floor "
                    f"(TFS_MIN_SPLIT_ROWS={floor})",
                )
            if not rows_level:
                # every size the recursive split can reach, proven
                # row-independent in one shot (memoized on the program)
                sizes = set()
                stack = [(0, n_rows)]
                while stack:
                    lo, hi = stack.pop()
                    sizes.add(hi - lo)
                    if hi - lo >= 2 * floor:
                        mid = (lo + hi) // 2
                        stack += [(lo, mid), (mid, hi)]
                specs = analysis.input_specs_for(program, infos)
                if specs is None or not analysis.rows_independent(
                    program, specs, sorted(sizes)
                ):
                    refuse(
                        exc,
                        "the program is not provably row-independent "
                        "(cross-row outputs cannot be recomputed from "
                        "half blocks)",
                    )
            dev_i = (
                pool.effective_device(di)
                if pool is not None
                else (0 if di is None else di)
            )
            dev = devices[dev_i] if devices is not None else None
            mid = n_rows // 2
            left = self._split_range(
                session, program, frame, bi, infos, rows_level, 0, mid,
                dev, dev_i,
            )
            right = self._split_range(
                session, program, frame, bi, infos, rows_level, mid,
                n_rows, dev, dev_i,
            )
            session.note_split(bi)
            return {
                k: jnp.concatenate([left[k], right[k]]) for k in left
            }

        return split

    def _split_range(
        self,
        session,
        program: Program,
        frame: TensorFrame,
        bi: int,
        infos,
        rows_level: bool,
        lo: int,
        hi: int,
        dev,
        dev_i: Optional[int],
    ) -> Dict[str, Any]:
        """Dispatch rows ``[lo, hi)`` of block ``bi``, splitting again on
        a further OOM until ``TFS_MIN_SPLIT_ROWS``.  Sub-dispatches use
        the plain non-donating entries (fresh small buffers; donation
        would fork another executable per split size for no HBM win) and
        their injected-fault site is ``"split"`` so attempt-selected
        specs never re-fire on recovery work."""
        floor = fault_tolerance.min_split_rows()
        try:
            faults.maybe_inject(bi, 0, dev_i, hi - lo, site="split")
            block = frame.block(bi)
            sub = {k: v[lo:hi] for k, v in block.items()}
            inputs = self._device_inputs(
                program, sub, infos, None, device=dev
            )
            if rows_level:
                return program.vmapped()(inputs)
            return self._run_block_program(program, inputs)
        except BaseException as exc:  # noqa: BLE001 - OOM-only recovery
            if not faults.is_oom(exc):
                raise
            if hi - lo < 2 * floor:
                raise fault_tolerance.BlockExecutionError(
                    f"block {bi} rows [{lo}, {hi}) exhausted device "
                    f"memory at the split floor (TFS_MIN_SPLIT_ROWS="
                    f"{floor}); this row range does not fit on the device"
                ) from exc
            mid = (lo + hi) // 2
            left = self._split_range(
                session, program, frame, bi, infos, rows_level, lo, mid,
                dev, dev_i,
            )
            right = self._split_range(
                session, program, frame, bi, infos, rows_level, mid, hi,
                dev, dev_i,
            )
            session.note_split(bi)
            return {
                k: jnp.concatenate([left[k], right[k]]) for k in left
            }

    def _map_dispatch_pool(
        self,
        program: Program,
        frame: TensorFrame,
        infos,
        host_stage,
        span,
        rows_level: bool,
        trim: bool,
        plans: Sequence[Optional[int]],
        pads: Sequence[Optional[int]],
        donate: bool,
        devices: Sequence[Any],
        session=None,
    ) -> List[Dict[str, Any]]:
        """Device-pool edition of the map-verb block loop: blocks dispatch
        round-robin/least-loaded across ``devices`` with per-device
        staging lanes and a bounded in-flight readback window per device
        (``ops/device_pool.py``).

        Each lane's worker stages its device's next blocks (host cast +
        ``host_stage`` + bucket pad + async ``device_put`` TO that
        device) while the consumer thread dispatches in global block
        order — dispatch is async, so device k computes block N while the
        consumer hands block N+1 to device k+1 and lane k stages block
        N+2.  Completed blocks start their D2H copy immediately and are
        materialised at most ``depth`` blocks behind dispatch, so output
        assembly overlaps later blocks' compute.  Outputs land in
        ``out_blocks[bi]`` (host numpy) strictly by block index — the
        pooled result is bit-identical to the serial path, reassembled in
        block order no matter which device finishes first.  Only called
        for host-FRESH frames, so the donation rules carry over
        unchanged: every staged buffer is fresh by construction (donate
        when the backend supports it), and no shared device-resident
        column can reach a donating executable.  Streamed blocks
        (``plans``) keep chunk-granular staging, pointed at their
        assigned device."""
        verb = "map_rows" if rows_level else "map_blocks"
        sizes = frame.block_sizes
        nb = frame.num_blocks
        assignment = device_pool.assign(sizes, len(devices))
        depth = prefetch.prefetch_depth()
        pool = device_pool.PoolRun(devices, assignment, depth or 1)
        if session is not None:
            session.pool = pool  # quarantine state lives on the PoolRun

        def stage_block(bi, dev):
            if plans[bi] is not None:
                return None  # streamed inline, chunk-level staging below
            return self._device_inputs(
                program, frame.block(bi), infos, host_stage,
                pad_to=pads[bi], device=dev,
            )

        if host_stage:
            # the host_stage contract predates the pool: stage fns run on
            # ONE staging thread in strict block order (they may be
            # stateful or non-reentrant).  Pooling keeps that contract —
            # a single lane stages every block in order, device_put
            # pointed at each block's assigned device; compute dispatch
            # and readback still parallelize across the pool.
            single = prefetch.Prefetcher(
                lambda bi: stage_block(bi, devices[assignment[bi]]),
                nb,
                name="tfs-pool-stage",
            )
            lanes = [single]
            lane_iters = None
            single_iter = iter(single)
        else:
            lanes = device_pool.lanes(devices, assignment, stage_block)
            lane_iters = [iter(l) for l in lanes]
            single_iter = None
        chunk_stats = {"items": 0, "stage_s": 0.0, "wait_s": 0.0}
        out_blocks: List[Optional[Dict[str, Any]]] = [None] * nb
        lane_dead = [False] * (1 if single_iter is not None else len(devices))
        for bi in range(nb):
            cancellation.checkpoint()  # block boundary (pooled loop)
            t_blk = observability.trace_now()  # flight recorder (r13)
            di = assignment[bi]
            li = 0 if single_iter is not None else di
            it = single_iter if single_iter is not None else lane_iters[di]
            # the shared host_stage lane stages blocks for EVERY device,
            # so its death names no particular device — pass pool=None so
            # no healthy device gets charged a failure it didn't cause
            staged = self._lane_next(
                it, lane_dead, li, session,
                pool if single_iter is None else None,
            )
            n_rows = sizes[bi]
            di_eff = pool.effective_device(di) if session is not None else di
            if plans[bi] is not None:

                def _resolve(_di=di):
                    e = pool.effective_device(_di)
                    return e, devices[e]

                outs = self._run_block_streamed(
                    program, frame.block(bi), infos, plans[bi],
                    rows_level=rows_level, pf_stats=chunk_stats,
                    device=devices[di_eff], bi=bi, session=session,
                    device_resolver=_resolve if session is not None else None,
                )
            elif session is not None:
                outs = self._run_block_ft(
                    session, program, frame, bi, infos, host_stage,
                    pads[bi], rows_level, trim, donate, staged,
                    devices=devices, pool=pool, di=di,
                )
                del staged
                di_eff = pool.effective_device(di)
            else:
                if rows_level:
                    outs = self._rows_run(program, donate)(staged)
                elif donate:
                    outs = self._block_run(program, True)(staged)
                else:
                    outs = self._run_block_program(program, staged)
                del staged  # drop staged refs (donation hygiene)
                if pads[bi] is not None:
                    outs = {k: v[:n_rows] for k, v in outs.items()}
            self._check_block_outputs(program, outs, n_rows, rows_level, trim)
            observability.trace_complete(
                f"{verb} b{bi}", f"device/{di_eff}", t_blk,
                block=bi, rows=n_rows, device=di_eff,
            )
            pool.submit(bi, di_eff, n_rows, outs, out_blocks)
        pool.finish(out_blocks)
        staged_blocks = sum(1 for p in plans if p is None)
        stage_s = (
            sum(l.stats["stage_s"] for l in lanes) + chunk_stats["stage_s"]
        )
        wait_s = (
            sum(l.stats["wait_s"] for l in lanes) + chunk_stats["wait_s"]
        )
        span.annotate("device_pool", pool.record(stage_s, wait_s))
        span.annotate(
            "prefetch",
            {
                "items": staged_blocks + chunk_stats["items"],
                "depth": prefetch.prefetch_depth(),
                "stage_s": round(stage_s, 6),
                "wait_s": round(wait_s, 6),
                "overlap_ratio": round(
                    prefetch.overlap_ratio(stage_s, wait_s), 4
                ),
                "donate": donate,
            },
        )
        if session is not None and session.events():
            span.annotate("fault_tolerance", session.record())
        return out_blocks

    def _map_dispatch_sharded(
        self,
        program: Program,
        frame: TensorFrame,
        infos,
        host_stage,
        span,
        rows_level: bool,
        trim: bool,
        cache,
    ) -> List[Dict[str, Any]]:
        """Affinity-aware dispatch for sharded-cached frames
        (``ops/frame_cache.py``): block ``bi``'s program runs on the
        device that already holds its cached column slices — the
        residency plan IS the schedule (both come from
        ``device_pool.assign`` on the same block sizes), so there are no
        staging lanes and no H2D for resident blocks.  This removes the
        old "device-resident frames stay serial" restriction.

        Contract deltas from the host-fresh pool path, all deliberate:

        * **no donation, ever** — shards are shared frame state, and a
          donated shard would corrupt every later verb (the prefetch
          safety contract).  The executables here are the same plain
          entries the serial device-resident path runs, so results are
          bit-identical to it (and to the host path).
        * **no chunk streaming** — the bytes are already in HBM.
        * **evicted blocks re-stage inline** from the authoritative host
          columns to their affinity device (counted in
          ``h2d_bytes_staged``); residency is an accelerator, never a
          correctness dependency.
        * **fault tolerance re-stages from host**: a retry or a
          quarantine redirect never touches the (possibly dead) shard —
          every attempt past the first builds fresh buffers from the
          host copy on the CURRENT effective device, the same
          re-staging rule the pooled fresh path follows.

        Outputs return host-assembled through the pool's overlapped
        readback windows (the round-8 trade: cross-device parallelism
        for device residency of the OUTPUT; adoption in
        ``ops/pipeline.py`` recovers output residency for chained
        epochs)."""
        nb = frame.num_blocks
        sizes = frame.block_sizes
        verb = "map_rows" if rows_level else "map_blocks"
        # bucket targets still apply (device-side pad + slice); chunk
        # streaming never does — pass all-None stream plans
        pads = self._bucket_plan(
            program, frame, infos, host_stage, rows_level, trim,
            [None] * nb,
        )
        devices = cache.devices
        pool = device_pool.PoolRun(
            devices, cache.assignment, prefetch.prefetch_depth() or 1,
            affinity=True,
        )
        session = fault_tolerance.frame_session(nb, verb=verb, pool=pool)
        staged_cols = {
            program.column_for_input(n) for n in (host_stage or {})
        }
        out_blocks: List[Optional[Dict[str, Any]]] = [None] * nb
        hits = 0
        restaged = 0
        for bi in range(nb):
            cancellation.checkpoint()  # block boundary (sharded loop)
            t_blk = observability.trace_now()  # flight recorder (r13)
            di = cache.assignment[bi]
            di_eff = pool.effective_device(di) if session is not None else di
            shard = cache.shard(bi)
            block = dict(frame.block(bi))
            used = False
            if shard is not None and di_eff == di:
                for cname, v in shard.items():
                    if cname not in staged_cols:
                        block[cname] = v
                        used = True
            if used:
                hits += 1
                observability.note_cache_shard_hit()
            else:
                restaged += 1
                if session is not None and shard is not None:
                    session.note_cache_restage()
            n_rows = sizes[bi]
            if session is not None:
                staged = (
                    self._device_inputs(
                        program, block, infos, host_stage,
                        pad_to=pads[bi], device=devices[di_eff],
                    )
                    if used
                    else None
                )
                outs = self._run_block_ft(
                    session, program, frame, bi, infos, host_stage,
                    pads[bi], rows_level, trim, False, staged,
                    devices=devices, pool=pool, di=di,
                )
                del staged
                di_eff = pool.effective_device(di)
            else:
                inputs = self._device_inputs(
                    program, block, infos, host_stage,
                    pad_to=pads[bi], device=devices[di_eff],
                )
                if rows_level:
                    outs = self._rows_run(program, False)(inputs)
                else:
                    outs = self._run_block_program(program, inputs)
                del inputs
                if pads[bi] is not None:
                    outs = {k: v[:n_rows] for k, v in outs.items()}
            self._check_block_outputs(program, outs, n_rows, rows_level, trim)
            observability.trace_complete(
                f"{verb} b{bi}", f"device/{di_eff}", t_blk,
                block=bi, rows=n_rows, device=di_eff, shard_hit=used,
            )
            pool.submit(bi, di_eff, n_rows, outs, out_blocks)
        pool.finish(out_blocks)
        span.annotate("device_pool", pool.record())
        fc = cache.record()
        fc["shard_hits"] = hits
        fc["restaged_blocks"] = restaged
        span.annotate("frame_cache", fc)
        if session is not None and session.events():
            span.annotate("fault_tolerance", session.record())
        return out_blocks

    def _empty_map_outputs(
        self,
        program: Program,
        frame: TensorFrame,
        infos,
        host_stage,
        rows_level: bool,
    ) -> Dict[str, np.ndarray]:
        """Zero-row output block for the empty-frame map contract, shaped
        by ``Program.analyze`` (host-staged inputs run their stage fn over
        the zero cells so the staged cell shape is authoritative)."""
        specs: Dict[str, Any] = {}
        block0 = frame.block(0)  # the one empty block: real (0, *cell)
        # column slices, so shape-preserving stage fns infer correctly
        for n in program.input_names:
            if host_stage and n in host_stage:
                try:
                    arr = self._staged_value(
                        host_stage[n], block0[program.column_for_input(n)], n
                    )
                except ValidationError:
                    raise
                except Exception as e:
                    raise ValidationError(
                        f"host_stage for input {n!r} failed on an empty "
                        f"frame ({e!r}); a stage fn must accept zero cells "
                        f"for the empty-frame contract to apply."
                    ) from e
                st = dtypes.coerce(dtypes.from_numpy(arr.dtype))
                cell = arr.shape[1:]
            else:
                st = dtypes.coerce(infos[n].scalar_type)
                cell = tuple(infos[n].cell_shape)
            specs[n] = (st, cell if rows_level else (0,) + cell)
        outs: Dict[str, np.ndarray] = {}
        for s in program.analyze(specs):
            if not s.is_output:
                continue
            shape = tuple(s.shape)
            if rows_level:
                shape = (0,) + shape
            elif not shape or shape[0] != 0:
                raise ValidationError(
                    f"map_blocks: output {s.name!r} has inferred shape "
                    f"{shape} for an empty block; a non-trimmed map must "
                    f"preserve the row count (use map_blocks_trimmed to "
                    f"change it)."
                )
            outs[s.name] = np.zeros(shape, dtype=s.scalar_type.np_dtype)
        return outs

    def map_rows(
        self,
        program: Program,
        frame: TensorFrame,
        host_stage: Optional[Mapping[str, Any]] = None,
    ) -> TensorFrame:
        """``mapRows`` (``DebugRowOps.scala:396-477``): the program is written
        at *cell* level and vmapped over the block's rows.  Ragged input
        columns are resolved per row by shape-bucketing (`_map_rows_ragged`)."""
        host_stage = _with_prelude(program, host_stage)
        with observability.verb_span(
            "map_rows", frame.num_rows, frame.num_blocks
        ) as span:
            infos = validation.check_map_inputs(
                program,
                frame,
                "map_rows",
                host_staged=host_stage or (),
                allow_ragged=True,
            )
            span.mark("validate")
            ragged = [
                n
                for n in program.input_names
                if not (host_stage and n in host_stage)
                and frame.column(program.column_for_input(n)).is_ragged
            ]
            if ragged:
                out = self._map_rows_ragged(
                    program, frame, infos, host_stage, ragged
                )
                span.mark("dispatch")
                return out
            # row programs are row-independent BY CONSTRUCTION (the cell
            # program is vmapped), so big uncached blocks always stream
            # their h2d in chunks (check_independence=False in the plan)
            out_blocks = self._map_dispatch(
                program, frame, infos, host_stage, span,
                rows_level=True, trim=False,
            )
            span.mark("dispatch")
            return self._build_map_output(frame, out_blocks, trim=False)

    def _run_rows_bucket(
        self, program: Program, arrays: Dict[str, jnp.ndarray]
    ) -> Dict[str, Any]:
        """Run the vmapped cell program over one same-shape row bucket.
        The mesh executor overrides this to pad+shard the bucket (rows are
        independent under vmap, so padding is semantics-safe)."""
        return program.vmapped()(arrays)

    def _ragged_pad_ok(
        self,
        program: Program,
        ragged_name: str,
        rcells: Sequence[np.ndarray],
        uniform: Mapping[str, np.ndarray],
        sizes: Sequence[int],
    ) -> bool:
        """Whether the single ragged input's cells may pad along their
        lead (ragged) axis: jaxpr-proven elementwise along that axis, at
        the exact (real, bucketed) lengths.

        The proof is the shared row-independence gate
        (:func:`analysis.rows_independent` — static classification with
        the exact-size compile probe as fallback) posed on
        the *cell* program with the ragged axis as the lead dim and every
        uniform input bound as a trace param — within one row the uniform
        inputs are constants w.r.t. the cell axis, which is exactly the
        proof's "group" class.  A program that reduces, sorts, or
        position-indexes along the ragged axis (``v.sum()``,
        ``v[::-1]``...) fails and keeps the exact per-shape buckets."""
        rest = {c.shape[1:] for c in rcells}
        if len(rest) != 1:
            return False  # trailing dims ragged too: exact buckets
        st = np.asarray(rcells[0]).dtype
        key = (
            "ragged-pad",
            ragged_name,
            tuple(sorted(sizes)),
            rest.pop(),
            str(st),
            tuple(sorted((u, a.shape[1:], str(a.dtype)) for u, a in uniform.items())),
        )
        cache = program._derived
        if key in cache:
            return cache[key]
        try:
            dummies = {
                u: np.zeros(a.shape[1:], a.dtype) for u, a in uniform.items()
            }
            probe = Program(
                program._fn,
                program.input_names + program.param_names,
                program._declared_fetches,
                None,
                {**program.params, **dummies},
            )
            specs = {
                ragged_name: jax.ShapeDtypeStruct(
                    (2,) + rcells[0].shape[1:], st
                )
            }
            ok = analysis.rows_independent(probe, specs, sizes)
        except analysis.AnalysisXCheckError:
            raise  # the differential fence must fail loudly
        except Exception:
            ok = False
        cache[key] = ok
        return ok

    def _map_rows_ragged(
        self,
        program: Program,
        frame: TensorFrame,
        infos: Mapping[str, ColumnInfo],
        host_stage: Optional[Mapping[str, Any]],
        ragged_names: Sequence[str],
    ) -> TensorFrame:
        """Ragged ``map_rows`` via shape-bucketing (SURVEY.md §7 hard part 1).

        The reference resolves variable per-row lead dims one row at a time
        inside its converter (``TFDataOps.scala:86-103``,
        ``DataOps.inferPhysicalShape`` L105-144); a compiled-program engine
        instead groups rows by their concrete cell shapes and runs ONE
        vmapped execution per distinct shape (bounded recompilation: one
        trace per bucket shape, reused across blocks and calls).

        Round 7 tightens "bounded" from O(distinct shapes) — unbounded if
        the data does not cooperate — to O(log max-dim): when the program
        is provably elementwise along the ragged axis
        (:meth:`_ragged_pad_ok`), rows are grouped by the *geometric
        bucket* of their ragged lead dim (``bucketing.bucket_for``), each
        cell padded up to the bucket by edge repetition, and each output
        row sliced back to its own true length — the pad elements are the
        validity mask's complement, computed and discarded."""
        n = frame.num_rows
        cells: Dict[str, List[np.ndarray]] = {}
        uniform: Dict[str, np.ndarray] = {}
        for in_name in program.input_names:
            col = frame.column(program.column_for_input(in_name))
            if host_stage and in_name in host_stage:
                uniform[in_name] = self._staged_value(
                    host_stage[in_name], col.cells(), in_name
                )
                continue
            st = dtypes.coerce(infos[in_name].scalar_type)
            if in_name in ragged_names:
                cells[in_name] = [
                    np.asarray(c).astype(st.np_dtype, copy=False)
                    for c in col.cells()
                ]
            else:
                uniform[in_name] = np.asarray(col.data).astype(
                    st.np_dtype, copy=False
                )

        # cell-axis bucket padding: single ragged input, pads proven safe
        pad_lengths: Dict[int, int] = {}
        if bucketing.enabled() and len(ragged_names) == 1:
            r = ragged_names[0]
            lengths = sorted({c.shape[0] for c in cells[r] if c.shape[0] > 0})
            targets = {d: bucketing.bucket_for(d) for d in lengths}
            if any(t != d for d, t in targets.items()):
                proof_sizes = sorted(set(lengths) | set(targets.values()))
                if self._ragged_pad_ok(
                    program, r, cells[r], uniform, proof_sizes
                ):
                    pad_lengths = {d: t for d, t in targets.items() if t != d}

        buckets: Dict[Tuple, List[int]] = {}
        for i in range(n):
            key = tuple(
                (pad_lengths.get(cells[r][i].shape[0], cells[r][i].shape[0]),)
                + cells[r][i].shape[1:]
                for r in ragged_names
            )
            buckets.setdefault(key, []).append(i)

        out_cells: Dict[str, List[Any]] = {}
        for key in sorted(buckets):  # deterministic trace order
            idxs = buckets[key]
            arrays: Dict[str, jnp.ndarray] = {}
            for r in ragged_names:
                target = key[0][0] if pad_lengths else None
                arrays[r] = jnp.asarray(
                    np.stack(
                        [
                            bucketing.pad_rows(cells[r][i], target)
                            if target is not None
                            else cells[r][i]
                            for i in idxs
                        ]
                    )
                )
            for u, arr in uniform.items():
                arrays[u] = jnp.asarray(arr[idxs])
            outs = self._run_rows_bucket(program, arrays)
            hosts = {name: np.asarray(v) for name, v in outs.items()}
            if not pad_lengths:
                _check_shape_hints(program, outs, "map_rows", cell_level=True)
                for name, host in hosts.items():
                    if name not in out_cells:
                        out_cells[name] = [None] * n
                    for j, i in enumerate(idxs):
                        out_cells[name][i] = host[j]
                continue
            # padded bucket: every output tracks the ragged axis on dim 0
            # (guaranteed by the _ragged_pad_ok proof) — slice each row's
            # outputs back to its own true length, and hint-check once per
            # distinct true length (shapes differ within the bucket)
            hint_checked: set = set()
            for j, i in enumerate(idxs):
                d = cells[ragged_names[0]][i].shape[0]
                row = {
                    name: host[j][:d] if d < host[j].shape[0] else host[j]
                    for name, host in hosts.items()
                }
                if program.shape_hints and d not in hint_checked:
                    _check_shape_hints(
                        program,
                        {name: cell[None] for name, cell in row.items()},
                        "map_rows",
                        cell_level=True,
                    )
                    hint_checked.add(d)
                for name, cell in row.items():
                    if name not in out_cells:
                        out_cells[name] = [None] * n
                    out_cells[name][i] = cell

        from ..frame import _column_from_cells

        cols = [
            _column_from_cells(name, out_cells[name])
            for name in sorted(out_cells)
        ]
        shadowed = {c.info.name for c in cols}
        for cname in frame.column_names:
            if cname not in shadowed:
                cols.append(frame.column(cname))
        return TensorFrame(cols, frame.offsets)

    def warmup(
        self,
        program: Program,
        frame: TensorFrame,
        rows_level: bool = False,
        host_stage: Optional[Mapping[str, Any]] = None,
    ) -> List[str]:
        """AOT-compile the executables the map verbs will actually run
        for ``frame``, returning their fingerprints.

        "Actually" is load-bearing: the executed sizes come from the
        same :meth:`_bucket_plan` the verbs use (a cross-row program
        keeps its exact per-size shapes — bucketed signatures would be
        dead weight), and when the verbs would take the donating entry
        (fresh host frame on a donation-capable backend) the donated jit
        entry itself is lowered, so the persistent-cache key matches the
        first real dispatch.  ``host_stage`` inputs are probed on one
        row (zero rows for an empty frame) to learn the staged cell
        shape.  Not covered: the chunked-streaming path's chunk-sized
        executables (blocks past ``stream_chunk_bytes`` compile on first
        use).

        With the persistent compilation cache configured
        (``TFS_COMPILE_CACHE``), this is the cold-start path: a fresh
        process warms every executable from disk before the first block
        arrives, paying deserialization instead of XLA.  Without the
        cache it duplicates compile work — configure the cache first."""
        host_stage = _with_prelude(program, host_stage)
        verb = "map_rows" if rows_level else "map_blocks"
        if rows_level and any(
            frame.column(program.column_for_input(n)).is_ragged
            and not (host_stage and n in host_stage)
            for n in program.input_names
        ):
            raise ValidationError(
                "warmup: ragged columns are not supported — ragged "
                "map_rows executables are keyed by (rows-per-bucket, "
                "padded cell shape), which depends on the data; they "
                "compile on first use (and land in the persistent cache "
                "like everything else)."
            )
        infos = validation.check_map_inputs(
            program, frame, verb, host_staged=host_stage or ()
        )
        # staged cell shapes: probe each stage fn on (at most) one row
        staged_specs: Dict[str, Tuple[Any, Tuple[int, ...]]] = {}
        if host_stage:
            block0 = frame.block(0)
            for n in program.input_names:
                if n not in host_stage:
                    continue
                value = block0[program.column_for_input(n)][:1]
                arr = self._staged_value(host_stage[n], value, n)
                staged_specs[n] = (
                    dtypes.coerce(dtypes.from_numpy(arr.dtype)),
                    arr.shape[1:],
                )
        # mirror the dispatch exactly: blocks the runtime would STREAM
        # compile chunk-sized executables on first use (documented gap) —
        # warming their whole-block signature would be dead weight.  A
        # sharded-cached frame never streams (its bytes are already in
        # HBM), so its plan is all-None like the dispatch's.
        cache = frame_cache.active_cache(frame)
        plans = (
            [None] * frame.num_blocks
            if cache is not None
            else [
                self._stream_plan(
                    program, frame.block(bi), infos, host_stage,
                    check_independence=not rows_level,
                )
                for bi in range(frame.num_blocks)
            ]
        )
        pads = self._bucket_plan(
            program, frame, infos, host_stage, rows_level, False, plans
        )
        exec_sizes = sorted(
            {
                pads[bi] if pads[bi] is not None else n
                for bi, n in enumerate(frame.block_sizes)
                if n > 0 and plans[bi] is None
            }
        )
        if not exec_sizes:
            # nothing block-sized will ever dispatch: every block streams
            # (chunk executables compile on first use), or the frame is
            # empty (the non-trimmed map verbs short-circuit without
            # compiling) — warming any signature would be dead weight
            return []
        # match the runtime's donation choice (_map_dispatch): donated
        # entries lower to a different persistent-cache key.  Cached
        # frames (sharded or single-device) never donate — shards and
        # resident columns are shared state
        donate = (
            prefetch.donate_inputs()
            and self._frame_fresh(frame)
            and cache is None
        )
        run = (
            self._rows_run(program, donate)
            if rows_level
            else self._block_run(program, donate)
        )
        raw = getattr(run, "raw_jit", None) or (
            program._vmap_raw() if rows_level else program._jit_raw()
        )
        fps = []
        for n_rows in exec_sizes:
            specs = {}
            for n in program.input_names:
                if n in staged_specs:
                    st, cell = staged_specs[n]
                else:
                    st = dtypes.coerce(infos[n].scalar_type)
                    cell = tuple(infos[n].cell_shape)
                specs[n] = jax.ShapeDtypeStruct(
                    (n_rows,) + tuple(cell), st.np_dtype
                )
            fn = program.aot_compile_raw(
                raw, specs, ("aot", bool(rows_level), donate)
            )
            fps.append(fn.fingerprint)
        # (bucket size, device) grid priming: execute the SAME entry the
        # dispatch loop uses once per (bucketed size, device) on
        # zero-filled blocks, so the first real dispatch on EVERY target
        # device is a jit-cache hit (backed by the persistent cache: the
        # per-device compile is a disk fetch in a warmed process).
        # Execution, not just lowering: jax keys executables by input
        # placement, and running the entry on the target device is the
        # one way to seed that key.  Programs are pure by contract, so a
        # zeros dispatch has no effect beyond the caches; trace counting
        # is suppressed (warmup is analysis).  The grid's device axis
        # (round 10): a host-fresh pool-eligible frame primes every pool
        # device; a SHARDED-cached frame primes its shard devices; a
        # single-device cached frame primes its resident device — so a
        # cached loop's first epoch pays no compile either.
        if cache is not None:
            prime_devs = [
                cache.devices[di] for di in sorted(set(cache.assignment))
            ]
        elif not self._frame_fresh(frame):
            dev = self._resident_device(frame)
            prime_devs = [dev] if dev is not None else []
        elif self.supports_device_pool and frame.num_blocks > 1:
            pool_devs = device_pool.pool_devices()
            prime_devs = pool_devs if len(pool_devs) >= 2 else []
        else:
            prime_devs = []
        if prime_devs:
            for n_rows in exec_sizes:
                zeros = {}
                for n in program.input_names:
                    if n in staged_specs:
                        st, cell = staged_specs[n]
                    else:
                        st = dtypes.coerce(infos[n].scalar_type)
                        cell = tuple(infos[n].cell_shape)
                    zeros[n] = np.zeros(
                        (n_rows,) + tuple(cell), st.np_dtype
                    )
                for dev in prime_devs:
                    inputs = {
                        k: jax.device_put(v, dev) for k, v in zeros.items()
                    }
                    with observability.suppress_trace_count():
                        out = run(inputs)
                    jax.block_until_ready(out)
        return fps

    def _resident_device(self, frame: TensorFrame):
        """The device a single-device cached frame's columns live on
        (first device column wins; columns are co-located by
        ``cache()``), or None for host frames.  Tolerates both jax API
        generations (``.devices()`` set vs ``.device``)."""
        for ci in frame.schema:
            data = frame.column(ci.name).data
            if not isinstance(data, jax.Array):
                continue
            devs = getattr(data, "devices", None)
            if callable(devs):
                try:
                    ds = devs()
                    if ds:
                        return next(iter(ds))
                except Exception:
                    pass
            dev = getattr(data, "device", None)
            try:
                return dev() if callable(dev) else dev
            except Exception:
                return None
        return None

    def _column_array(
        self, frame: TensorFrame, col_name: str, ci: ColumnInfo
    ):
        """A whole column as one contiguous array in its compute dtype —
        device-resident columns stay on device, host columns stay on host
        (callers ``device_put`` with their own sharding)."""
        st = dtypes.coerce(ci.scalar_type)
        data = frame.column(col_name).data
        if isinstance(data, jax.Array):
            return data if data.dtype == st.np_dtype else data.astype(st.np_dtype)
        return np.asarray(data).astype(st.np_dtype, copy=False)

    def _build_map_output(
        self,
        frame: TensorFrame,
        out_blocks: List[Dict[str, np.ndarray]],
        trim: bool,
        offsets: Optional[Sequence[int]] = None,
    ) -> TensorFrame:
        out_frame = TensorFrame.from_blocks(out_blocks)
        if trim:
            return out_frame
        # non-trimmed: append original columns not shadowed by outputs
        # (reference output schema: outputs ++ original, DebugRowOps.scala:
        # 349-372).  Divergence, by design: Spark tolerates duplicate column
        # names so the reference can emit both; our schema forbids duplicates,
        # so an output *shadows* the same-named passthrough column.
        shadowed = set(out_frame.column_names)
        cols = list(out_frame.columns)
        for cname in frame.column_names:
            if cname not in shadowed:
                cols.append(frame.column(cname))
        return TensorFrame(
            cols, offsets if offsets is not None else out_frame.offsets
        )

    # ------------------------------------------------------------- reduce --

    def _pair_call(self, program: Program, bases: Sequence[str]):
        def pairfn(left: Dict[str, Any], right: Dict[str, Any], params):
            inputs = {}
            for b in bases:
                inputs[f"{b}_1"] = left[b]
                inputs[f"{b}_2"] = right[b]
            return program.call(inputs, params)

        return pairfn

    def _tree_fold(
        self, pairfn, arrays: Dict[str, jnp.ndarray], params
    ) -> Dict[str, jnp.ndarray]:
        """Balanced deterministic tree fold over the lead axis (static size)."""
        vpair = jax.vmap(pairfn, in_axes=(0, 0, None))

        def fold(arrs: Dict[str, jnp.ndarray]):
            n = next(iter(arrs.values())).shape[0]
            if n == 0:
                raise ValidationError("cannot pairwise-fold zero rows")
            if n == 1:
                return {k: v[0] for k, v in arrs.items()}
            half = n // 2
            left = {k: v[:half] for k, v in arrs.items()}
            right = {k: v[half : 2 * half] for k, v in arrs.items()}
            combined = vpair(left, right, params)
            if n % 2:
                combined = {
                    k: jnp.concatenate([v, arrs[k][2 * half :]])
                    for k, v in combined.items()
                }
            return fold(combined)

        return fold(arrays)

    def _seq_fold(
        self, pairfn, arrays: Dict[str, jnp.ndarray], params
    ) -> Dict[str, jnp.ndarray]:
        """Left fold in row order — bit-exact reproduction of the reference's
        sequential pairwise reduction (``performReducePairwise``,
        ``DebugRowOps.scala:930-969``)."""
        init = {k: v[0] for k, v in arrays.items()}
        rest = {k: v[1:] for k, v in arrays.items()}

        def step(carry, row):
            return pairfn(carry, row, params), None

        out, _ = jax.lax.scan(step, init, rest)
        return out

    def _reduce_rows_setup(
        self, program: Program, frame: TensorFrame, mode: str
    ):
        """Shared pre-flight for reduce_rows (single-device and mesh): checks
        the pairwise contract and returns ``(bases, reduced, run)`` where
        ``run`` jit-folds a dict of block arrays down to one cell each."""
        if frame.num_rows == 0:
            raise ValidationError(
                "reduce_rows: cannot reduce an empty frame (no identity "
                "element is available for an arbitrary pairwise program)"
            )
        reduced = validation.check_reduce_rows(program, frame)
        bases = sorted(reduced)
        summaries = program.analyze(
            {
                f"{b}_{i}": (
                    dtypes.coerce(reduced[b].scalar_type),
                    tuple(reduced[b].cell_shape),
                )
                for b in bases
                for i in (1, 2)
            }
        )
        validation.check_reduce_rows_outputs(reduced, summaries)
        if mode not in ("tree", "sequential"):
            raise ValidationError(
                f"reduce_rows: unknown mode {mode!r}; use 'tree' or "
                f"'sequential'"
            )
        pairfn = self._pair_call(program, bases)
        fold = self._tree_fold if mode == "tree" else self._seq_fold

        run = program.cached_jit(
            ("reduce_rows", mode, tuple(bases)),
            lambda: lambda arrs, params: fold(pairfn, arrs, params),
        )
        return bases, reduced, run

    def reduce_rows(
        self, program: Program, frame: TensorFrame, mode: str = "tree"
    ) -> Dict[str, np.ndarray]:
        """``reduceRows`` (``DebugRowOps.scala:479-501``): pairwise-fold all
        rows of the named columns down to one row."""
        with observability.verb_span(
            "reduce_rows", frame.num_rows, frame.num_blocks
        ) as span:
            bases, reduced, run = self._reduce_rows_setup(program, frame, mode)
            span.mark("validate")
            # empty-partition guard inside (DebugRowOps:489-499); pooled
            # across local devices for host-fresh multi-block frames
            partials = self._reduce_partials(run, bases, reduced, frame, span)
            final = self._combine_partials(run, bases, partials)
            span.mark("dispatch")
            out = {b: _np(final[b]) for b in bases}
            span.mark("sync")
            return out

    def _combine_partials(
        self, run, bases, partials: List[Dict[str, jnp.ndarray]]
    ) -> Dict[str, jnp.ndarray]:
        """The ONE final-combine shape of the reduce verbs: stack every
        per-block partial in block order and re-apply ``run`` once.
        Shared by ``reduce_rows``/``reduce_blocks`` and the streaming
        incremental folds (``streaming/verbs.py``), which accumulate the
        same per-block partials window by window — so a windowed reduce
        is bit-identical to the materialized reduce over a frame with
        the same block boundaries, by construction rather than by
        numerical luck."""
        if len(partials) == 1:
            return partials[0]
        stacked = {b: jnp.stack([p[b] for p in partials]) for b in bases}
        return run(stacked)

    def _reduce_partials(
        self, run, bases, reduced, frame: TensorFrame, span
    ) -> List[Dict[str, jnp.ndarray]]:
        """Per-block partials for the reduce verbs (empty blocks skipped),
        device-pooled when the pool engages.

        Pooled: each nonempty block's input arrays stage to its assigned
        device on a per-device lane and ``run`` folds the block THERE —
        the device-granularity analog of the reference's per-partition
        reduce (SURVEY P1/P4).  Every partial then moves (async, one cell
        per base column) to ONE combine device, in block order, so the
        caller's final combine is byte-for-byte the single-device fold —
        same stack, same fold shape, bit-identical results regardless of
        completion order.  (A per-device local pre-fold would be one
        combine cheaper but would change the fold shape; bit-identity
        wins.)"""
        sizes = frame.block_sizes
        nonempty = [bi for bi in range(frame.num_blocks) if sizes[bi] > 0]
        sts = {b: dtypes.coerce(reduced[b].scalar_type) for b in bases}
        # base -> RESOLVED source column (feed-dict renames, round 11):
        # check_reduce_* returns the fed column's ColumnInfo, so its
        # .name is what block dicts and cache shards key on
        cols = {b: reduced[b].name for b in bases}
        session = fault_tolerance.frame_session(
            frame.num_blocks, verb="reduce"
        )
        # sharded frame cache: per-block partials fold on each block's
        # RESIDENT device (no H2D for resident shards), then hop — one
        # reduced cell per base — to ONE combine device in block order,
        # so the caller's final combine keeps the exact serial fold
        # shape (bit-identity, like the round-8 pooled partials)
        cache = frame_cache.active_cache(frame)
        if cache is not None and len(nonempty) > 1:
            return self._reduce_partials_sharded(
                run, bases, sts, cols, frame, span, cache, session, sizes,
                nonempty,
            )
        pool_devs = (
            device_pool.pool_devices()
            if (
                self.supports_device_pool
                and len(nonempty) > 1
                and self._frame_fresh(frame)
            )
            else []
        )
        if len(pool_devs) < 2:
            partials: List[Dict[str, jnp.ndarray]] = []
            for bi in nonempty:
                cancellation.checkpoint()  # block boundary (partials)
                t_blk = observability.trace_now()  # flight recorder

                def attempt(a, dev_i, _bi=bi):
                    block = frame.block(_bi)
                    arrays = {
                        b: self._device_value(block[cols[b]], sts[b])
                        for b in bases
                    }
                    return run(arrays)

                if session is None:
                    partials.append(attempt(0, None))
                else:
                    # reduce partials are cross-row by definition: no OOM
                    # split — an OOM surfaces with the block's row range
                    partials.append(
                        session.run(bi, sizes[bi], attempt, device=0)
                    )
                observability.note_request_block(0, sizes[bi])
                observability.trace_complete(
                    f"reduce b{bi}", "serial", t_blk,
                    block=bi, rows=sizes[bi],
                )
            if session is not None and session.events():
                span.annotate("fault_tolerance", session.record())
            span.mark("dispatch_partials")
            return partials
        assignment = device_pool.assign(
            [sizes[bi] for bi in nonempty], len(pool_devs)
        )
        pool = device_pool.PoolRun(
            pool_devs, assignment, prefetch.prefetch_depth() or 1
        )
        if session is not None:
            session.pool = pool

        def stage_block(k, dev):
            block = frame.block(nonempty[k])
            return {
                b: self._device_value(block[cols[b]], sts[b], device=dev)
                for b in bases
            }

        lanes = device_pool.lanes(pool_devs, assignment, stage_block)
        lane_iters = [iter(l) for l in lanes]
        lane_dead = [False] * len(pool_devs)
        combine = pool_devs[0]
        partials = []
        for k, bi in enumerate(nonempty):
            cancellation.checkpoint()  # block boundary (pooled partials)
            t_blk = observability.trace_now()  # flight recorder (r13)
            di = assignment[k]
            if session is None:
                arrays = next(lane_iters[di])
                p = run(arrays)
                di_eff = di
            else:
                staged = self._lane_next(
                    lane_iters[di], lane_dead, di, session, pool
                )
                holder = {"v": staged}
                del staged

                def attempt(a, dev_i, _k=k, _h=holder, _di=di):
                    arrs = (
                        _h.pop("v", None)
                        if (a == 0 and dev_i == _di)
                        else None
                    )
                    _h.clear()
                    if arrs is None:
                        arrs = stage_block(_k, pool_devs[dev_i])
                    return run(arrs)

                p = session.run(
                    bi,
                    sizes[bi],
                    attempt,
                    device=lambda _di=di: pool.effective_device(_di),
                )
                di_eff = pool.effective_device(di)
            pool.note_dispatch(di_eff, sizes[bi])
            observability.trace_complete(
                f"reduce b{bi}", f"device/{di_eff}", t_blk,
                block=bi, rows=sizes[bi], device=di_eff,
            )
            # async hop to the combine device: one reduced cell per base
            partials.append(
                {b: jax.device_put(p[b], combine) for b in bases}
            )
        span.annotate(
            "device_pool",
            pool.record(
                sum(l.stats["stage_s"] for l in lanes),
                sum(l.stats["wait_s"] for l in lanes),
            ),
        )
        if session is not None and session.events():
            span.annotate("fault_tolerance", session.record())
        span.mark("dispatch_partials")
        return partials

    def _reduce_partials_sharded(
        self, run, bases, sts, cols, frame, span, cache, session, sizes,
        nonempty,
    ) -> List[Dict[str, jnp.ndarray]]:
        """Affinity partials for the reduce verbs over a sharded-cached
        frame: each nonempty block's fold runs on its resident device
        (shards never donate; evicted blocks re-stage from the host copy
        inline), every partial then moves async to ONE combine device in
        block order.  Retries and quarantine redirects re-stage from the
        authoritative host columns on the current effective device."""
        devices = cache.devices
        pool = device_pool.PoolRun(
            devices,
            [cache.assignment[bi] for bi in nonempty],
            prefetch.prefetch_depth() or 1,
            affinity=True,
        )
        if session is not None:
            session.pool = pool
        combine = devices[0]
        partials: List[Dict[str, jnp.ndarray]] = []
        hits = 0
        for bi in nonempty:
            cancellation.checkpoint()  # block boundary (sharded partials)
            t_blk = observability.trace_now()  # flight recorder (r13)
            di = cache.assignment[bi]
            shard0 = cache.shard(bi)
            has_shard = shard0 is not None and any(
                cols[b] in shard0 for b in bases
            )
            # whether the attempt that SUCCEEDED read the shard — a
            # retried block re-stages from host, and the hit counter
            # must not claim otherwise
            used = {"v": False}

            def stage(dev_i, use_shard, _bi=bi, _shard=shard0):
                block = frame.block(_bi)
                shard = _shard if use_shard else None
                return {
                    b: self._device_value(
                        shard[cols[b]]
                        if shard is not None and cols[b] in shard
                        else block[cols[b]],
                        sts[b],
                        device=devices[dev_i],
                    )
                    for b in bases
                }

            if session is None:
                used["v"] = has_shard
                p = run(stage(di, True))
                di_eff = di
            else:

                def attempt(
                    a, dev_i, _stage=stage, _di=di, _has=has_shard,
                    _used=used,
                ):
                    # only attempt 0 on the home device may read the
                    # shard; every retry / redirect re-stages from host
                    u = a == 0 and dev_i == _di and _has
                    _used["v"] = u
                    return run(_stage(dev_i, u))

                p = session.run(
                    bi,
                    sizes[bi],
                    attempt,
                    device=lambda _di=di: pool.effective_device(_di),
                )
                di_eff = pool.effective_device(di)
                if has_shard and not used["v"]:
                    session.note_cache_restage()
            if used["v"]:
                hits += 1
                observability.note_cache_shard_hit()
            pool.note_dispatch(di_eff, sizes[bi])
            observability.trace_complete(
                f"reduce b{bi}", f"device/{di_eff}", t_blk,
                block=bi, rows=sizes[bi], device=di_eff,
                shard_hit=used["v"],
            )
            # async hop to the combine device: one reduced cell per base
            partials.append(
                {b: jax.device_put(p[b], combine) for b in bases}
            )
        span.annotate("device_pool", pool.record())
        fc = cache.record()
        fc["shard_hits"] = hits
        span.annotate("frame_cache", fc)
        if session is not None and session.events():
            span.annotate("fault_tolerance", session.record())
        span.mark("dispatch_partials")
        return partials

    def _reduce_blocks_setup(
        self, program: Program, frame: TensorFrame, verb: str = "reduce_blocks"
    ):
        """Shared pre-flight for reduce_blocks/aggregate-style programs:
        checks the x_input contract and returns ``(bases, reduced, run)``
        where ``run`` jit-applies the block program to a dict of block
        arrays keyed by base column name."""
        if frame.num_rows == 0:
            raise ValidationError(
                f"{verb}: cannot reduce an empty frame (no identity "
                f"element is available for an arbitrary block program)"
            )
        reduced = validation.check_reduce_blocks(program, frame, verb=verb)
        bases = sorted(reduced)
        # analyze at an arbitrary static block size to validate the contract
        probe = max(frame.block_sizes) or 1
        summaries = program.analyze(
            {
                f"{b}_input": (
                    dtypes.coerce(reduced[b].scalar_type),
                    (probe,) + tuple(reduced[b].cell_shape),
                )
                for b in bases
            }
        )
        validation.check_reduce_blocks_outputs(reduced, summaries, verb=verb)

        run = program.cached_jit(
            (verb, tuple(bases)),
            lambda: lambda arrs, params: program.call(
                {f"{b}_input": arrs[b] for b in bases}, params
            ),
        )
        return bases, reduced, run

    def reduce_blocks(
        self, program: Program, frame: TensorFrame
    ) -> Dict[str, np.ndarray]:
        """``reduceBlocks`` (``DebugRowOps.scala:503-526``): phase 1 reduces
        each block to one row with the user's block program; phase 2 re-applies
        the same program once to the stacked per-block partials."""
        with observability.verb_span(
            "reduce_blocks", frame.num_rows, frame.num_blocks
        ) as span:
            bases, reduced, run = self._reduce_blocks_setup(program, frame)
            span.mark("validate")
            # empty-partition guard inside (DebugRowOps:512-522); pooled
            # across local devices for host-fresh multi-block frames
            partials = self._reduce_partials(run, bases, reduced, frame, span)
            final = self._combine_partials(run, bases, partials)
            span.mark("dispatch")
            out = {b: _np(final[b]) for b in bases}
            span.mark("sync")
            return out

    # ---------------------------------------------------------- aggregate --

    def _run_groups(
        self, vrun, batch: Dict[str, np.ndarray]
    ) -> Dict[str, jnp.ndarray]:
        """Run the vmapped block program over one [groups, size, *cell]
        bucket.  The mesh executor overrides this to shard (and pad) the
        groups axis — groups are independent under vmap, so padding is
        semantics-safe there, unlike frame rows."""
        return vrun({b: jnp.asarray(v) for b, v in batch.items()})

    def aggregate(
        self, program: Program, grouped: GroupedFrame
    ) -> TensorFrame:
        """``aggregate`` (``DebugRowOps.scala:547-592`` + ``TensorFlowUDAF``
        L601-695): apply the x_input block program once per key group.

        Groups are bucketed by cardinality and each bucket runs as ONE
        ``vmap``-ed device call over all its groups — the TPU-shaped
        replacement for Spark's shuffle + row-buffered UDAF."""
        if type(grouped) is not GroupedFrame:
            # a deferred LazyGroupedFrame handed straight to an engine
            # instance: materialise and run the eager constructor's key
            # checks (scalar rank, existence) that deferral skipped
            grouped = GroupedFrame(grouped.frame, grouped.keys)
        with observability.verb_span(
            "aggregate", grouped.frame.num_rows, grouped.frame.num_blocks
        ) as span:
            return self._aggregate_impl(program, grouped, span)

    def _aggregate_impl(
        self, program: Program, grouped: GroupedFrame, span
    ) -> TensorFrame:
        frame = grouped.frame
        reduced = validation.check_reduce_blocks(program, frame, verb="aggregate")
        bases = sorted(reduced)
        for k in grouped.keys:
            if k in reduced:
                raise ValidationError(
                    f"aggregate: column {k!r} is both a grouping key and a "
                    f"reduced column"
                )

        if frame.num_rows == 0:
            # empty-frame contract: zero groups, so an empty result frame
            # with the key columns and the program's inferred output cells
            # — the block-reduction contract is still validated (a broken
            # program must fail the same way on 0 rows as on N)
            probe_summaries = program.analyze(
                {
                    f"{b}_input": (
                        dtypes.coerce(reduced[b].scalar_type),
                        (1,) + tuple(reduced[b].cell_shape),
                    )
                    for b in bases
                }
            )
            validation.check_reduce_blocks_outputs(
                reduced, probe_summaries, verb="aggregate"
            )
            span.mark("validate_and_group_index")
            cols = []
            for kname in grouped.keys:
                kst = frame.schema[kname].scalar_type
                kdata = np.zeros((0,), dtype=kst.np_dtype)
                cols.append(
                    Column(
                        ColumnInfo(kname, kst, Shape((UNKNOWN,))), kdata
                    )
                )
            for s in probe_summaries:
                if not s.is_output:
                    continue
                cell = tuple(s.shape)
                arr = np.zeros((0,) + cell, dtype=s.scalar_type.np_dtype)
                cols.append(
                    Column(
                        ColumnInfo(
                            s.name,
                            s.scalar_type,
                            Shape(arr.shape).with_lead(UNKNOWN),
                        ),
                        arr,
                    )
                )
            return TensorFrame(cols)

        # --- device-side segmented reduction (dense monoid fast path) ---
        seg = self._aggregate_segment(program, grouped, reduced, bases, span)
        if seg is not None:
            return seg

        # --- host-side group index build (the shuffle replacement) ---
        key_cells = [np.asarray(frame.column(k).data) for k in grouped.keys]
        n = frame.num_rows
        if len(key_cells) == 1:
            uniq, inverse = np.unique(key_cells[0], return_inverse=True)
            uniq_cols = [uniq]
        else:
            stacked = np.rec.fromarrays(key_cells)
            uniq, inverse = np.unique(stacked, return_inverse=True)
            uniq_cols = [np.asarray(uniq[name]) for name in uniq.dtype.names]
        num_groups = len(uniq_cols[0])
        order = np.argsort(inverse, kind="stable")
        counts = np.bincount(inverse, minlength=num_groups)
        starts = np.zeros(num_groups, dtype=np.int64)
        np.cumsum(counts[:-1], out=starts[1:])

        # validate the block-reduction contract at the largest group size
        # (same check reduce_blocks performs; a program that does not reduce
        # its block to one cell must fail loudly, not mis-shape the output)
        probe = int(counts.max())
        summaries = program.analyze(
            {
                f"{b}_input": (
                    dtypes.coerce(reduced[b].scalar_type),
                    (probe,) + tuple(reduced[b].cell_shape),
                )
                for b in bases
            }
        )
        validation.check_reduce_blocks_outputs(
            reduced, summaries, verb="aggregate"
        )
        span.mark("validate_and_group_index")

        # --- data columns, reordered so groups are contiguous ---
        data = {}
        for b in bases:
            ci = reduced[b]
            st = dtypes.coerce(ci.scalar_type)
            data[b] = np.asarray(frame.column(ci.name).data).astype(
                st.np_dtype, copy=False
            )[order]

        vrun = program.cached_jit(
            ("aggregate_v", tuple(bases)),
            lambda: lambda arrs, params: jax.vmap(
                lambda a: program.call(
                    {f"{b}_input": a[b] for b in bases}, params
                ),
                in_axes=(0,),
            )(arrs),
        )

        # --- per-group reduction ---
        # Two device strategies (SURVEY.md P5, replacing Spark's shuffle +
        # row-buffered UDAF):
        #   * few distinct group sizes (the dense/uniform-key case): one
        #     vmapped dispatch per distinct size, gather indices built
        #     vectorized — uniform keys = ONE dispatch total;
        #   * heavy size skew: a pairwise combine tree over partials,
        #     O(log max_count) dispatches regardless of the size histogram
        #     (legal because aggregate requires an algebraic, re-applicable
        #     reduction — Operations.scala:110-126; the reference's UDAF
        #     merges partial buffers under the same assumption,
        #     DebugRowOps.scala:658-676).
        by_size: Dict[int, np.ndarray] = {}
        for size in np.unique(counts):
            by_size[int(size)] = np.nonzero(counts == size)[0]

        if len(by_size) <= 8:
            results = self._aggregate_bucketed(
                vrun, bases, data, starts, by_size, num_groups
            )
        else:
            results = self._aggregate_tree(
                vrun, bases, data, np.repeat(
                    np.arange(num_groups, dtype=np.int64), counts
                ), num_groups
            )
        span.mark("execute")

        # --- assemble one-block result: keys ++ outputs, one row per group ---
        cols: List[Column] = []
        for kname, kvals in zip(grouped.keys, uniq_cols):
            st = dtypes.from_numpy(kvals.dtype)
            info = ColumnInfo(kname, st, Shape(kvals.shape).with_lead(UNKNOWN))
            cols.append(Column(info, kvals))
        for b in bases:
            arr = results[b]
            st = dtypes.from_numpy(arr.dtype)
            info = ColumnInfo(b, st, Shape(arr.shape).with_lead(UNKNOWN))
            cols.append(Column(info, arr))
        return TensorFrame(cols)

    def _aggregate_segment(
        self, program: Program, grouped: GroupedFrame, reduced, bases, span
    ) -> Optional[TensorFrame]:
        """Device fast path (SURVEY P5's TPU equivalent): the whole keyed
        reduction runs ON DEVICE as one segmented reduction.

        Applies when the program is a recognized *monoid* per column —
        ``sum`` / ``min`` / ``max`` / ``prod`` straight over the block axis
        (detected from the jaxpr, never guessed from probing).  Keys may be
        any number of int / bool / float scalar columns.  Then, instead of
        the host ``np.unique``/argsort/gather shuffle replacement:

        * ONE device ``lax.sort`` over all key columns (lexicographic,
          stable) carrying a row-index operand — the multi-key analog of
          a stable argsort; float keys are canonicalised first (-0.0 ->
          +0.0, every NaN payload -> the NaN) so device grouping matches
          ``np.unique``, and their segment boundaries compare *bit
          patterns* so the canonical NaNs group together;
        * segment ids from the sorted-key boundaries,
          ``jax.ops.segment_{sum,min,max,prod}`` over the reordered
          columns — zero full-column host copies, zero host sort;
        * the one host sync is a scalar readback of the group count;
          ``num_segments`` (static under jit) is padded to the next power
          of two so recompiles stay logarithmic in group count;
        * outputs (group keys + reduced cells) stay device-resident.

        On a :class:`~tensorframes_tpu.parallel.MeshExecutor` the key and
        data columns are sharded over the data axis (``_place_rows``), so
        the sort, the scatter-reduce, and the compaction run as ONE
        GSPMD-partitioned computation whose cross-shard exchanges ride the
        ICI — the mesh-scale form of the reference's shuffle-grouped
        aggregation (``DebugRowOps.scala:601-695``).

        Returns None when not applicable — non-monoid programs, ragged or
        host-only columns, and key dtypes that would not survive device
        canonicalisation (int64/f64 with x64 off merge distinct groups)
        keep the exact host-indexed paths."""
        if not getattr(self, "supports_segment_aggregate", True):
            return None
        frame = grouped.frame
        n = frame.num_rows
        if n == 0 or n >= np.iinfo(np.int32).max:
            return None
        kcols = []
        for kname in grouped.keys:
            kcol = frame.column(kname)
            kst = kcol.info.scalar_type
            # keys must survive device canonicalisation unchanged: with x64
            # off, int64/f64 keys would silently truncate on device and
            # merge distinct groups (the hazard frame.cache() documents) —
            # those fall back to the host np.unique path, which is exact
            if (
                kcol.is_ragged
                or np.dtype(kst.np_dtype).kind not in "iubf"
                or dtypes.coerce(kst) is not kst
            ):
                return None
            kcols.append(kcol)
        for b in bases:
            col = frame.column(reduced[b].name)
            if col.is_ragged or not col.info.scalar_type.device_ok:
                return None
        plan = _recognize_segment_plan(program, reduced, bases)
        if plan is None:
            return None

        # mesh divisor-cliff fix (round 5): BARE-monoid plans pad the row
        # axis to a mesh multiple — pad values are the reduction identity
        # and pad keys copy row 0's key, so no group's result changes and
        # no group is added (pad iotas sort after every real row, so the
        # compaction never picks one).  Plans with a pre/post stage cannot
        # pad safely (mean reads counts; sumsq would square the pad) and
        # keep the largest-divisor sharding.
        pad_rows = self._segment_pad_rows(n) if plan.trivial_kinds else 0
        total = n + pad_rows

        def _pad_tail(arr):
            if not pad_rows:
                return arr
            return jnp.concatenate(
                [arr, jnp.repeat(arr[:1], pad_rows, axis=0)]
            )

        keys = tuple(
            self._place_rows(_pad_tail(jnp.asarray(kcol.data)))
            for kcol in kcols
        )
        iota = self._place_rows(jnp.arange(total, dtype=jnp.int32))
        # stage 1 (one dispatch): canonicalise + lexicographic sort +
        # segment-id build + group count
        sk, order, gid, newseg, count = _segment_index(keys, iota)
        num_groups = int(count)  # the one host sync (scalar)
        pad = 1 << (num_groups - 1).bit_length()
        # stage 2 (one dispatch): compact the unique key rows; the static
        # size is the power-of-two pad — like the reduce stage — so
        # executables cache logarithmically in group count
        uniqs = tuple(
            u[:num_groups] for u in _segment_compact(sk, newseg, pad)
        )
        span.mark("group_index_device")

        # stage 3 (one fused dispatch): elementwise pre stage -> key-order
        # gather -> segment scatter-reduce(s) -> per-group post stage
        # (vmapped), per the program's SegmentPlan (segment_compile.py) —
        # round 5 widens this beyond bare monoids to mean / sum-of-squares
        # / weighted-sum-style affine compositions (VERDICT r4 weak #5)
        in_cols = {}
        for b in bases:
            st = dtypes.coerce(reduced[b].scalar_type)
            arr = jnp.asarray(frame.column(reduced[b].name).data).astype(
                st.np_dtype
            )
            if pad_rows:
                ident = _monoid_identity(
                    plan.trivial_kinds[b], st.np_dtype
                )
                arr = jnp.concatenate(
                    [
                        arr,
                        jnp.full(
                            (pad_rows,) + arr.shape[1:], ident, arr.dtype
                        ),
                    ]
                )
            in_cols[f"{b}_input"] = self._place_rows(arr)
        sig = tuple(
            (nm, tuple(c.shape), str(c.dtype))
            for nm, c in sorted(in_cols.items())
        )
        run = program.cached_jit(
            ("aggregate_plan", sig, pad),
            lambda: functools.partial(_plan_apply, plan, pad),
        )
        outs_all = run(in_cols, order, gid)
        outs = {b: outs_all[b][:num_groups] for b in bases}
        span.mark("execute")

        cols: List[Column] = []
        for kcol, uniq in zip(kcols, uniqs):
            kinfo = ColumnInfo(
                kcol.info.name,
                kcol.info.scalar_type,
                Shape(uniq.shape).with_lead(UNKNOWN),
            )
            cols.append(Column(kinfo, uniq))
        for b in bases:
            arr = outs[b]
            st = dtypes.from_numpy(np.dtype(arr.dtype))
            info = ColumnInfo(b, st, Shape(arr.shape).with_lead(UNKNOWN))
            cols.append(Column(info, arr))
        return TensorFrame(cols)

    def _aggregate_bucketed(
        self, vrun, bases, data, starts, by_size, num_groups
    ) -> Dict[str, np.ndarray]:
        """One vmapped dispatch per distinct group size; gather indices are
        built with a single broadcast add per bucket (no per-group python
        loop — VERDICT r1 weak #3)."""
        out: Dict[str, Optional[np.ndarray]] = {b: None for b in bases}
        for size, gids in sorted(by_size.items()):
            gather = starts[gids][:, None] + np.arange(size, dtype=np.int64)
            batch = {b: data[b][gather] for b in bases}
            outs = self._run_groups(vrun, batch)  # base -> [len(gids), *cell]
            for b in bases:
                host = _np(outs[b])
                if out[b] is None:
                    out[b] = np.empty(
                        (num_groups,) + host.shape[1:], dtype=host.dtype
                    )
                out[b][gids] = host
        return out

    def _aggregate_tree(
        self, vrun, bases, data, gid, num_groups
    ) -> Dict[str, np.ndarray]:
        """Pairwise combine tree over row partials: each level pairs adjacent
        same-group partials and runs ONE vmapped 2-row reduction over all
        pairs (padded to a power of two so trace count stays logarithmic).
        Converges in ceil(log2(max_count)) levels for ANY size skew.

        Level 0 seeds every row as the partial ``f([x])`` — one vmapped
        singleton-block dispatch over all rows — mirroring the reference
        UDAF's init-then-merge contract (``DebugRowOps.scala:658-676``):
        partials are always *program outputs*, never raw input rows, so
        singleton groups get reduced too and every combine merges
        f-partials with f (legal for the algebraic programs aggregate
        requires)."""
        seed = self._run_groups(vrun, {b: data[b][:, None] for b in bases})
        parts = {b: _np(seed[b]) for b in bases}
        while len(gid) > num_groups:
            # stable-sorted gid -> segment starts -> pair adjacent elements
            seg_start = np.empty(len(gid), dtype=np.int64)
            seg_start[0] = 0
            new_seg = np.nonzero(np.diff(gid))[0] + 1
            starts_at = np.zeros(len(gid), dtype=np.int64)
            starts_at[new_seg] = new_seg
            np.maximum.accumulate(starts_at, out=starts_at)
            pos = np.arange(len(gid), dtype=np.int64) - starts_at
            counts = np.bincount(gid, minlength=num_groups)[gid]
            is_left = (pos % 2 == 0) & (pos + 1 < counts)
            left = np.nonzero(is_left)[0]
            right = left + 1
            passthrough = np.nonzero((pos % 2 == 0) & (pos + 1 >= counts))[0]
            p = len(left)
            # pad pair count to the next power of two: bounded trace count,
            # pad pairs are computed and discarded (independent under vmap)
            p_pad = 1 << max(p - 1, 0).bit_length() if p else 0
            li = np.concatenate([left, np.repeat(left[-1:], p_pad - p)])
            ri = np.concatenate([right, np.repeat(right[-1:], p_pad - p)])
            batch = {
                b: np.stack([parts[b][li], parts[b][ri]], axis=1)
                for b in bases
            }
            outs = self._run_groups(vrun, batch)
            new_parts = {}
            for b in bases:
                host = _np(outs[b])[:p]
                new_parts[b] = np.concatenate(
                    [host, parts[b][passthrough]]
                )
            new_gid = np.concatenate([gid[left], gid[passthrough]])
            order = np.argsort(new_gid, kind="stable")
            gid = new_gid[order]
            parts = {b: v[order] for b, v in new_parts.items()}
        # gid is sorted and exactly one partial per group remains
        return {b: parts[b] for b in bases}


def _recognize_segment_plan(program: Program, reduced, bases):
    """Compile the block program into a :class:`segment_compile.
    SegmentPlan` (elementwise pre -> segment reduce -> per-group post), or
    None when it is not expressible that way.

    Round 4 recognized only bare ``reduce_{sum,min,max,prod}`` straight
    over ``<base>_input``; the segment compiler widens this to mean,
    sum-of-squares, weighted sums, norms, and any other elementwise
    composition around the reduces, with block-size literals re-bound to
    per-group counts (``segment_compile`` module docstring).  The plan is
    memoized on the Program per input signature (three probe traces ever,
    shared by repeated aggregate calls)."""
    specs = {
        f"{b}_input": jax.ShapeDtypeStruct(
            (2,) + tuple(reduced[b].cell_shape),
            dtypes.coerce(reduced[b].scalar_type).np_dtype,
        )
        for b in bases
    }
    key = (
        "segplan",
        tuple(sorted((n, s.shape, str(s.dtype)) for n, s in specs.items())),
    )
    cache = program._derived
    if key in cache:
        return cache[key]
    cache[key] = result = segment_compile.recognize(program, specs, bases)
    return result


def _recognize_monoids(
    program: Program, reduced, bases
) -> Optional[Dict[str, str]]:
    """The strict round-3 surface: per-output monoid kinds when every
    output is a bare ``reduce_{sum,min,max,prod}`` over axis 0 applied
    DIRECTLY to its own ``<base>_input`` — None for anything wider (which
    may still run on device via the full :func:`_recognize_segment_plan`
    path)."""
    plan = _recognize_segment_plan(program, reduced, bases)
    return plan.trivial_kinds if plan is not None else None


def _monoid_identity(kind: str, dtype) -> np.ndarray:
    """The reduction identity for one monoid kind at ``dtype`` — the pad
    value that leaves a group's result unchanged (segment-aggregate mesh
    padding)."""
    dt = np.dtype(dtype)
    if kind == "sum":
        return np.zeros((), dt)
    if kind == "prod":
        return np.ones((), dt)
    if dt.kind == "f":
        return np.asarray(np.inf if kind == "min" else -np.inf, dt)
    if dt.kind == "b":
        return np.asarray(kind == "min")
    info = np.iinfo(dt)
    return np.asarray(info.max if kind == "min" else info.min, dt)


# segment-reduction dispatch shared by the plan path (one table: kinds
# come from segment_compile's _REDUCE_KINDS values)
_SEGMENT_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
    "prod": jax.ops.segment_prod,
}


def _plan_apply(plan, pad: int, cols, order, gid, params):
    """Aggregate fast-path stage 3 (one fused dispatch): run the plan's
    row stage on the full columns, gather into key-sorted order, scatter-
    reduce each segment input, then run the per-group post stage vmapped
    over the (power-of-two padded) group axis.  Pad groups hold reduction
    identities (and count 0 — post NaNs there are sliced off by the
    caller)."""
    pre_cols = plan.pre(cols, params)
    segs = tuple(
        _SEGMENT_REDUCERS[kind](pc[order], gid, num_segments=pad)
        for pc, kind in zip(pre_cols, plan.reduce_kinds)
    )
    counts = jax.ops.segment_sum(
        jnp.ones(gid.shape, jnp.int32), gid, num_segments=pad
    )
    return jax.vmap(
        lambda s, c: plan.post(s, c, params), in_axes=(0, 0)
    )(segs, counts)


def _canonical_key(k):
    """Float keys canonicalised so device grouping matches ``np.unique``:
    -0.0 folds into +0.0 and every NaN payload becomes THE NaN (their
    shared bit pattern then groups them in ``_boundary``)."""
    if np.dtype(k.dtype).kind == "f":
        # explicit where (not `k + 0.0`): XLA's algebraic simplifier
        # rewrites x+0 to x, which would leave -0.0 bit patterns alive
        k = jnp.where(k == 0, jnp.zeros((), k.dtype), k)
        k = jnp.where(jnp.isnan(k), jnp.asarray(jnp.nan, k.dtype), k)
    return k


def _boundary(k):
    """True where sorted key column changes value (float: bit compare, so
    the canonical NaNs form one group)."""
    if np.dtype(k.dtype).kind == "f":
        ibits = {2: jnp.int16, 4: jnp.int32, 8: jnp.int64}[
            np.dtype(k.dtype).itemsize
        ]
        b = jax.lax.bitcast_convert_type(k, ibits)
        return b[1:] != b[:-1]
    return k[1:] != k[:-1]


@jax.jit
def _segment_index(keys, iota):
    """Aggregate fast-path stage 1, one dispatch: canonicalise, stable
    lexicographic sort (all key columns + the row index as the last
    operand), boundary flags, segment ids, group count."""
    keys = tuple(_canonical_key(k) for k in keys)
    sorted_all = jax.lax.sort(
        keys + (iota,), num_keys=len(keys), is_stable=True
    )
    sk, order = sorted_all[:-1], sorted_all[-1]
    neq = _boundary(sk[0])
    for k in sk[1:]:
        neq = neq | _boundary(k)
    newseg = jnp.concatenate([jnp.ones((1,), bool), neq])
    gid = jnp.cumsum(newseg.astype(jnp.int32)) - 1
    return sk, order, gid, newseg, gid[-1] + 1


@functools.partial(jax.jit, static_argnames=("pad",))
def _segment_compact(sk, newseg, pad: int):
    """Aggregate fast-path stage 2: gather the first row of every group.
    ``pad`` is the power-of-two-padded group count (executables cache per
    (shapes, pad), not per exact count); pad entries repeat row 0 and are
    sliced off by the caller."""
    idx = jnp.nonzero(newseg, size=pad)[0]
    return tuple(k[idx] for k in sk)


_DEFAULT = Executor()


def _resolve(engine: Optional[Executor]) -> Executor:
    return engine if engine is not None else _DEFAULT


# ---------------------------------------------------------------------------
# public verb API (the tfs.* surface, core.py:10-11)
# ---------------------------------------------------------------------------


def _wrap(fn, fetches, feed_dict=None, shapes=None) -> Program:
    program = Program.wrap(fn, fetches, feed_dict)
    if shapes:
        program = program.with_shape_hints(shapes)
    return program


def _lazy_target(frame, engine):
    """The LazyFrame a map verb should append to instead of
    dispatching, or None for the eager path (``ops/planner.py``:
    the frame is lazy via ``frame.lazy()``, or ``TFS_PLAN=1`` routes
    plain frames).  An explicit ``engine=`` (mesh executors) always
    stays eager — a plan targets the default engine's dispatch
    surface."""
    if engine is not None:
        return None
    from . import planner

    return planner.maybe_lazy(frame)


def _lazy_frame(frame):
    """Materialise a LazyFrame argument for verbs that are
    materialisation points (reduce/aggregate over plain frames,
    warmup)."""
    if getattr(frame, "_tfs_lazy", False):
        from . import planner

        return planner.ensure_frame(frame)
    return frame


def map_blocks(
    fn,
    frame: TensorFrame,
    trim: bool = False,
    fetches: Optional[Sequence[str]] = None,
    feed_dict: Optional[Mapping[str, str]] = None,
    host_stage: Optional[Mapping[str, Any]] = None,
    shapes: Optional[Mapping[str, Sequence[int]]] = None,
    engine: Optional[Executor] = None,
) -> TensorFrame:
    """Apply a block-level program to every block (``tfs.map_blocks``,
    reference ``core.py:213-253``).

    ``host_stage``: input name -> host preprocessing fn (binary decode).
    ``shapes``: output name -> block-shape hint (``ShapeDescription``).

    Planned mode (``ops/planner.py``): a ``frame.lazy()`` frame — or any
    frame under ``TFS_PLAN=1`` — records the verb on a logical plan and
    returns a LazyFrame; the optimized plan executes on first
    materialisation."""
    program = _wrap(fn, fetches, feed_dict, shapes)
    lazy = _lazy_target(frame, engine)
    if lazy is not None:
        return lazy._append(
            "map_blocks", program, trim=trim, host_stage=host_stage
        )
    return _resolve(engine).map_blocks(
        program, frame, trim=trim, host_stage=host_stage
    )


def map_rows(
    fn,
    frame: TensorFrame,
    fetches: Optional[Sequence[str]] = None,
    feed_dict: Optional[Mapping[str, str]] = None,
    host_stage: Optional[Mapping[str, Any]] = None,
    shapes: Optional[Mapping[str, Sequence[int]]] = None,
    engine: Optional[Executor] = None,
) -> TensorFrame:
    """Apply a row-level program to every row (``tfs.map_rows``,
    reference ``core.py:175-211``).  ``shapes`` hints are per-row cell
    shapes.  Planned mode records the verb lazily (see
    :func:`map_blocks`)."""
    program = _wrap(fn, fetches, feed_dict, shapes)
    lazy = _lazy_target(frame, engine)
    if lazy is not None:
        return lazy._append("map_rows", program, host_stage=host_stage)
    return _resolve(engine).map_rows(program, frame, host_stage=host_stage)


def reduce_rows(
    fn,
    frame: TensorFrame,
    fetches: Optional[Sequence[str]] = None,
    mode: str = "tree",
    shapes: Optional[Mapping[str, Sequence[int]]] = None,
    engine: Optional[Executor] = None,
) -> Dict[str, np.ndarray]:
    """Pairwise-reduce all rows to one (``tfs.reduce_rows``,
    reference ``core.py:138-173``).  A LazyFrame argument is a
    materialisation point: the optimized plan executes first, then the
    reduce runs eagerly over the result."""
    program = _wrap(fn, fetches, shapes=shapes)
    if engine is None and getattr(frame, "_tfs_lazy", False):
        return frame._reduce("reduce_rows", program, mode=mode)
    return _resolve(engine).reduce_rows(
        program, _lazy_frame(frame), mode=mode
    )


def reduce_blocks(
    fn,
    frame: TensorFrame,
    fetches: Optional[Sequence[str]] = None,
    shapes: Optional[Mapping[str, Sequence[int]]] = None,
    engine: Optional[Executor] = None,
) -> Dict[str, np.ndarray]:
    """Block-reduce then combine across blocks (``tfs.reduce_blocks``,
    reference ``core.py:255-291``).  A LazyFrame argument is a
    materialisation point (see :func:`reduce_rows`)."""
    program = _wrap(fn, fetches, shapes=shapes)
    if engine is None and getattr(frame, "_tfs_lazy", False):
        return frame._reduce("reduce_blocks", program)
    return _resolve(engine).reduce_blocks(program, _lazy_frame(frame))


def aggregate(
    fn,
    grouped: GroupedFrame,
    fetches: Optional[Sequence[str]] = None,
    shapes: Optional[Mapping[str, Sequence[int]]] = None,
    engine: Optional[Executor] = None,
) -> TensorFrame:
    """Keyed algebraic aggregation (``tfs.aggregate``,
    reference ``core.py:319-336``).  Grouping a LazyFrame defers the
    one materialisation it still needs (group structure is
    data-dependent) to this call, which prunes the chain's fetches to
    exactly the key + reduced columns (``ops/planner.py`` round 19);
    the aggregate itself always runs the eager engine over the
    materialised columns, so grouping numerics cannot drift."""
    program = _wrap(fn, fetches, shapes=shapes)
    from . import planner

    if isinstance(grouped, planner.LazyGroupedFrame):
        if engine is None:
            return grouped.lazy._aggregate_terminal(
                program, grouped.keys, grouped=grouped
            )
        # explicit engine: materialise the full plan, validate keys
        grouped = GroupedFrame(grouped.frame, grouped.keys)
    if getattr(grouped.frame, "_tfs_lazy", False):
        grouped = GroupedFrame(_lazy_frame(grouped.frame), grouped.keys)
    return _resolve(engine).aggregate(program, grouped)


def warmup(
    fn,
    frame: TensorFrame,
    rows_level: bool = False,
    fetches: Optional[Sequence[str]] = None,
    feed_dict: Optional[Mapping[str, str]] = None,
    host_stage: Optional[Mapping[str, Any]] = None,
    engine: Optional[Executor] = None,
) -> List[str]:
    """AOT-compile the map-verb executables ``fn`` will run over
    ``frame`` (persistent-cache cold start; see ``Executor.warmup``).

    A LazyFrame argument first primes the PLAN's own fused-chain grid
    (``planner.warm_plan`` — the bucketed, donating, per-device entries
    the optimizer dispatches, which per-stage warmups miss), then
    materialises and warms ``fn`` over the result."""
    program = Program.wrap(fn, fetches, feed_dict)
    if engine is None and getattr(frame, "_tfs_lazy", False):
        from . import planner

        planner.warm_plan(frame)
    frame = _lazy_frame(frame)
    return _resolve(engine).warmup(
        program, frame, rows_level=rows_level, host_stage=host_stage
    )
