"""Async block ingestion: double-buffered host->device prefetch.

The eager verbs already dispatch asynchronously (``device_put`` and jitted
execution both return before the work finishes), but the HOST side of block
ingestion — the dtype cast, the ``host_stage`` preprocessing, the act of
*issuing* the next transfer — still ran serially with the verb loop: block
N+1's bytes only started moving once every host-side step of block N had
run.  On a transfer-bound link (BENCH_r05: h2d 16.37 s/block against
0.154 s of compute) any host gap between transfers is throughput lost.

:class:`Prefetcher` closes the gap with the standard TPU input-pipeline
discipline:

* a single worker thread stages up to ``depth`` blocks ahead of the
  consumer — host cast + ``host_stage`` + ``jax.device_put`` all happen on
  the worker, so transfers queue back-to-back on the link while the
  consumer's compute dispatches run;
* the window is bounded (default 2 = double buffering), so at most
  ``depth`` staged input blocks exist at once;
* with **donation** (``donate_argnums`` on the consuming executable, see
  :func:`donate_inputs`) XLA reuses each staged input buffer for the
  block's outputs, so steady-state HBM holds <= ``depth`` input blocks no
  matter how many blocks the frame has.

Donation safety contract (the "no use-after-donate" rule): a donated
executable invalidates its input buffers, so ONLY buffers the engine
itself freshly staged for exactly one program application may flow
through a donating entry.  Device-resident frame columns (``cache()``-d
frames, chained verb outputs) are shared state and must never be donated
— the engine checks residency per block and routes shared buffers through
the non-donating executable.  Staged buffers are handed to the donating
executable exactly once and the reference is dropped immediately after.

Shape-canonical staging (round 7, ``ops/bucketing.py``): when block
bucketing applies, the engine's stage functions pad the row axis ON THE
HOST before the ``device_put``, so the staged buffer already carries the
padded signature the (single, shared) executable expects — the transfer
moves the padded bytes and no device-side reshape sits between staging
and dispatch.  Padded staged buffers remain donation-eligible: they are
fresh per block by construction, pad rows included, and the donating
executable consumes exactly the padded shape it was compiled for.

Knobs:

* ``TFS_PREFETCH_BLOCKS`` — staging window depth (default 2; ``0``
  disables the worker thread and stages synchronously, the pre-round-6
  behavior).
* ``TFS_DONATE`` — ``auto`` (default: donate on backends that implement
  buffer donation, i.e. TPU/GPU), ``1`` (force, e.g. to exercise the
  donated code path on CPU where jax warns and ignores the donation), or
  ``0`` (never donate).

The per-verb stats (:attr:`Prefetcher.stats`) record how much of the
staging wall time was hidden behind compute; the engine attaches them to
the verb span (``observability``) and ``bench.py`` reports the overlap
ratio for the streaming-ingestion leg.

Device-pool composition (round 8, ``ops/device_pool.py``): the pool
scheduler runs ONE Prefetcher per local device — each lane stages its
device's blocks in block order with ``device_put`` pointed at that
device (``name="tfs-pool-d<k>"``), and the donation contract above
carries over unchanged because only host-fresh frames ever pool.
"""

from __future__ import annotations

import contextvars
import queue
import threading
import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from .. import observability
from .. import envutil

DEFAULT_DEPTH = 2


class StagingError(RuntimeError):
    """A prefetch worker's staging callable failed.

    The message names the failing item index and the lane
    (``raise ... from e`` keeps the original as ``__cause__``), so a
    mid-stream staging failure points at a BLOCK instead of surfacing
    as a bare queue-crossed exception with no context.  Program-contract
    errors (``ValidationError``) pass through unwrapped — they already
    carry their own diagnosis and callers assert on their type.
    ``resilience.FailureDetector`` classifies a StagingError by walking
    its cause, so a transient transfer failure stays retryable."""

# backends whose PJRT client implements input-buffer donation; elsewhere
# jax warns ("Some donated buffers were not usable") and copies instead
_DONATING_BACKENDS = ("tpu", "gpu", "cuda", "rocm")


def prefetch_depth() -> int:
    """The staging window depth from ``TFS_PREFETCH_BLOCKS`` (>=0)."""
    raw = envutil.env_raw("TFS_PREFETCH_BLOCKS")
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_DEPTH


def overlap_ratio(stage_s: float, wait_s: float) -> float:
    """Fraction of staging wall time the consumer did NOT wait for —
    1.0 means every transfer was fully hidden behind the consumer's own
    work, 0.0 means fully serial (the synchronous baseline).  The one
    definition both :class:`Prefetcher` and the engine's merged
    block+chunk span stats report."""
    if stage_s <= 0.0:
        return 0.0
    return max(0.0, min(1.0, 1.0 - wait_s / stage_s))


def donate_inputs() -> bool:
    """Whether freshly staged input buffers should be donated to the
    consuming executable (``TFS_DONATE``; ``auto`` = backend supports
    donation)."""
    raw = envutil.env_raw("TFS_DONATE", "auto").lower()
    if raw in ("1", "true", "yes"):
        return True
    if raw in ("0", "false", "no"):
        return False
    return jax.default_backend() in _DONATING_BACKENDS


class Prefetcher:
    """Iterate staged values with up to ``depth`` items in flight.

    ``stage(i)`` runs on the worker thread and must return the staged
    (typically device-resident) value for item ``i`` — e.g. a dict of
    arrays created by ``jax.device_put`` (async: the call returns while
    the DMA is in flight).  ``stage`` must not trace/compile jax programs
    (keep all jit entry points on the consumer thread); ``device_put``,
    numpy work, and host_stage functions are safe and are exactly the
    work worth overlapping.

    Items are yielded strictly in order.  A ``stage`` exception is
    re-raised at the consumer's matching ``next()``.  ``stats`` holds
    ``{"items", "depth", "stage_s", "wait_s"}`` where ``stage_s`` is
    total worker staging wall time and ``wait_s`` is total consumer time
    blocked waiting for a staged item; :meth:`overlap_ratio` is the
    fraction of staging time hidden behind the consumer's own work.

    ``num_items=None`` (round 12, the streaming window reader): the item
    count is unknown upfront — ``stage(i)`` is called for ``i = 0, 1,
    ...`` until it raises ``StopIteration``, which ends the iteration
    cleanly (the windowed reader pulls from an unbounded Arrow batch
    source, so only the source knows when it is dry).  ``stats["items"]``
    then counts the items actually staged.
    """

    def __init__(
        self,
        stage: Callable[[int], Any],
        num_items: Optional[int],
        depth: Optional[int] = None,
        name: str = "tfs-prefetch",
    ):
        self._stage = stage
        self._n = None if num_items is None else int(num_items)
        self._depth = prefetch_depth() if depth is None else max(0, depth)
        # thread name: the device-pool scheduler runs one lane per device
        # ("tfs-pool-d<k>"), and distinguishable names matter in py-spy /
        # profiler dumps when several lanes stage concurrently
        self._name = name
        self.stats: Dict[str, Any] = {
            "items": 0 if self._n is None else self._n,
            "depth": self._depth,
            "stage_s": 0.0,
            "wait_s": 0.0,
        }

    def overlap_ratio(self) -> float:
        """:func:`overlap_ratio` over this prefetcher's own stats."""
        return overlap_ratio(self.stats["stage_s"], self.stats["wait_s"])

    # -- iteration -----------------------------------------------------------

    def __iter__(self):
        if self._depth <= 0 or (self._n is not None and self._n <= 1):
            # synchronous fallback: stage inline on the consumer thread
            i = 0
            while self._n is None or i < self._n:
                t0 = time.perf_counter()
                try:
                    v = self._stage(i)
                except StopIteration as e:
                    if self._n is not None:
                        # a BOUNDED stage running dry early is a bug in
                        # the stage, not clean exhaustion — silently
                        # truncating would hand the consumer a short
                        # frame with no diagnosis
                        raise StagingError(
                            f"{self._name}: staging item {i} raised "
                            f"StopIteration before the declared "
                            f"{self._n} items"
                        ) from e
                    return  # unbounded source exhausted
                t1 = time.perf_counter()
                dt = t1 - t0
                self.stats["stage_s"] += dt
                self.stats["wait_s"] += dt
                if self._n is None:
                    self.stats["items"] += 1
                # flight recorder: one event per staged item on this
                # lane's track (synchronous path: staging == waiting)
                observability.trace_complete(
                    f"stage {i}", f"lane/{self._name}", t0, t1, item=i
                )
                yield v
                i += 1
            return
        yield from self._iter_threaded()

    def _iter_threaded(self):
        q: "queue.Queue" = queue.Queue(maxsize=self._depth)
        stop = threading.Event()
        end = object()  # unbounded-mode exhaustion sentinel

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            i = 0
            try:
                while self._n is None or i < self._n:
                    if stop.is_set():
                        return
                    t0 = time.perf_counter()
                    try:
                        v = self._stage(i)
                    except StopIteration:
                        if self._n is not None:
                            # bounded mode: early exhaustion is a stage
                            # bug — re-raise so the outer handler ships
                            # the error sentinel (the consumer would
                            # otherwise block on the queue forever)
                            raise
                        break  # unbounded source exhausted
                    t1 = time.perf_counter()
                    self.stats["stage_s"] += t1 - t0
                    if self._n is None:
                        self.stats["items"] += 1
                    # flight recorder: staging timeline per lane — the
                    # H2D/compute-overlap half of the Perfetto view
                    observability.trace_complete(
                        f"stage {i}", f"lane/{self._name}", t0, t1, item=i
                    )
                    if not put((v, None)):
                        return
                    i += 1
                if self._n is None:
                    put((end, None))
            except BaseException as e:  # propagate to the consumer,
                # tagged with the failing item so the consumer can
                # re-raise with block context (StagingError)
                put((None, (i, e)))

        # request-scoped telemetry (round 15): the worker runs under a
        # COPY of the consumer thread's context, so counter bumps made
        # while staging (``note_h2d_bytes`` inside ``device_put`` paths)
        # and the lane's trace events are attributed to the request that
        # staged them — without this, a ledger's h2d accounting would
        # miss exactly the bytes the staging lanes move.  Cancellation
        # semantics are unchanged: staging code never calls
        # ``cancellation.checkpoint()``, so the copied scope is inert.
        ctx = contextvars.copy_context()
        t = threading.Thread(
            target=lambda: ctx.run(worker), name=self._name, daemon=True
        )
        t.start()
        try:
            produced = 0
            while self._n is None or produced < self._n:
                t0 = time.perf_counter()
                v, err = q.get()
                self.stats["wait_s"] += time.perf_counter() - t0
                if err is not None:
                    i, e = err
                    from .validation import ValidationError

                    if isinstance(e, ValidationError):
                        # program-contract errors keep their type (the
                        # verb API's documented error surface)
                        raise e
                    raise StagingError(
                        f"{self._name}: staging block {i} failed: "
                        f"{type(e).__name__}: {e}"
                    ) from e
                if v is end:
                    return
                yield v
                produced += 1
        finally:
            stop.set()
            # unblock a worker stuck on a full queue, then reap it
            while t.is_alive():
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)


def stage_columns(
    cols: Dict[str, Any], device=None
) -> Dict[str, jax.Array]:
    """Issue one async ``device_put`` per host column, back to back, so
    the per-column transfers of a multi-column frame queue on the link
    together instead of being issued lazily by the consuming jit call.
    Device-resident values pass through untouched."""
    staged = {}
    for name, arr in cols.items():
        if isinstance(arr, jax.Array):
            staged[name] = arr
        else:
            host = np.asarray(arr)
            observability.note_h2d_bytes(host.nbytes)
            staged[name] = jax.device_put(host, device)
    return staged
