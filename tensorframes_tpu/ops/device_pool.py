"""Block-parallel device-pool scheduler: independent blocks across chips.

The reference's core scaling story is data parallelism over partitions —
one tensor program per Spark partition, in parallel across executors, with
a driver-coordinated pairwise reduce (SURVEY §2.7 P1/P4).  The engine so
far had the two extremes: the single-device :class:`~tensorframes_tpu.ops.
engine.Executor` walks blocks serially on one chip, and the GSPMD
``MeshExecutor`` fuses the whole frame into one logical block.  This module
supplies the embarrassingly parallel middle — the paper's native mode — for
a multi-chip HOST: blocks of a frame are scheduled across
``jax.local_devices()`` with

* **deterministic least-loaded assignment** (:func:`assign`): blocks are
  assigned in block order to the device with the fewest assigned rows
  (ties -> lowest device index), so the plan depends only on the block
  sizes, never on runtime completion order;
* **per-device prefetch lanes** (:func:`lanes`): one
  :class:`~tensorframes_tpu.ops.prefetch.Prefetcher` per device stages
  that device's blocks in order — host cast + ``host_stage`` + async
  ``device_put`` *to its target device* — so block N+1's transfer for a
  device overlaps block N's compute on the same device, and the lanes of
  different devices stage concurrently.  The donation rules are inherited
  unchanged from the prefetch contract: freshly staged buffers donate,
  device-resident/cached columns never reach the pool (the engine only
  pools host-fresh frames);
* **bounded in-flight windows + overlapped D2H readback**
  (:class:`PoolRun`): a dispatched block's outputs start their async
  device->host copy immediately (``copy_to_host_async``), and at most
  ``depth`` blocks per device stay un-materialised — output assembly
  overlaps later blocks' compute instead of paying one serial readback at
  the end, and steady-state HBM per device stays bounded.

Order guarantees are bit-exact: outputs are reassembled by block index
(never completion order), and the reduce verbs compute per-block partials
on their assigned devices but bring ALL partials back to one device and
run the exact single-device combine — the fold shape is identical to the
serial path, so results match bit for bit regardless of which device
finished first.

Knobs:

* ``TFS_DEVICE_POOL`` — ``auto`` (default: all local devices; the pool
  only engages when there are >= 2), an integer N (first N local
  devices; ``0``/``1``/``off`` disable the pool), read per verb call so
  bench A/B legs and tests can toggle it mid-process.
* ``TFS_PREFETCH_BLOCKS`` — reused as both the per-lane staging depth and
  the per-device in-flight readback window (``0`` stages synchronously
  and keeps a window of 1 — the "overlap off" baseline).

Scope, by design: the pool engages for host-fresh multi-block frames on
the plain ``Executor`` only.  ``MeshExecutor`` keeps its GSPMD semantics
(``supports_device_pool = False``); device-resident (cached) frames stay
on their device — splitting a cached column across the pool would turn
every verb into a cross-device shuffle; ``aggregate`` keeps its
single-device paths (the segment fast path is already ONE fused dispatch,
and splitting its global key sort would change the reduction order);
fused row-terminal pipelines stay one dispatch (their combine shape IS
the executable).  Map-terminal pipelines pool per block
(``ops/pipeline.py``).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import observability, resilience
from .. import envutil
from . import fault_tolerance, prefetch

logger = logging.getLogger("tensorframes_tpu.device_pool")

ENV_VAR = "TFS_DEVICE_POOL"

# the exception classes a failed ``copy_to_host_async`` may legitimately
# raise (backend lacks the method's semantics, buffer already on host,
# runtime refused the async copy): jax runtime errors plus the plain
# RuntimeError/NotImplementedError some PJRT clients use.  Narrow by
# design — a TypeError here is a bug and must propagate.
_COPY_FALLBACK_TYPES = (
    RuntimeError,
    NotImplementedError,
) + resilience._runtime_error_types()

_warned: set = set()


def _warn_once(raw: str) -> None:
    if raw not in _warned:
        _warned.add(raw)
        logger.warning(
            "%s=%r is malformed; use 'auto', an integer device count, or "
            "'0'/'off' to disable. Falling back to 'auto'.",
            ENV_VAR,
            raw,
        )


def pool_devices() -> List[Any]:
    """The resolved device pool, or ``[]`` when pooling is disabled.

    ``auto``/unset: all ``jax.local_devices()`` (empty unless >= 2 —
    a one-device pool is just the serial path).  An integer caps the
    pool at the first N local devices; ``0``/``1``/``off`` disable it.
    Read per call: the knob toggles mid-process (bench legs, tests)."""
    import jax

    raw = envutil.env_raw(ENV_VAR, "auto").lower()
    if raw in ("0", "1", "off", "none", "false"):
        return []
    if raw in ("", "auto", "all"):
        n = None
    else:
        try:
            n = int(raw)
        except ValueError:
            _warn_once(raw)
            n = None
        else:
            if n <= 1:
                return []
    devs = list(jax.local_devices())
    if n is not None:
        devs = devs[: min(n, len(devs))]
    return devs if len(devs) >= 2 else []


def enabled() -> bool:
    """Whether the pool would engage (>= 2 resolved devices)."""
    return len(pool_devices()) >= 2


# process-wide quarantine memory (round 11): quarantine decisions live on
# each PoolRun, but a serving front-end needs to report "this host has a
# sick chip" across requests — every quarantine event also lands here so
# the bridge's health RPC can expose it.  Advisory/observational only:
# scheduling always consults the CURRENT run's own failure counts.
_quarantine_history: set = set()
_quarantine_lock = threading.Lock()


def recently_quarantined() -> List[int]:
    """Device indices any PoolRun quarantined since process start (or
    the last :func:`reset_quarantine_history`) — the health-RPC view of
    chip sickness on this host."""
    with _quarantine_lock:
        return sorted(_quarantine_history)


def reset_quarantine_history() -> None:
    """Clear the advisory quarantine history (tests; an operator's
    "I swapped the chip" acknowledgement)."""
    with _quarantine_lock:
        _quarantine_history.clear()


def assign(block_sizes: Sequence[int], n_devices: int) -> List[int]:
    """Deterministic least-loaded assignment: block index -> device index.

    Blocks are placed in block order on the device with the fewest
    assigned ROWS so far (ties -> lowest device index) — round-robin for
    equal blocks, row-balanced for skewed ones.  Depends only on the
    sizes, so the same frame always produces the same plan (the order
    guarantee the readback assembly relies on)."""
    loads = [0] * n_devices
    out: List[int] = []
    for sz in block_sizes:
        di = min(range(n_devices), key=lambda k: (loads[k], k))
        out.append(di)
        loads[di] += max(int(sz), 1)  # empty blocks still cost a dispatch
    return out


def lanes(
    devices: Sequence[Any],
    assignment: Sequence[int],
    stage_block: Callable[[int, Any], Any],
    name: str = "tfs-pool",
) -> List[prefetch.Prefetcher]:
    """One staging-lane :class:`~tensorframes_tpu.ops.prefetch.Prefetcher`
    per device: lane ``di`` stages the blocks assigned to device ``di`` in
    block order, calling ``stage_block(bi, device)`` on its worker thread.

    The consumer must pull via ``iter(lane)`` in GLOBAL block order —
    each lane yields its own blocks in ascending block index, and the
    global order visits every device's blocks in that same relative
    order, so ``next(lane_iters[assignment[bi]])`` always returns block
    ``bi``'s staged value.  The Prefetcher contract carries over: no jit
    entry points in ``stage_block`` (``device_put``/numpy/host_stage
    only)."""
    per_dev = [
        [bi for bi, d in enumerate(assignment) if d == di]
        for di in range(len(devices))
    ]
    out = []
    for di, dev in enumerate(devices):
        blocks_di = per_dev[di]

        def _stage(k, _blocks=blocks_di, _dev=dev):
            return stage_block(_blocks[k], _dev)

        out.append(
            prefetch.Prefetcher(
                _stage, len(blocks_di), name=f"{name}-d{di}"
            )
        )
    return out


class PoolRun:
    """One verb invocation's pool bookkeeping: per-device in-flight
    readback windows plus the scheduler stats a verb span records.

    ``submit(bi, di, n_rows, outs, out_blocks)`` notes the dispatch,
    starts the outputs' async device->host copies, and — once device
    ``di`` has more than ``depth`` un-materialised blocks — materialises
    the oldest into ``out_blocks[bi]`` (host numpy).  ``finish`` drains
    the remaining windows.  Assembly is always by block index."""

    def __init__(
        self,
        devices: Sequence[Any],
        assignment: Sequence[int],
        depth: int,
        affinity: bool = False,
    ):
        self.devices = list(devices)
        self.assignment = list(assignment)
        self.depth = max(1, int(depth))
        # affinity runs (sharded frame cache, round 10) dispatch blocks
        # on the device already holding their data: no staging lanes, so
        # stage_s/overlap stats read 0 by design — the flag keeps span
        # consumers from mistaking that for a dead prefetcher
        self.affinity = bool(affinity)
        n = len(self.devices)
        self._window: List[List] = [[] for _ in range(n)]
        self.blocks = [0] * n
        self.rows = [0] * n
        self._first_dispatch: List[Optional[float]] = [None] * n
        self._last_done: List[Optional[float]] = [None] * n
        self.drain_s = 0.0
        self._t0 = time.perf_counter()
        # fault tolerance (round 9): per-device transient-failure counts
        # and the quarantine set the retry layer consults
        # (ops/fault_tolerance.py); the threshold is captured once so a
        # mid-run env flip cannot split one run's policy
        self.failures = [0] * n
        self.quarantined: set = set()
        self._quarantine_after = fault_tolerance.quarantine_after()
        self._copy_warned = False

    # -- fault tolerance -----------------------------------------------------

    def note_block_failure(self, di: int) -> bool:
        """Record one transient dispatch failure on device ``di``;
        returns True when this failure newly quarantines the device.
        A quarantined device receives no further blocks this run —
        :meth:`effective_device` redirects them to healthy devices
        (Spark's executor blacklisting, at pool scope)."""
        self.failures[di] += 1
        if di in self.quarantined:
            return False
        if self.failures[di] < self._quarantine_after:
            return False
        self.quarantined.add(di)
        with _quarantine_lock:
            _quarantine_history.add(di)
        observability.note_device_quarantined()
        observability.trace_instant(
            "quarantine", "faults", device=di, failures=self.failures[di]
        )
        healthy = len(self.devices) - len(self.quarantined)
        logger.warning(
            "device %d quarantined after %d transient failures; "
            "re-dispatching its blocks across %d healthy device(s)%s",
            di,
            self.failures[di],
            healthy,
            " (pool degraded to the serial path)" if healthy <= 1 else "",
        )
        return True

    def effective_device(self, di: int) -> int:
        """The device index block work assigned to ``di`` should actually
        dispatch to: ``di`` while healthy, else the least-loaded healthy
        device (deterministic: ties to the lowest index).  With one
        healthy device left this is, by construction, the serial path on
        that device; with none left the frame fails loudly."""
        if di not in self.quarantined:
            return di
        healthy = [
            k for k in range(len(self.devices)) if k not in self.quarantined
        ]
        if not healthy:
            raise fault_tolerance.BlockExecutionError(
                f"device pool: all {len(self.devices)} devices are "
                f"quarantined (failure counts: {self.failures}); no "
                f"healthy device remains to re-dispatch blocks"
            )
        return min(healthy, key=lambda k: (self.rows[k], k))

    # -- dispatch/readback ---------------------------------------------------

    def note_dispatch(self, di: int, n_rows: int) -> None:
        """Record one block dispatched to device ``di`` (used directly by
        the reduce verbs, whose partials stay on device instead of going
        through the readback window).  The device index and row count
        ride into the active request's ledger (round 15) so per-request
        attribution carries blocks-per-device."""
        observability.note_pool_dispatch(di, n_rows)
        if self._first_dispatch[di] is None:
            self._first_dispatch[di] = time.perf_counter()
        self.blocks[di] += 1
        self.rows[di] += int(n_rows)

    def submit(
        self,
        bi: int,
        di: int,
        n_rows: int,
        outs: Dict[str, Any],
        out_blocks: List[Optional[Dict[str, np.ndarray]]],
    ) -> None:
        self.note_dispatch(di, n_rows)
        for v in outs.values():
            # overlapped D2H: the copy rides the link while later blocks
            # compute; np.asarray below then mostly finds the bytes ready
            copy = getattr(v, "copy_to_host_async", None)
            if copy is not None:
                try:
                    copy()
                except _COPY_FALLBACK_TYPES as e:
                    # readback still happens synchronously below — but a
                    # swallowed failure is a lost overlap, so it is
                    # counted (pool_copy_fallbacks) and logged once per
                    # run; anything outside the expected runtime-error
                    # types propagates (a TypeError here is a bug, not a
                    # backend quirk)
                    observability.note_pool_copy_fallback()
                    if not self._copy_warned:
                        self._copy_warned = True
                        logger.warning(
                            "copy_to_host_async failed (%s: %s); falling "
                            "back to synchronous readback for this run "
                            "(counted in pool_copy_fallbacks)",
                            type(e).__name__,
                            e,
                        )
        self._window[di].append((bi, outs))
        while len(self._window[di]) > self.depth:
            self._materialize(di, out_blocks)

    def _materialize(self, di: int, out_blocks) -> None:
        bi, outs = self._window[di].pop(0)
        t0 = time.perf_counter()
        out_blocks[bi] = {k: np.asarray(v) for k, v in outs.items()}
        observability.note_d2h_bytes(
            sum(int(v.nbytes) for v in out_blocks[bi].values())
        )
        now = time.perf_counter()
        # flight recorder: the D2H materialisation is where a pooled
        # block actually syncs — its track placement shows per-device
        # readback overlap in the Perfetto timeline
        observability.trace_complete(
            f"readback b{bi}", f"device/{di}", t0, now, block=bi, device=di
        )
        self.drain_s += now - t0
        self._last_done[di] = now

    def finish(self, out_blocks) -> None:
        for di in range(len(self.devices)):
            while self._window[di]:
                self._materialize(di, out_blocks)

    # -- stats ---------------------------------------------------------------

    def record(self, stage_s: float = 0.0, wait_s: float = 0.0) -> dict:
        """Scheduler observability for the verb span (and, via the span,
        for bench records): per-device blocks/rows, wall-clock occupancy
        (fraction of the verb's pool wall time the device had dispatched
        work in flight — an estimate from dispatch/materialise
        timestamps, no extra device syncs) and idle time, plus the lane
        staging totals and the overlap ratio they imply."""
        wall = max(time.perf_counter() - self._t0, 1e-9)
        occupancy, idle_s = [], []
        for di in range(len(self.devices)):
            t_first = self._first_dispatch[di]
            if t_first is None:
                occupancy.append(0.0)
                idle_s.append(round(wall, 6))
                continue
            t_done = self._last_done[di] or time.perf_counter()
            busy = max(0.0, t_done - t_first)
            occupancy.append(round(min(1.0, busy / wall), 4))
            idle_s.append(round(max(0.0, wall - busy), 6))
        rec = {
            "devices": len(self.devices),
            "depth": self.depth,
            "blocks_per_device": list(self.blocks),
            "rows_per_device": list(self.rows),
            "occupancy": occupancy,
            "idle_s": idle_s,
            "drain_s": round(self.drain_s, 6),
            "stage_s": round(stage_s, 6),
            "wait_s": round(wait_s, 6),
            "overlap_ratio": round(
                prefetch.overlap_ratio(stage_s, wait_s), 4
            ),
            "wall_s": round(wall, 6),
        }
        if self.affinity:
            rec["affinity"] = True
        if any(self.failures):
            rec["failures_per_device"] = list(self.failures)
            rec["quarantined_devices"] = sorted(self.quarantined)
        return rec
