"""Lazy verb-graph planner: fuse, prune, auto-cache (``TFS_PLAN``, round 14).

The reference exposes a *logical plan* surface — ``explain``/``analyze``
describe what will run before anything does (PAPER.md §L3) — but every
verb in this port executed eagerly until this round: a chained
``map -> map -> map`` pays one dispatch per verb, under the device pool
each link re-stages the previous verb's host-assembled output, and a
twice-consumed intermediate (the kmeans-epochs shape) re-stages per
consumer unless the user remembers ``cache(sharded=True)``.

``frame.lazy()`` (or ``TFS_PLAN=1`` for the module-level verbs) switches
a frame into *planned* mode: map verbs append :class:`PlanStep`\\ s to a
logical plan instead of dispatching, and the plan is optimized and
executed on first materialisation (``collect``/``to_arrays``/…, a
reduce verb, or ``aggregate``).  The optimizer:

* **fuses** maximal runs of adjacent map stages into ONE chained
  dispatch: each block is staged once (pruned), the stages' OWN
  compiled entries (``Program.jitted``/``vmapped`` — the exact
  executables the eager verbs run, bucket plans and persistent compile
  cache included) apply back-to-back on the block's device, and one
  readback returns the chain's outputs.  Under the pool this removes
  the per-verb host-assembly + re-staging round trip entirely; on the
  serial path intermediates stay device-resident.  Deliberately NOT a
  single XLA trace of the whole chain: XLA contracts arithmetic across
  stage boundaries (a stage-1 ``mul`` feeding a stage-2 ``add`` becomes
  one fma), which would round differently from the eager per-verb
  dispatches — per-stage executables make the six-verb bit-identity
  invariant structural instead of numerical luck;
* **prunes dead columns before staging**: the chain stages exactly the
  source columns some stage consumes, so columns no stage reads are
  never ``device_put`` (``h2d_bytes_staged`` drops measurably).  For
  non-trimmed chains the pruned columns still ride into the output
  frame as untouched host passthroughs — same values, zero transfer;
* **auto-inserts a sharded cache** when a subplan has >= 2 consumers
  (two derived chains, or repeated terminal consumption — epochs):
  pooled chain outputs are donation-ADOPTED as the result's shards
  (``frame_cache.adopt``), and re-consumed intermediates get
  ``cache(sharded=True)``-style placement over exactly the columns
  downstream stages read.  Either way a ``weakref.finalize`` releases
  the shards (refunding ``TFS_HBM_BUDGET``) when the planned frame is
  garbage-collected;
* **chooses pool vs fused-serial per fused group** from the existing
  roofline cost model (``roofline._aggregate_cost`` over the composed
  chain's compiled HLO → flops/byte) and the retrace state (a plan
  whose stage executables are already warm pools for free; a cold,
  transfer-bound chain stays serial — device-resident chaining, no
  per-device compiles).  The decision — and why — is recorded in the
  ``plan`` span annotation and rendered by ``tfs.explain``.

Eager execution stays the default (``TFS_PLAN`` unset / ``0``); every
planned verb is bit-identical to its eager counterpart, including the
pooled, sharded-cache, and fault-injection legs
(``tests/test_planner.py``).  Column ORDER of a planned map-terminal
output may differ from the eager chain's (derived outputs sort together
before source passthroughs); names and values are identical.

Round 19 promotes the planner into the **system-wide optimizer**
(ISSUE 14).  Four legs on top of the round-14 chain optimizer:

* **fused terminal reduce/aggregate** — a plan ending in
  ``reduce_rows``/``reduce_blocks`` folds each block's partial INSIDE
  the pooled chain dispatch, on the block's device, reusing the
  engine's own ``_reduce_*_setup`` executables and finishing with the
  engine's ``_combine_partials`` (stack in block order, re-apply once)
  — the EXACT fold shape of the eager verbs, so bit-identity is
  structural.  The materialized intermediate frame is eliminated
  entirely: no per-block D2H assembly, no re-staging H2D for the
  reduce.  A terminal ``aggregate`` (via a deferred
  :class:`LazyGroupedFrame`) prunes the chain's fetches to exactly the
  key + reduced columns before the one materialisation it still needs
  (group structure is data-dependent), then runs the UNCHANGED eager
  aggregate so grouping numerics cannot drift.
* **cross-plan common-subexpression sharing** — a process-wide
  plan-signature registry (source frame + step programs + live param
  identity, weakref-guarded) lets concurrent bridge requests and
  separate ``.lazy()`` chains with an identical subplan execute it
  ONCE: the owner runs under a private root ledger and every consumer
  registered by completion absorbs an exact integer share
  (:meth:`observability.RequestLedger.absorb`, the coalescer's
  attribution contract), so per-request ledgers still sum to the
  global counters delta bit for bit.  Later identical chains reuse the
  shared (auto-cached) result while it is alive (``plan_cse_hits``).
* **pipelined multi-epoch** :func:`iterate_epochs` — the planner-aware
  epoch driver: the entry frame's sharded cache is inserted on the
  FIRST consumption (the loop declares its >= 2 consumptions up
  front), evicted shards are re-staged through a background primer
  between epochs so epoch N+1's blocks are resident while epoch N's
  host work runs, and steady-state epochs stage 0 H2D bytes and
  re-trace nothing.
* **plans over streaming verbs** — stacked per-window map stages
  (``StreamFrame.map_blocks``/``map_rows`` chains and the relational
  pipeline's map stages) route through :func:`run_window_chain`:
  fusion, dead-column pruning, and the static
  ``analysis.rows_independent`` bucket pads apply per window
  (``plan_stream_windows``).  With ``TFS_PLAN_CALIBRATE`` on, the
  measured rows/s every plan execution records (the substance behind
  ``explain(analyze=True)``) feeds back into the pool-vs-serial
  decision: once both dispatches have been measured for a chain
  signature, the faster one wins over the static intensity threshold.

Knobs:

* ``TFS_PLAN`` — ``1``/``true`` routes the module-level verbs through
  the planner for plain frames; ``frame.lazy()`` opts in per frame
  regardless of the env.
* ``TFS_PLAN_POOL_MIN_INTENSITY`` — flops/byte below which a COLD fused
  group prefers the serial fused dispatch over the device pool (default
  ``1.0``; warm executables always pool when the pool is available).
* ``TFS_PLAN_CSE`` — cross-plan common-subexpression sharing (default
  on for planned executions; ``0`` disables the registry).
* ``TFS_PLAN_CALIBRATE`` — measured-throughput feedback into the
  pool-vs-serial decision (default off; ``1`` prefers whichever
  dispatch measured faster for the chain signature).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
import weakref
from typing import Any, Dict, List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

from .. import cancellation, dtypes, observability
from .. import envutil
from .. import roofline as _roofline
from ..frame import TensorFrame
from ..program import Program
from ..schema import ColumnInfo, Schema
from . import (
    bucketing,
    device_pool,
    fault_tolerance,
    frame_cache,
    prefetch,
)
from ..analysis import rowdep as analysis
from .engine import (
    _DEFAULT,
    Executor,
    GroupedFrame,
    _check_shape_hints,
    _np,
)
from .pipeline import analyzed_outputs
from .validation import ValidationError

_log = logging.getLogger("tensorframes_tpu.planner")

ENV_PLAN = "TFS_PLAN"
ENV_POOL_INTENSITY = "TFS_PLAN_POOL_MIN_INTENSITY"
ENV_CSE = "TFS_PLAN_CSE"
ENV_CALIBRATE = "TFS_PLAN_CALIBRATE"
_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("0", "false", "no", "off")


def planning_enabled() -> bool:
    """Whether ``TFS_PLAN`` routes the module-level verbs through the
    planner for plain frames (read per call: bench legs and tests flip
    it mid-process)."""
    return envutil.env_raw(ENV_PLAN).lower() in _TRUTHY


def cse_enabled() -> bool:
    """Cross-plan common-subexpression sharing (``TFS_PLAN_CSE``): on
    by default for planned executions, ``0`` disables the registry."""
    return envutil.env_raw(ENV_CSE).lower() not in _FALSY


def calibrate_enabled() -> bool:
    """Measured-throughput feedback into the pool-vs-serial decision
    (``TFS_PLAN_CALIBRATE``, default off)."""
    return envutil.env_raw(ENV_CALIBRATE).lower() in _TRUTHY


def pool_min_intensity() -> float:
    raw = envutil.env_raw(ENV_POOL_INTENSITY)
    if not raw:
        return 1.0
    try:
        return float(raw)
    except ValueError:
        return 1.0


class _SerialExecutor(Executor):
    """The fused-serial dispatch target: the exact default engine with
    the device-pool scheduler opted out — the planner's per-group
    "serial" decision, expressed the same way ``MeshExecutor`` opts out
    (``supports_device_pool``) so no dispatch-loop code forks."""

    supports_device_pool = False


_SERIAL = _SerialExecutor()


# ---------------------------------------------------------------------------
# plan steps + fusion metadata
# ---------------------------------------------------------------------------


class PlanStep:
    """One recorded map verb (reduce/aggregate are materialisation
    points, not steps)."""

    __slots__ = ("kind", "program", "trim", "host_stage")

    def __init__(
        self,
        kind: str,
        program: Program,
        trim: bool = False,
        host_stage: Optional[Mapping[str, Any]] = None,
    ):
        self.kind = kind  # "map_blocks" | "map_rows"
        self.program = program
        self.trim = trim
        self.host_stage = host_stage

    @property
    def label(self) -> str:
        if self.kind == "map_blocks" and self.trim:
            return "map_blocks_trimmed"
        return self.kind

    @property
    def stage_bound(self) -> bool:
        """Whether this step must run eagerly because it carries host
        preprocessing (explicit ``host_stage`` or an importer
        ``host_prelude``) — host fns cannot join a fused chain."""
        return bool(self.host_stage) or bool(
            getattr(self.program, "host_prelude", None)
        )


def _device_infos(frame: TensorFrame) -> Dict[str, ColumnInfo]:
    """Device-feedable uniform columns of a concrete frame — the
    columns a fused chain may consume."""
    out: Dict[str, ColumnInfo] = {}
    for c in frame.columns:
        if c.info.scalar_type.device_ok and not c.is_ragged:
            out[c.info.name] = c.info
    return out


# Per-stage shape inference is an eval_shape trace (~ms): an epochs loop
# rebuilding the same chain would pay it per stage per epoch, which is
# pure overhead on a hot path that dispatches in single-digit ms.  Keyed
# by program identity + the exact input info signature, weakref-guarded
# like the fusion cache.
_ANALYSIS_CACHE: "collections.OrderedDict[Any, Tuple[Any, Dict]]" = (
    collections.OrderedDict()
)
_ANALYSIS_CACHE_CAP = 256


def _analyzed_outputs_cached(
    program: Program, infos: Mapping[str, ColumnInfo], cell: bool
) -> Dict[str, ColumnInfo]:
    key = (
        id(program),
        cell,
        tuple(
            sorted(
                (n, ci.scalar_type.name, tuple(ci.block_shape))
                for n, ci in infos.items()
            )
        ),
    )
    hit = _ANALYSIS_CACHE.get(key)
    if hit is not None:
        ref, outs = hit
        if ref() is program:
            _ANALYSIS_CACHE.move_to_end(key)
            return outs
        del _ANALYSIS_CACHE[key]
    outs = analyzed_outputs(program, infos, cell=cell, verb="plan")
    _ANALYSIS_CACHE[key] = (weakref.ref(program), outs)
    while len(_ANALYSIS_CACHE) > _ANALYSIS_CACHE_CAP:
        _ANALYSIS_CACHE.popitem(last=False)
    return outs


def _fusable_run(
    steps: Sequence[PlanStep], visible: Dict[str, ColumnInfo]
) -> Tuple[int, Optional[str], Dict[str, ColumnInfo]]:
    """Length of the maximal fusable prefix of ``steps`` given the
    ``visible`` device-feedable columns at entry, the reason the run
    stopped (None when it covered every step), and the visible columns
    AFTER the prefix (so callers can keep walking a chain).

    A step fuses when: no host stage, every input resolves to a visible
    device-feedable uniform column, and shape inference succeeds."""
    visible = dict(visible)
    n = 0
    why = None
    for st in steps:
        if st.stage_bound:
            why = "host_stage"
            break
        infos: Dict[str, ColumnInfo] = {}
        bad = None
        for name in st.program.input_names:
            col = st.program.column_for_input(name)
            ci = visible.get(col)
            if ci is None:
                bad = col
                break
            infos[name] = ci
        if bad is not None:
            why = f"column {bad!r} is host-only/ragged or absent"
            break
        try:
            outs = _analyzed_outputs_cached(
                st.program, infos, cell=st.kind == "map_rows"
            )
        except Exception as e:  # analysis failure: run the stage eagerly
            why = f"shape inference failed ({type(e).__name__})"
            break
        if st.trim:
            visible = dict(outs)
        else:
            visible.update(outs)
        n += 1
    return n, why, visible


class _FusedMeta:
    """One fused group's compile-time facts: the chain's staged entry
    columns (pruned), final fetches, per-stage bucket-proof specs,
    per-stage liveness (columns still needed after each stage — the
    donation/free analysis), and the composed ANALYSIS program the
    roofline decision probes (never executed — execution applies the
    stage programs' own entries)."""

    __slots__ = (
        "program",
        "fetches",
        "src_inputs",
        "pruned",
        "trim",
        "steps",
        "param_slots",
        "stage_specs",
        "stage_infos",
        "final_infos",
        "live_after",
        # round 20: memoized calibration fingerprints per frame shape
        "_calib_fps",
    )


# Fusion metadata is cached process-wide so re-running a rebuilt chain
# (same stage Programs, same entry layout) skips re-analysis and reuses
# one probe program.  Keys hold id()s; entries carry weakrefs so a
# recycled id can never alias stale metadata onto different programs.
_FUSED_CACHE: "collections.OrderedDict[Any, Tuple[Any, _FusedMeta]]" = (
    collections.OrderedDict()
)
_FUSED_CACHE_CAP = 64


def _entry_signature(frame: TensorFrame) -> Tuple:
    sig = []
    for c in frame.columns:
        if c.info.scalar_type.device_ok and not c.is_ragged:
            sig.append(
                (c.info.name, tuple(c.data.shape[1:]), str(c.data.dtype))
            )
    return tuple(sorted(sig))


def _compose(
    steps: Sequence[PlanStep],
    frame: TensorFrame,
    keep: Optional[Set[str]] = None,
) -> _FusedMeta:
    """Analyse ``steps`` as one fused chain over ``frame``'s entry
    columns (cached): which source columns the chain consumes (its
    pruned staging set), what it produces, the per-stage specs the
    bucket-padding proof needs, and a composed probe Program whose
    compiled HLO feeds the pool/serial cost decision.

    ``keep`` (round 19, terminal fetch pruning): restrict the chain's
    fetches to the derived columns a terminal consumer actually reads —
    a reduce's base columns, an aggregate's keys + bases — so liveness
    can free/donate every other intermediate and nothing unread is ever
    assembled back to host."""
    key = (
        tuple((st.kind, id(st.program), st.trim) for st in steps),
        _entry_signature(frame),
        None if keep is None else tuple(sorted(keep)),
    )
    hit = _FUSED_CACHE.get(key)
    if hit is not None:
        refs, meta = hit
        if all(r() is st.program for r, st in zip(refs, steps)):
            _FUSED_CACHE.move_to_end(key)
            _sync_probe_params(meta)
            return meta
        del _FUSED_CACHE[key]

    import jax

    src_infos = _device_infos(frame)
    origin: Dict[str, str] = {n: "source" for n in src_infos}
    infos_now: Dict[str, ColumnInfo] = dict(src_infos)
    src_inputs: List[str] = []
    param_slots: List[Tuple[str, Program]] = []  # (param name, owner)
    stage_specs: List[Optional[Dict[str, Any]]] = []
    stage_infos: List[Dict[str, ColumnInfo]] = []
    for st in steps:
        step_infos: Dict[str, ColumnInfo] = {}
        for name in st.program.input_names:
            col = st.program.column_for_input(name)
            if col not in origin:
                raise ValidationError(
                    f"plan.{st.label}: program input {name!r} requests "
                    f"column {col!r}, which is not available at this "
                    f"point in the chain. Available: {sorted(origin)}."
                )
            if origin[col] == "source" and col not in src_inputs:
                src_inputs.append(col)
            step_infos[name] = infos_now[col]
        # (2, *cell) probe specs for the row-independence proof behind
        # bucket padding — None when a cell dim is Unknown at this stage
        stage_specs.append(
            analysis.input_specs_for(st.program, step_infos)
        )
        stage_infos.append(dict(step_infos))
        outs = _analyzed_outputs_cached(
            st.program, step_infos, cell=st.kind == "map_rows"
        )
        if st.trim:
            origin = {n: "derived" for n in outs}
            infos_now = dict(outs)
        else:
            origin.update({n: "derived" for n in outs})
            infos_now.update(outs)
        for p in st.program.param_names:
            if all(p != q for q, _ in param_slots):
                param_slots.append((p, st.program))
    fetches = sorted(n for n, kind in origin.items() if kind == "derived")
    if keep is not None:
        fetches = [f for f in fetches if f in keep]
    if not fetches:
        raise ValidationError(
            "plan: the fused chain produces no derived outputs"
            + (" the terminal consumer reads" if keep is not None else "")
        )
    pruned = sorted(set(src_infos) - set(src_inputs))
    trim = any(st.trim for st in steps)

    steps_t = tuple(steps)
    stage_params = tuple(tuple(st.program.param_names) for st in steps_t)

    def probe(**kw):
        # ANALYSIS-ONLY composed body (roofline cost probe): the real
        # execution applies each stage's own compiled entry so fused
        # rounding is bit-identical to eager (see module docstring)
        import jax as _jax

        blk: Dict[str, Any] = {c: kw[c] for c in src_inputs}
        for st, pnames in zip(steps_t, stage_params):
            prog = st.program
            params = {p: kw[p] for p in pnames}
            inputs = {
                n: blk[prog.column_for_input(n)] for n in prog.input_names
            }
            if st.kind == "map_rows":
                outs = _jax.vmap(
                    lambda ins, _p=params, _pr=prog: _pr.call(ins, _p),
                    in_axes=(0,),
                )(inputs)
            else:
                outs = prog.call(inputs, params)
            blk = dict(outs) if st.trim else {**blk, **outs}
        return {f: blk[f] for f in fetches}

    merged_params = {p: owner._params[p] for p, owner in param_slots}
    program = Program(
        probe,
        list(src_inputs) + [p for p, _ in param_slots],
        fetches=fetches,
        params=merged_params,
    )

    # liveness: columns still needed AFTER stage k (later stages'
    # inputs + the final fetches) — drives both the dead-buffer frees
    # between stages and the donation eligibility below
    live = set(fetches)
    live_after: List[Set[str]] = [set() for _ in steps_t]
    for k in range(len(steps_t) - 1, -1, -1):
        live_after[k] = set(live)
        live |= {
            steps_t[k].program.column_for_input(n)
            for n in steps_t[k].program.input_names
        }

    meta = _FusedMeta()
    meta.program = program
    meta.fetches = fetches
    meta.src_inputs = list(src_inputs)
    meta.pruned = pruned
    meta.trim = trim
    meta.steps = steps_t
    meta.param_slots = tuple(param_slots)
    meta.stage_specs = stage_specs
    meta.stage_infos = stage_infos
    meta.final_infos = dict(infos_now)
    meta.live_after = live_after
    refs = tuple(weakref.ref(st.program) for st in steps_t)
    _FUSED_CACHE[key] = (refs, meta)
    while len(_FUSED_CACHE) > _FUSED_CACHE_CAP:
        _FUSED_CACHE.popitem(last=False)
    return meta


def _sync_probe_params(meta: _FusedMeta) -> None:
    """Keep the probe program's params tracking the live stage params
    (shape-stable by ``update_params``' contract), so its cost analysis
    and cached specs never go stale.  Execution always reads the stage
    programs' own live params via their compiled entries."""
    for p, owner in meta.param_slots:
        live = owner._params.get(p)
        if live is not None and meta.program._params.get(p) is not live:
            meta.program._params[p] = live


# ---------------------------------------------------------------------------
# measured-throughput calibration (TFS_PLAN_CALIBRATE, round 19)
# ---------------------------------------------------------------------------
#
# Every plan execution already measures itself (`_measured`, the
# substance behind ``explain(analyze=True)``).  With the knob on those
# measurements feed BACK into the pool-vs-serial decision: per chain
# signature the best observed rows/s per dispatch kind is kept, and once
# both kinds have been measured the faster one wins over the static
# ``TFS_PLAN_POOL_MIN_INTENSITY`` threshold — the calibration loop for
# real TPU hosts where H2D is PCIe rather than memcpy and the roofline's
# flops/byte alone misjudges the crossover.

_CALIBRATION: "collections.OrderedDict[Any, Dict[str, float]]" = (
    collections.OrderedDict()
)
_CALIBRATION_CAP = 256
_CALIBRATION_LOCK = threading.Lock()

# -- cross-process persistence (round 20) ------------------------------------
#
# The in-memory table keys on live object ids — exact, but dead with the
# process, so every restarted replica re-learned pool-vs-serial from
# cold heuristics (the round-19 open item).  With BOTH knobs on
# (TFS_PLAN_CALIBRATE + TFS_COMPILE_CACHE) measurements also persist to
# ``<compile-cache>/tfs_calibration-v1.json`` under a STABLE chain
# fingerprint (step kinds/trims + program input/fetch/feed names +
# entry signature + fetches + rows + blocks — no ids), versioned and
# atomically replaced.  Lookup order: live in-memory entry first (object
# identity is stricter), persisted fingerprint second — so a fresh
# process's FIRST request picks the measured winner instead of the
# static intensity threshold.  A fingerprint collision can only steer a
# heuristic (decision quality), never correctness: every dispatch kind
# is bit-identical by contract.

_CALIB_PERSIST_FORMAT = "tfs-calibration-v1"
_calib_persist: Optional[Dict[str, Dict[str, float]]] = None
_calib_persist_dir: Optional[str] = None


def _calib_persist_path(cache_dir: str) -> str:
    import os

    return os.path.join(cache_dir, f"{_CALIB_PERSIST_FORMAT}.json")


def _calib_persist_table() -> Optional[Dict[str, Dict[str, float]]]:
    """The persisted fingerprint table (lock held by caller), lazily
    loaded from the active compile-cache dir; None when no persistent
    home is configured."""
    global _calib_persist, _calib_persist_dir
    from .. import compile_cache

    d = compile_cache.cache_dir()
    if not d:
        return None
    if _calib_persist is not None and _calib_persist_dir == d:
        return _calib_persist
    import json

    table: Dict[str, Dict[str, float]] = {}
    try:
        with open(_calib_persist_path(d), "rb") as f:
            doc = json.loads(f.read().decode())
        if (
            isinstance(doc, dict)
            and doc.get("format") == _CALIB_PERSIST_FORMAT
        ):
            for fp, rec in (doc.get("entries") or {}).items():
                table[str(fp)] = {
                    k: float(v)
                    for k, v in rec.items()
                    if k in ("pool", "serial")
                }
    except (OSError, ValueError):
        pass  # absent / torn / old format: start fresh
    _calib_persist = table
    _calib_persist_dir = d
    return table


def _calib_persist_save() -> None:
    """Atomic-replace write of the persisted table (lock held by
    caller).  The file is tiny (<= _CALIBRATION_CAP entries) — a write
    per measured execution is noise next to the execution itself."""
    import json
    import os

    if _calib_persist is None or not _calib_persist_dir:
        return
    # bound like the in-memory table: drop oldest-inserted overflow
    while len(_calib_persist) > _CALIBRATION_CAP:
        _calib_persist.pop(next(iter(_calib_persist)))
    path = _calib_persist_path(_calib_persist_dir)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(
                json.dumps(
                    {
                        "format": _CALIB_PERSIST_FORMAT,
                        "entries": _calib_persist,
                    }
                ).encode()
            )
        os.replace(tmp, path)
    except OSError:
        _log.warning(
            "planner: calibration persistence write failed", exc_info=True
        )


def _calib_fingerprint(meta: "_FusedMeta", frame: TensorFrame) -> str:
    """A stable, cross-process fingerprint of the calibration workload:
    everything ``_calib_key`` captures EXCEPT object identity.
    Memoized on the meta (keyed by the frame-shape half) — the JSON +
    sha256 walk must not run per planned dispatch."""
    import hashlib
    import json

    memo_key = (frame.num_rows, frame.num_blocks, _entry_signature(frame))
    memo = getattr(meta, "_calib_fps", None)
    if memo is None:
        memo = meta._calib_fps = {}
    hit = memo.get(memo_key)
    if hit is not None:
        return hit

    doc = {
        "steps": [
            {
                "kind": st.kind,
                "trim": bool(st.trim),
                "inputs": list(st.program._input_names),
                "fetches": st.program._declared_fetches or [],
                "feed": sorted(st.program._feed.items()),
            }
            for st in meta.steps
        ],
        "entry": _entry_signature(frame),
        "fetches": list(meta.fetches),
        "rows": frame.num_rows,
        "blocks": frame.num_blocks,
    }
    fp = hashlib.sha256(
        json.dumps(doc, sort_keys=True, default=str).encode()
    ).hexdigest()[:24]
    if len(memo) < 64:
        memo[memo_key] = fp
    return fp


def _calib_key(meta: "_FusedMeta", frame: TensorFrame) -> Tuple:
    # fetches distinguish a keep-pruned terminal chain from the full
    # chain of the same steps — their D2H volumes (and so their
    # measured rows/s) are different workloads — and the frame SIZE is
    # part of the workload too: the pool/serial crossover moves with
    # rows and block count, so a small frame's serial win must never
    # decide a large frame's dispatch
    return (
        tuple((st.kind, id(st.program), st.trim) for st in meta.steps),
        _entry_signature(frame),
        tuple(meta.fetches),
        frame.num_rows,
        frame.num_blocks,
    )


def _calib_entry(key: Tuple, meta: "_FusedMeta") -> Optional[Dict]:
    """The live entry for a chain (lock held by caller).  Keys embed
    ``id()``s, so — like ``_FUSED_CACHE`` — each record carries weakrefs
    to its programs and a recycled id can never alias a dead chain's
    measurements onto a different one (a stale entry is dropped)."""
    rec = _CALIBRATION.get(key)
    if rec is None:
        return None
    if not all(
        r() is st.program for r, st in zip(rec["_refs"], meta.steps)
    ):
        del _CALIBRATION[key]
        return None
    return rec


def _calib_note(
    meta: "_FusedMeta", frame: TensorFrame, dispatch: str, rows_per_s
) -> None:
    """Record one measured pool/serial execution.  ``affinity``
    dispatches (resident shards, ~0 H2D) are NOT folded into the pool
    bucket — their throughput would inflate the pool estimate used to
    decide uncached dispatches — and CSE reuses measure nothing."""
    if rows_per_s is None or dispatch not in ("pool", "serial"):
        return
    key = _calib_key(meta, frame)
    with _CALIBRATION_LOCK:
        rec = _calib_entry(key, meta)
        if rec is None:
            rec = _CALIBRATION[key] = {
                "_refs": tuple(
                    weakref.ref(st.program) for st in meta.steps
                ),
            }
        rec[dispatch] = max(rec.get(dispatch, 0.0), float(rows_per_s))
        _CALIBRATION.move_to_end(key)
        while len(_CALIBRATION) > _CALIBRATION_CAP:
            _CALIBRATION.popitem(last=False)
        # cross-process persistence (compile-cache dir configured):
        # fold the measurement into the fingerprint table too, so a
        # restarted process starts from measured history
        persisted = _calib_persist_table()
        if persisted is not None:
            fp = _calib_fingerprint(meta, frame)
            prec = persisted.setdefault(fp, {})
            if float(rows_per_s) > prec.get(dispatch, 0.0):
                # write the (tiny) file only when the best measurement
                # actually moved — steady state pays zero file writes
                prec[dispatch] = float(rows_per_s)
                _calib_persist_save()


def _calib_lookup(
    meta: "_FusedMeta", frame: TensorFrame
) -> Optional[Dict[str, float]]:
    key = _calib_key(meta, frame)
    with _CALIBRATION_LOCK:
        rec = _calib_entry(key, meta)
        live = (
            {k: v for k, v in rec.items() if not k.startswith("_")}
            if rec is not None
            else {}
        )
        persisted = _calib_persist_table()
        if persisted is not None:
            # persisted history fills what this process has not yet
            # measured (the post-restart first request); a live
            # measurement of the same kind wins — it is the fresher
            # observation of THIS process's conditions
            for k, v in persisted.get(
                _calib_fingerprint(meta, frame), {}
            ).items():
                live.setdefault(k, float(v))
        return live or None


def reset_calibration(persisted: bool = False) -> None:
    """Clear the in-memory calibration table (tests/bench legs);
    ``persisted=True`` also forgets the loaded fingerprint table so the
    next lookup re-reads the compile-cache file from disk."""
    global _calib_persist, _calib_persist_dir
    with _CALIBRATION_LOCK:
        _CALIBRATION.clear()
        if persisted:
            _calib_persist = None
            _calib_persist_dir = None


def calibration_snapshot() -> List[Dict[str, Any]]:
    """The live calibration table (test/bench surface): one record per
    measured chain signature with the best rows/s per dispatch kind."""
    with _CALIBRATION_LOCK:
        return [
            {
                "stages": len(k[0]),
                **{
                    kk: vv
                    for kk, vv in v.items()
                    if not kk.startswith("_")
                },
            }
            for k, v in _CALIBRATION.items()
        ]


# ---------------------------------------------------------------------------
# pool-vs-serial decision (roofline + retrace state)
# ---------------------------------------------------------------------------


def _fused_intensity(
    program: Program, frame: TensorFrame
) -> Optional[float]:
    """Arithmetic intensity (flops/byte) of the fused chain at this
    frame's largest (bucketed) block signature, from the XLA cost model
    ``roofline._aggregate_cost`` reads — memoized on the probe program,
    so it compiles once per signature."""
    import jax

    rows = max(frame.block_sizes or [0])
    if rows <= 0:
        return None
    if bucketing.enabled():
        rows = bucketing.bucket_for(rows)
    specs = {}
    for n in program.input_names:
        col = frame.column(n)
        cell = tuple(np.shape(col.data)[1:])
        st = dtypes.coerce(col.info.scalar_type)
        specs[n] = jax.ShapeDtypeStruct((rows,) + cell, st.np_dtype)
    sig = tuple(
        (n, specs[n].shape, str(specs[n].dtype)) for n in sorted(specs)
    )
    key = ("plan-intensity", sig)
    if key in program._derived:
        return program._derived_hit(key)
    try:
        param_specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
            program._params,
        )
        with observability.suppress_trace_count():
            compiled = program._jit_raw().lower(specs, param_specs).compile()
        flops, nbytes = _roofline._aggregate_cost(compiled)
        intensity = (
            float(flops) / float(nbytes) if flops and nbytes else None
        )
    except Exception:  # noqa: BLE001 - the decision degrades, never fails
        intensity = None
    while len(program._derived) >= program._DERIVED_CAP:
        program._derived.pop(next(iter(program._derived)))
    program._derived[key] = intensity
    return intensity


def _chain_warm(steps: Sequence[PlanStep]) -> bool:
    """Whether every stage's compiled entry already exists (traced by a
    prior planned run OR by the eager verbs — the caches are shared):
    pooling a warm chain costs no first-dispatch compiles."""
    for st in steps:
        prog = st.program
        if st.kind == "map_rows":
            if prog._vmapped is None:
                return False
        elif prog._jitted is None:
            return False
    return True


def _choose_dispatch(
    meta: _FusedMeta, frame: TensorFrame, warm: bool
) -> Dict[str, Any]:
    """The per-group dispatch decision record: ``affinity`` (sharded
    cache resident), ``pool`` (warm executables, or compute-bound per
    the roofline cost model), or ``serial`` (pool unavailable, or a
    cold transfer-bound chain where device-resident serial chaining
    beats paying one compile per device)."""
    rec: Dict[str, Any] = {"warm": bool(warm)}
    if frame_cache.active_cache(frame) is not None:
        rec.update(decision="affinity", reason="sharded_cache_resident")
        return rec
    devs = device_pool.pool_devices()
    rec["devices"] = len(devs)
    if (
        len(devs) < 2
        or frame.num_blocks < 2
        or frame.num_rows == 0
        or not _DEFAULT._frame_fresh(frame)
    ):
        rec.update(decision="serial", reason="pool_unavailable")
        return rec
    # blocks past the engine's chunked-streaming threshold must keep
    # the serial per-stage dispatch: there _stream_plan ingests them
    # chunk-by-chunk with bounded HBM and OOM-split handling, a
    # contract the pooled chain's whole-block device_put would bypass
    chunk = _DEFAULT.stream_chunk_bytes
    if chunk:
        per_row = 0
        for name in meta.src_inputs:
            col = frame.column(name)
            cell = tuple(np.shape(col.data)[1:])
            st = dtypes.coerce(col.info.scalar_type)
            per_row += int(np.prod(cell, dtype=np.int64)) * np.dtype(
                st.np_dtype
            ).itemsize
        if max(frame.block_sizes) * per_row >= 2 * chunk:
            rec.update(decision="serial", reason="stream_chunked_blocks")
            return rec
    if calibrate_enabled():
        # measured-throughput feedback (TFS_PLAN_CALIBRATE): once both
        # dispatch kinds have real measurements for this chain
        # signature, the observed winner overrides the static model
        measured = _calib_lookup(meta, frame)
        if measured and "pool" in measured and "serial" in measured:
            if measured["pool"] >= measured["serial"]:
                rec.update(decision="pool", reason="calibrated_pool")
            else:
                rec.update(decision="serial", reason="calibrated_serial")
            rec["calibration_rows_s"] = {
                k: round(v, 1) for k, v in measured.items()
            }
            return rec
    if warm:
        rec.update(decision="pool", reason="warm_executables")
        return rec
    intensity = _fused_intensity(meta.program, frame)
    rec["intensity_flops_per_byte"] = (
        round(intensity, 4) if intensity is not None else None
    )
    threshold = pool_min_intensity()
    rec["threshold"] = threshold
    if intensity is None or intensity >= threshold:
        rec.update(
            decision="pool",
            reason="no_cost_model" if intensity is None else "compute_bound",
        )
        return rec
    rec.update(decision="serial", reason="transfer_bound_cold")
    return rec


# ---------------------------------------------------------------------------
# fused-chain execution
# ---------------------------------------------------------------------------


def _apply_stages(
    meta: _FusedMeta, staged: Dict[str, Any], donate_entries: bool
) -> Dict[str, Any]:
    """Apply the chain's stages to ONE block's staged inputs via each
    stage program's OWN compiled entry (``jitted``/``vmapped`` — the
    executables the eager verbs run, live params bound), keeping every
    intermediate on the block's device.  Shape hints are re-checked per
    stage exactly like the eager dispatch.

    HBM discipline mirrors the eager pooled loop's: buffers no later
    stage (nor the fetches) reads are DROPPED after each stage, and a
    stage whose every input is a fresh buffer (this call's staged
    entries when ``donate_entries`` — never shards — or an earlier
    stage's intermediate) that dies at this stage runs through the
    engine's DONATING entry, so XLA reuses the input memory for the
    outputs exactly like ``_block_run(program, donate=True)`` does for
    the eager verbs.  Retries are safe by the existing contract: every
    attempt past the first re-stages fresh buffers."""
    donate_ok = prefetch.donate_inputs()
    blk = dict(staged)
    # fresh[c]: buffer c may be donated (created by/for this call only)
    fresh = {c: donate_entries for c in blk}
    for k, st in enumerate(meta.steps):
        prog = st.program
        cols = [prog.column_for_input(n) for n in prog.input_names]
        inputs = {n: blk[c] for n, c in zip(prog.input_names, cols)}
        live = meta.live_after[k]
        donate = (
            donate_ok
            and all(fresh.get(c, False) for c in cols)
            and not (set(cols) & live)
        )
        if st.kind == "map_rows":
            outs = _DEFAULT._rows_run(prog, donate)(inputs)
        else:
            outs = _DEFAULT._block_run(prog, donate)(inputs)
        del inputs
        _check_shape_hints(
            prog, outs, f"plan.{st.label}", cell_level=st.kind == "map_rows"
        )
        if st.trim:
            blk = dict(outs)
            fresh = {}
        else:
            blk.update(outs)
            # free buffers nothing downstream reads (donated ones are
            # dead already; the rest would otherwise pin HBM until the
            # chain ends)
            blk = {c: v for c, v in blk.items() if c in live}
            fresh = {c: f for c, f in fresh.items() if c in live}
        fresh.update({c: True for c in outs})
    return {f: blk[f] for f in meta.fetches}


def _check_chain_outputs(
    meta: _FusedMeta, outs: Dict[str, Any], n_rows: int
) -> None:
    if not meta.trim:
        for name, v in outs.items():
            if v.ndim == 0 or v.shape[0] != n_rows:
                raise ValidationError(
                    f"plan: fused output {name!r} has shape {v.shape} but "
                    f"the input block has {n_rows} rows; a non-trimmed "
                    f"chain must preserve the row count."
                )
    else:
        counts = {v.shape[0] if v.ndim else None for v in outs.values()}
        if len(counts) != 1 or None in counts:
            raise ValidationError(
                f"plan: trimmed chain outputs disagree on row count: "
                f"{ {k: v.shape for k, v in outs.items()} }"
            )


def _chain_pads(
    meta: _FusedMeta, frame: TensorFrame
) -> List[Optional[int]]:
    """Bucket targets for the pooled chain (the engine's
    ``_bucket_plan`` analog): pad each block's entry to its bucket so
    one executable per stage serves every block size — gated on EVERY
    block-level stage passing the jaxpr row-independence proof at the
    exact (real, padded) sizes (map_rows stages are independent by
    construction).  Trimmed chains keep exact shapes (program-defined
    output row counts cannot slice back)."""
    nb = frame.num_blocks
    none: List[Optional[int]] = [None] * nb
    if meta.trim or not bucketing.enabled():
        return none
    sizes = frame.block_sizes
    targets = [
        bucketing.bucket_for(s) if s > 0 else None for s in sizes
    ]
    targets = [
        t if t is not None and t != sizes[i] else None
        for i, t in enumerate(targets)
    ]
    if all(t is None for t in targets):
        return none
    proof_sizes = sorted(
        {sizes[i] for i, t in enumerate(targets) if t is not None}
        | {t for t in targets if t is not None}
    )
    for st, specs in zip(meta.steps, meta.stage_specs):
        if st.kind == "map_rows":
            continue
        if specs is None or not analysis.rows_independent(
            st.program, specs, proof_sizes
        ):
            return none
    return targets


class _TerminalReduce:
    """The fused terminal fold (round 19): the engine-built reduce
    executable (``_reduce_rows_setup``/``_reduce_blocks_setup`` — the
    exact ``run`` the eager verbs dispatch) plus the base -> resolved
    chain-output column map, applied per block INSIDE the pooled chain
    dispatch so no intermediate frame is ever assembled."""

    __slots__ = ("run", "bases", "cols", "sts", "verb")

    def __init__(self, run, bases, cols, sts, verb: str):
        self.run = run
        self.bases = bases
        self.cols = cols
        self.sts = sts
        self.verb = verb


def _chain_fold(
    meta: _FusedMeta,
    terminal: _TerminalReduce,
    staged: Dict[str, Any],
    donate_entries: bool,
    pad: Optional[int],
    n_rows: int,
) -> Optional[Dict[str, Any]]:
    """One block's chain + terminal fold, device-resident end to end:
    apply the stages, slice bucket pads back off, validate, then run the
    reduce executable on the block's device.  Returns None for a block
    whose (trimmed) output has no rows — the eager reduce skips those,
    and the fold shape must match it exactly."""
    outs = _apply_stages(meta, staged, donate_entries=donate_entries)
    if pad is not None:
        outs = {k: v[:n_rows] for k, v in outs.items()}
    _check_chain_outputs(meta, outs, n_rows)
    first = outs[meta.fetches[0]]
    if first.ndim == 0 or first.shape[0] == 0:
        return None
    arrays = {}
    for b in terminal.bases:
        v = outs[terminal.cols[b]]
        dt = terminal.sts[b].np_dtype
        if v.dtype != dt:  # mirror the eager _device_value cast
            v = v.astype(dt)
        arrays[b] = v
    return terminal.run(arrays)


def _run_serial_chain(
    steps: Sequence[PlanStep], frame: TensorFrame
) -> TensorFrame:
    """The fused-serial leg: stages dispatch through the pool-opted-out
    engine — device-resident chaining, only the first stage's inputs
    ever stage H2D, every engine contract (bucketing, streaming,
    donation, retries, empty frames) byte-identical to the eager serial
    path because it IS that path."""
    cur = frame
    for st in steps:
        if st.kind == "map_rows":
            cur = _SERIAL.map_rows(st.program, cur, host_stage=st.host_stage)
        else:
            cur = _SERIAL.map_blocks(
                st.program, cur, trim=st.trim, host_stage=st.host_stage
            )
    return cur


def _run_pooled_chain(
    meta: _FusedMeta,
    frame: TensorFrame,
    cache,
    devices: Sequence[Any],
    terminal: Optional[_TerminalReduce] = None,
) -> Tuple[Any, Dict[str, Any]]:
    """The pooled fused chain: each block stages ONCE (pruned entry
    columns, per-device staging lanes — or resident shards when the
    entry frame is sharded-cached), the whole stage chain runs on the
    block's device, and one overlapped readback window assembles the
    final outputs — the planner's replacement for per-verb pooling's
    host-assembly + re-staging between links.

    Fault tolerance mirrors the engine's pooled loops: retries re-stage
    fresh host buffers on the current effective device and re-run the
    chain; quarantine redirects follow ``PoolRun``.  Outputs are
    donation-adopted as the result frame's shards when sharding
    resolves, with a GC finalizer releasing the budget.

    ``terminal`` (round 19): fold each block's partial on its device
    instead of assembling any output frame — empty blocks are skipped
    (never dispatched), partials hop async to ONE combine device
    (``devices[0]``) in block order, and the return value is
    ``(partials, record)`` for the caller's ``_combine_partials`` —
    byte-for-byte the eager reduce's fold shape."""
    import jax

    sizes = frame.block_sizes
    nb = frame.num_blocks
    offsets = frame.offsets
    assignment = (
        list(cache.assignment)
        if cache is not None
        else device_pool.assign(sizes, len(devices))
    )
    pool = device_pool.PoolRun(
        devices,
        assignment,
        prefetch.prefetch_depth() or 1,
        affinity=cache is not None,
    )
    session = fault_tolerance.frame_session(nb, verb="plan", pool=pool)
    pads = _chain_pads(meta, frame)
    np_dtypes: Dict[str, Any] = {}
    host_cols: Dict[str, np.ndarray] = {}
    for name in meta.src_inputs:
        col = frame.column(name)
        np_dtypes[name] = dtypes.coerce(col.info.scalar_type).np_dtype
        host_cols[name] = np.asarray(col.data)

    def stage_block(bi, dev):
        lo, hi = offsets[bi], offsets[bi + 1]
        staged = {}
        for name in meta.src_inputs:
            a = host_cols[name][lo:hi]
            if a.dtype != np_dtypes[name]:
                a = a.astype(np_dtypes[name])
            if pads[bi] is not None:
                a = bucketing.pad_rows(a, pads[bi])
            observability.note_h2d_bytes(a.nbytes)
            staged[name] = jax.device_put(a, dev)
        return staged

    def stage_cached(bi, dev_i):
        """Entry dict for one sharded-cached block: resident shard
        columns pass through on their device; missing columns and
        evicted blocks re-stage from the authoritative host copy."""
        shard = cache.shard(bi) if dev_i == assignment[bi] else None
        lo, hi = offsets[bi], offsets[bi + 1]
        staged = {}
        used = False
        for name in meta.src_inputs:
            v = shard.get(name) if shard is not None else None
            if v is not None:
                if pads[bi] is not None:
                    v = bucketing.pad_rows(v, pads[bi])
                staged[name] = v
                used = True
                continue
            a = host_cols[name][lo:hi]
            if a.dtype != np_dtypes[name]:
                a = a.astype(np_dtypes[name])
            if pads[bi] is not None:
                a = bucketing.pad_rows(a, pads[bi])
            observability.note_h2d_bytes(a.nbytes)
            staged[name] = jax.device_put(a, devices[dev_i])
        return staged, used

    if cache is None:
        lanes = device_pool.lanes(
            devices, assignment, stage_block, name="tfs-plan"
        )
        lane_iters = [iter(ln) for ln in lanes]
        lane_dead = [False] * len(devices)
    else:
        lanes = []
    out_blocks: List[Optional[Dict[str, Any]]] = [None] * nb
    adopt_outs = (
        [None] * nb
        if (
            terminal is None
            and (
                cache is not None
                or len(frame_cache.shard_devices(None)) >= 2
            )
        )
        else None
    )
    partials: List[Dict[str, Any]] = []
    combine = devices[0]
    eff_assign: List[int] = []
    shard_hits = 0
    for bi in range(nb):
        cancellation.checkpoint()  # block boundary (pooled chain)
        t_blk = observability.trace_now()  # flight recorder
        di = assignment[bi]
        if terminal is not None and sizes[bi] == 0:
            # the eager reduce never dispatches empty blocks; consume
            # the staged lane entry so later blocks stay aligned
            if cache is None:
                if session is None:
                    next(lane_iters[di])
                else:
                    _DEFAULT._lane_next(
                        lane_iters[di], lane_dead, di, session, pool
                    )
            eff_assign.append(di)
            continue
        if cache is not None:
            di_eff = pool.effective_device(di) if session else di
            staged, used = (
                stage_cached(bi, di_eff)
                if (session is None or di_eff == di)
                else (None, False)
            )
            if used:
                shard_hits += 1
                observability.note_cache_shard_hit()
            elif session is not None and di_eff != di:
                session.note_cache_restage()
        elif session is None:
            staged = next(lane_iters[di])
        else:
            staged = _DEFAULT._lane_next(
                lane_iters[di], lane_dead, di, session, pool
            )
        if session is None:
            if terminal is not None:
                # chain + fold, device-resident: no assembly, no frame
                p = _chain_fold(
                    meta, terminal, staged, cache is None,
                    pads[bi], sizes[bi],
                )
            else:
                # entry buffers donate only when freshly staged this
                # call (never resident shards — shared frame state)
                outs = _apply_stages(
                    meta, staged, donate_entries=cache is None
                )
            del staged
            di_eff = di
        else:
            holder = {"v": staged}
            del staged

            def attempt(a, dev_i, _bi=bi, _h=holder, _di=di):
                # attempt 0 may consume the staged entry; every retry
                # (and any quarantine redirect) re-stages fresh host
                # buffers on the CURRENT device and re-runs the chain
                ins = _h.pop("v", None) if (a == 0 and dev_i == _di) else None
                _h.clear()
                restaged = ins is None
                if ins is None:
                    ins = stage_block(_bi, devices[dev_i])
                # re-staged buffers are fresh even for cached frames;
                # attempt-0 entries are fresh only without a cache
                if terminal is not None:
                    # the fold rides inside the attempt so a fault at
                    # the reduce dispatch retries the whole block
                    return _chain_fold(
                        meta, terminal, ins, restaged or cache is None,
                        pads[_bi], sizes[_bi],
                    )
                return _apply_stages(
                    meta, ins, donate_entries=restaged or cache is None
                )

            res = session.run(
                bi,
                sizes[bi],
                attempt,
                device=lambda _di=di: pool.effective_device(_di),
            )
            if terminal is not None:
                p = res
            else:
                outs = res
            di_eff = pool.effective_device(di)
        if terminal is not None:
            if p is not None:
                # async hop to the combine device, one reduced cell per
                # base, in block order — the eager partials' exact shape
                partials.append(
                    {
                        b: jax.device_put(p[b], combine)
                        for b in terminal.bases
                    }
                )
            eff_assign.append(di_eff)
            pool.note_dispatch(di_eff, sizes[bi])
            observability.trace_complete(
                f"plan+{terminal.verb} b{bi}", f"device/{di_eff}", t_blk,
                block=bi, rows=sizes[bi],
            )
            continue
        if pads[bi] is not None:
            # bucket-padded chain: slice the pad rows back off (the
            # per-stage proofs guarantee real rows' values)
            outs = {k: v[: sizes[bi]] for k, v in outs.items()}
        _check_chain_outputs(meta, outs, sizes[bi])
        if adopt_outs is not None:
            adopt_outs[bi] = outs
        eff_assign.append(di_eff)
        pool.submit(bi, di_eff, sizes[bi], outs, out_blocks)
        observability.trace_complete(
            f"plan b{bi}", f"device/{di_eff}", t_blk,
            block=bi, rows=sizes[bi],
        )
    pool.finish(out_blocks)
    if terminal is not None:
        rec = {
            "device_pool": pool.record(
                sum(ln.stats["stage_s"] for ln in lanes),
                sum(ln.stats["wait_s"] for ln in lanes),
            )
        }
        if cache is not None:
            fc = cache.record()
            fc["shard_hits"] = shard_hits
            rec["frame_cache"] = fc
        if session is not None and session.events():
            rec["fault_tolerance"] = session.record()
        return partials, rec
    out_frame = TensorFrame.from_blocks(out_blocks)
    if not meta.trim:
        # source columns not shadowed by chain outputs pass through
        # unchanged — including the PRUNED ones, host-side, zero staging
        extra = [
            c
            for c in frame.columns
            if c.info.name not in out_frame.column_names
        ]
        if extra:
            out_frame = TensorFrame(
                list(out_frame.columns) + extra, out_frame.offsets
            )
    rec: Dict[str, Any] = {
        "device_pool": pool.record(
            sum(ln.stats["stage_s"] for ln in lanes),
            sum(ln.stats["wait_s"] for ln in lanes),
        )
    }
    if cache is not None:
        fc = cache.record()
        fc["shard_hits"] = shard_hits
        rec["frame_cache"] = fc
    if session is not None and session.events():
        rec["fault_tolerance"] = session.record()
    adopted = (
        frame_cache.adopt(out_frame, devices, eff_assign, adopt_outs)
        if adopt_outs is not None
        else None
    )
    if adopted is not None:
        # planner-created cache: refund the HBM budget at frame GC
        weakref.finalize(out_frame, _release_cache, adopted)
        observability.note_plan_cache_insert()
        rec["adopted_blocks"] = adopted.resident_blocks()
    return out_frame, rec


# ---------------------------------------------------------------------------
# cross-plan common-subexpression sharing (round 19)
# ---------------------------------------------------------------------------
#
# A process-wide plan-signature registry: two planned executions of an
# IDENTICAL subplan — same source frame object, same step Program
# objects at the same live-params generation, same terminal pruning —
# execute it once.  Concurrent requests rendezvous on an in-flight
# entry: the first claimant (the owner) runs the segment under a
# PRIVATE root ledger, and at completion every consumer registered so
# far (owner + waiters) absorbs an exact integer share of the measured
# counters/blocks/rows (`RequestLedger.absorb`, the coalescer's round-16
# attribution contract) — so per-request ledgers still SUM to the
# global counters delta bit for bit.  Later identical chains reuse the
# shared result while it is alive (`plan_cse_hits`); signatures embed
# object ids but every entry holds weakrefs, so a recycled id can never
# alias stale results onto different frames/programs.


def _apportion_even(total: int, k: int) -> List[int]:
    """Split ``total`` into ``k`` equal integer shares that sum exactly
    (the shared :func:`observability.apportion` with unit weights — one
    implementation of the attribution-critical split, not two)."""
    return observability.apportion(int(total), [1] * k)


def _plan_signature(
    nodes: Sequence["LazyFrame"],
    frame: TensorFrame,
    keep: Optional[Set[str]],
) -> Optional[Tuple]:
    steps = []
    for nd in nodes:
        st = nd._step
        if st is None or st.stage_bound:
            # host-staged stages run arbitrary python per dispatch —
            # never share their results
            return None
        prog = st.program
        steps.append(
            (
                st.kind,
                st.trim,
                id(prog),
                getattr(prog, "_params_version", 0),
            )
        )
    return (
        id(frame),
        frame.num_rows,
        frame.num_blocks,
        _entry_signature(frame),
        tuple(steps),
        None if keep is None else tuple(sorted(keep)),
    )


class _ReduceResult(dict):
    """A reduce-terminal CSE result: plain dicts cannot carry weak
    references, and the registry holds completed results by weakref
    only (so cached outputs never outlive their consumers).  Behaves
    exactly like the ``{base: ndarray}`` dict it wraps."""

    __slots__ = ("__weakref__",)


class _CseEntry:
    __slots__ = (
        "event",
        "consumers",
        "done",
        "failed",
        "frame_wr",
        "guards",
    )

    def __init__(self, frame, nodes):
        self.event = threading.Event()
        # (ledger-or-None, slot) per consumer registered before
        # completion; the owner's pair is consumers[0]
        self.consumers: List[Tuple[Any, Dict[str, Any]]] = []
        self.done = False
        self.failed = False
        self.frame_wr = None
        self.guards = [weakref.ref(frame)] + [
            weakref.ref(nd._step.program) for nd in nodes
        ]

    def valid(self) -> bool:
        return all(g() is not None for g in self.guards)


class _PlanRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[Tuple, _CseEntry]" = (
            collections.OrderedDict()
        )
        # signature -> {"executions", "hits", "stages"}; survives result
        # GC so tfs.doctor()'s cse_miss rule can see repeat executions
        self._stats: "collections.OrderedDict[Tuple, Dict[str, int]]" = (
            collections.OrderedDict()
        )
        self._cap = 256

    def _stat(self, sig: Tuple, stages: int) -> Dict[str, int]:
        rec = self._stats.setdefault(
            sig, {"executions": 0, "hits": 0, "stages": stages}
        )
        self._stats.move_to_end(sig)
        while len(self._stats) > self._cap:
            self._stats.popitem(last=False)
        return rec

    def lookup_or_claim(
        self, sig: Tuple, frame: TensorFrame, nodes: Sequence["LazyFrame"]
    ) -> Tuple:
        """("hit", frame) | ("wait", slot, event) | ("own", entry)."""
        with self._lock:
            for key in [
                k for k, e in self._entries.items() if not e.valid()
            ]:
                del self._entries[key]
            ent = self._entries.get(sig)
            if ent is not None:
                if ent.done and not ent.failed:
                    out = ent.frame_wr() if ent.frame_wr else None
                    if out is not None:
                        self._stat(sig, len(nodes))["hits"] += 1
                        self._entries.move_to_end(sig)
                        return ("hit", out)
                    # result was garbage-collected: execute afresh
                elif not ent.done:
                    slot: Dict[str, Any] = {}
                    ent.consumers.append(
                        (observability.current_request(), slot)
                    )
                    # a rendezvous IS a share: count it here so the
                    # cse_miss doctor rule cannot fire on workloads
                    # whose sharing is always concurrent (the owner
                    # failing is the rare corner this may overcount)
                    self._stat(sig, len(nodes))["hits"] += 1
                    return ("wait", slot, ent.event)
            ent = _CseEntry(frame, nodes)
            ent.consumers.append(
                (observability.current_request(), {})
            )
            self._entries[sig] = ent
            self._stat(sig, len(nodes))["executions"] += 1
            while len(self._entries) > self._cap:
                _, old = self._entries.popitem(last=False)
                if not old.done:
                    old.failed = True
                    old.done = True
                    old.event.set()
            return ("own", ent)

    def complete(self, sig: Tuple, ent: _CseEntry, out, led) -> None:
        """Owner finished: deliver the frame to every waiter, apportion
        the private ledger's exact delta across all consumers
        registered by now, and downgrade the entry to a weakref.
        Waiters that ABANDONED the rendezvous (woken early by a cap
        eviction and already paying their own execution) are excluded —
        absorbing a share on top of their own full delta would
        double-bill their request ledgers."""
        counters = {k2: v for k2, v in led.counters.items() if v}
        blocks = dict(led.blocks_per_device)
        # snapshot, absorb, and delivery all under the registry lock:
        # an abandoning waiter (cap-evicted rendezvous) flips its flag
        # under the same lock, so it is either excluded here or finds
        # its frame delivered — never both billed and self-paying.
        # Lock order is registry -> ledger only; ledger locks are leaf.
        with self._lock:
            consumers = [
                c for c in ent.consumers if not c[1].get("abandoned")
            ]
            ent.frame_wr = weakref.ref(out)
            ent.done = True
            k = len(consumers)
            shares = {
                k2: _apportion_even(v, k) for k2, v in counters.items()
            }
            block_shares = {
                d: _apportion_even(v, k) for d, v in blocks.items()
            }
            row_shares = _apportion_even(led.rows, k)
            for i, (consumer_led, slot) in enumerate(consumers):
                if consumer_led is not None:
                    consumer_led.absorb(
                        {k2: s[i] for k2, s in shares.items()},
                        {d: s[i] for d, s in block_shares.items()},
                        row_shares[i],
                    )
                slot["frame"] = out
            # waiters hold their own slot references; dropping the list
            # keeps the registry from pinning result frames alive
            ent.consumers = []
        ent.event.set()

    def fail(self, sig: Tuple, ent: _CseEntry) -> None:
        with self._lock:
            ent.failed = True
            ent.done = True
            if self._entries.get(sig) is ent:
                del self._entries[sig]
        ent.event.set()

    def stats(self) -> List[Dict[str, int]]:
        with self._lock:
            return [dict(v) for v in self._stats.values()]


_REGISTRY = _PlanRegistry()


def recent_plan_stats() -> List[Dict[str, int]]:
    """Per-signature execution/hit counts from the CSE registry — the
    evidence behind ``tfs.doctor()``'s ``cse_miss`` rule (injectable
    there as ``plans=``)."""
    return _REGISTRY.stats()


def _cse_execute(
    nodes: List["LazyFrame"],
    frame: TensorFrame,
    records: List[Dict],
    start_idx: int,
    cse: bool = True,
    keep: Optional[Set[str]] = None,
) -> TensorFrame:
    """Execute one flush segment through the CSE registry: reuse a live
    identical result, rendezvous with an in-flight execution, or own the
    execution under a private root ledger and apportion its exact cost
    across every consumer registered by completion."""
    sig = (
        _plan_signature(nodes, frame, keep)
        if (cse and cse_enabled())
        else None
    )
    if sig is None:
        return _flush(nodes, frame, records, start_idx, keep=keep)
    claim = _REGISTRY.lookup_or_claim(sig, frame, nodes)
    verb = "+".join(nd._step.label for nd in nodes)
    if claim[0] == "hit":
        observability.note_plan_cse_hit()
        records.append(
            {
                "stage": start_idx,
                "verb": verb,
                "fused": len(nodes),
                "dispatch": "cse",
                "reason": "registry_hit",
                "rows": claim[1].num_rows,
            }
        )
        return claim[1]
    if claim[0] == "wait":
        _, slot, event = claim
        try:
            while not event.wait(0.05):
                cancellation.checkpoint()  # deadlines cut the wait too
        except BaseException:
            # cancelled mid-rendezvous: renounce the share UNDER THE
            # LOCK so the owner's complete() cannot bill this request
            # for a result it never received (if the frame was already
            # delivered, the absorbed share legitimately stands)
            with _REGISTRY._lock:
                if slot.get("frame") is None:
                    slot["abandoned"] = True
            raise
        out = slot.get("frame")
        if out is None:
            # woken without a result (owner failed, or the entry was
            # cap-evicted mid-flight): declare the rendezvous abandoned
            # UNDER THE LOCK so a late complete() cannot also absorb a
            # share for us, then re-check — the flag and the delivery
            # are ordered by the registry lock
            with _REGISTRY._lock:
                if slot.get("frame") is None:
                    slot["abandoned"] = True
            out = slot.get("frame")
        if out is not None:
            observability.note_plan_cse_hit()
            records.append(
                {
                    "stage": start_idx,
                    "verb": verb,
                    "fused": len(nodes),
                    "dispatch": "cse",
                    "reason": "shared_inflight",
                    "rows": out.num_rows,
                }
            )
            return out
        # the owner failed (or was evicted mid-flight): pay our own way
        return _flush(nodes, frame, records, start_idx, keep=keep)
    ent = claim[1]
    # the owner's execution runs under a PRIVATE root ledger so its
    # delta can be apportioned exactly; the suspended request context
    # gets its share back through absorb (consumers[0] is the owner)
    tok0 = observability.activate_request(None)
    led = observability.RequestLedger(method="plan_cse")
    tok1 = observability.activate_request(led)
    try:
        out = _flush(nodes, frame, records, start_idx, keep=keep)
    except BaseException:
        observability.deactivate_request(tok1)
        observability.deactivate_request(tok0)
        _REGISTRY.fail(sig, ent)
        raise
    observability.deactivate_request(tok1)
    observability.deactivate_request(tok0)
    _REGISTRY.complete(sig, ent, out, led)
    return out


# ---------------------------------------------------------------------------
# the lazy frame
# ---------------------------------------------------------------------------


class LazyFrame:
    """A frame whose verbs build a logical plan (``frame.lazy()``).

    Nodes form a DAG: each derived LazyFrame holds its parent strongly
    (the plan must survive) and parents hold children weakly (consumer
    bookkeeping must not leak).  Materialisation memoizes the executed
    frame on the node, so a shared subplan executes once; a node with
    two or more consumers becomes an optimization *barrier* and — when a
    device pool is available — gets an auto-inserted sharded cache over
    the columns its consumers read.

    Any TensorFrame attribute not defined here (``collect``,
    ``to_arrays``, ``column``, ``schema``, …) materialises the plan and
    delegates — the lazy surface is a superset of the eager one."""

    _tfs_lazy = True

    def __init__(
        self,
        source: Optional[TensorFrame] = None,
        parent: Optional["LazyFrame"] = None,
        step: Optional[PlanStep] = None,
    ):
        if (source is None) == (parent is None):
            raise ValidationError(
                "LazyFrame: exactly one of source/parent is required"
            )
        self._source = source
        self._parent = parent
        self._step = step
        self._child_refs: List[Any] = []
        self._children = 0  # registered consumers (derived + terminal)
        self._materialized: Optional[TensorFrame] = (
            source if step is None else None
        )
        self._mat_uses = 0  # dispatch-consumptions of the memoized frame
        self._auto_cached = False
        self._finalizer = None
        self._last_records: List[Dict[str, Any]] = []
        self._runs = 0  # times this node's step has executed

    # -- plan building -------------------------------------------------------

    def lazy(self) -> "LazyFrame":
        return self

    # guards shared plan-tree bookkeeping (root get-or-create, child
    # registration): concurrent bridge requests append chains to ONE
    # shared per-frame root, and unlocked read-modify-writes there
    # would lose consumer counts / drop live child refs — starving the
    # auto-cache trigger and _needed_below's cached-column set
    _TREE_LOCK = threading.Lock()

    def _bump(self, attr: str) -> int:
        """Locked increment for shared-node consumer bookkeeping
        (``_children``/``_mat_uses``): concurrent requests off one
        shared root must not lose counts — the auto-cache trigger
        reads them."""
        with LazyFrame._TREE_LOCK:
            v = getattr(self, attr) + 1
            setattr(self, attr, v)
            return v

    def _append(
        self,
        kind: str,
        program: Program,
        trim: bool = False,
        host_stage: Optional[Mapping[str, Any]] = None,
    ) -> "LazyFrame":
        step = PlanStep(kind, program, trim=trim, host_stage=host_stage)
        child = LazyFrame(parent=self, step=step)
        with LazyFrame._TREE_LOCK:
            if len(self._child_refs) >= 32:
                # epochs loops re-derive from one shared root every
                # pass: drop dead consumer refs so the list stays
                # bounded by the LIVE fan-out, not the plan's lifetime
                self._child_refs = [
                    r for r in self._child_refs if r() is not None
                ]
            self._child_refs.append(weakref.ref(child))
            self._children += 1  # lock already held (non-reentrant)
        return child

    def group_by(self, *keys: str) -> GroupedFrame:
        """Group for ``aggregate``.  An unmaterialised plan defers the
        materialisation to the aggregate itself (round 19): the
        aggregate then knows exactly which chain outputs it reads, so
        the one materialisation it still needs (group structure is
        data-dependent) fetches ONLY the key + reduced columns.  Key
        contracts are still checked HERE whenever the chain's schema is
        statically known — deferral must not move the eager call-site
        error to aggregate time."""
        self._bump("_children")
        if self._materialized is not None:
            return GroupedFrame(self._materialized, keys)
        if keys:
            self._check_group_keys(keys)
        return LazyGroupedFrame(self, keys)

    def _check_group_keys(self, keys: Sequence[str]) -> None:
        """The eager ``GroupedFrame`` constructor's key checks, run
        against the chain's statically inferred output schema (entry
        columns + analyzed derived columns).  An opaque chain (host
        stages, unresolvable inputs) defers to aggregate time."""
        chain: List[LazyFrame] = []
        cur = self
        while cur._materialized is None:
            chain.append(cur)
            cur = cur._parent
        chain.reverse()
        src = cur._materialized
        if src is None or not chain:
            return
        steps = [nd._step for nd in chain]
        n, _, _ = _fusable_run(steps, _device_infos(src))
        if n != len(steps):
            return  # schema not statically known: checked at aggregate
        meta = _compose(steps, src)
        shim = _SchemaShim(src, meta.final_infos, trim=meta.trim)
        for k in keys:
            ci = shim.schema[k]  # raises SchemaError exactly like eager
            if ci.cell_shape.rank != 0:
                raise ValidationError(
                    f"group_by: key column {k!r} must be scalar, has "
                    f"cell shape {ci.cell_shape}"
                )

    def frame(self) -> TensorFrame:
        """Force execution and return the materialised TensorFrame."""
        return self._materialize(count_use=False)

    # -- execution -----------------------------------------------------------

    def _materialize(
        self,
        needed_hint: Optional[Set[str]] = None,
        count_use: bool = True,
        keep: Optional[Set[str]] = None,
        cse: bool = True,
    ) -> TensorFrame:
        """Execute the plan.  ``keep`` (round 19): prune the FINAL fused
        group's fetches to the named derived columns (a terminal
        consumer's read set) — the result is then partial by design and
        is NOT memoized on the node.  ``cse=False`` bypasses the
        cross-plan registry (per-window streaming plans, whose source
        frames never repeat)."""
        if self._materialized is not None:
            if count_use:
                self._bump("_mat_uses")
                if self._mat_uses >= 2:
                    self._ensure_auto_cache(needed_hint)
            return self._materialized

        # the chain of unmaterialised steps back to the nearest memo/root
        chain: List[LazyFrame] = []
        cur = self
        while cur._materialized is None:
            chain.append(cur)
            cur = cur._parent
        chain.reverse()
        entry = cur
        frame = entry._materialized
        # one more dispatch reads the shared entry: promote it to an
        # auto cache on its second consumption (the epochs pattern)
        entry._bump("_mat_uses")
        if entry._mat_uses >= 2:
            entry._ensure_auto_cache(_first_step_cols(chain) or needed_hint)

        records: List[Dict[str, Any]] = []
        with observability.verb_span(
            "plan", frame.num_rows, frame.num_blocks
        ) as span:
            pending: List[LazyFrame] = []
            done = 0
            for nd in chain:
                pending.append(nd)
                if nd._children >= 2 and nd is not chain[-1]:
                    # shared subplan: materialisation barrier + cache
                    frame = _cse_execute(
                        pending, frame, records, done, cse=cse
                    )
                    done += len(pending)
                    pending = []
                    nd._materialized = frame
                    nd._mat_uses = 1
                    nd._ensure_auto_cache(None)
                    frame = nd._materialized
            if pending:
                frame = _cse_execute(
                    pending, frame, records, done, cse=cse, keep=keep
                )
            span.annotate(
                "planner",
                {
                    "stages": records,
                    "fused_groups": sum(
                        1 for r in records if r.get("fused", 0) >= 2
                    ),
                    "pruned_columns": sorted(
                        {c for r in records for c in r.get("pruned", ())}
                    ),
                },
            )
        if keep is None:
            self._materialized = frame
            self._mat_uses = 1
        self._last_records = records
        return frame

    # -- auto cache ----------------------------------------------------------

    # serializes auto-cache insertion across threads: concurrent bridge
    # requests materializing off one shared root must not both pass the
    # check-then-act and build two caches for one frame (the loser's
    # shards would stay charged against TFS_HBM_BUDGET until frame GC)
    _AUTOCACHE_LOCK = threading.Lock()

    def _ensure_auto_cache(
        self, needed_hint: Optional[Set[str]] = None
    ) -> None:
        """Insert the sharded cache on this node's materialised frame,
        over the columns downstream consumers read — once, and only when
        shard placement resolves (>= 2 pool devices per
        ``TFS_CACHE_SHARDED``'s auto rule, exactly like ``cache()``'s
        default).  A ``weakref.finalize`` on the frame releases the
        shards when the planned frame is garbage-collected, refunding
        ``TFS_HBM_BUDGET`` deterministically instead of waiting for a
        later charge walk to prune the dead entries."""
        mat = self._materialized
        if mat is None or self._auto_cached:
            return
        with LazyFrame._AUTOCACHE_LOCK:
            if self._auto_cached:
                return
            if frame_cache.active_cache(mat) is not None:
                self._auto_cached = True  # adopted / user-cached already
                return
            devs = frame_cache.shard_devices(None)
            if len(devs) < 2:
                return
            needed, everything = self._needed_below()
            if needed_hint:
                needed |= set(needed_hint)
            cacheable = [
                name
                for name in _device_infos(mat)
                if not mat.column(name).is_device
                and (everything or name in needed)
            ]
            if not cacheable:
                return
            cache = frame_cache.build(mat, sorted(cacheable), devices=devs)
            if cache is None:
                return
            frame_cache.attach(mat, cache)
            self._finalizer = weakref.finalize(mat, _release_cache, cache)
            self._auto_cached = True
        observability.note_plan_cache_insert()
        _log.info(
            "planner: auto-inserted sharded cache over %s (%d consumers)",
            cacheable,
            max(self._children, self._mat_uses),
        )

    def _needed_below(self) -> Tuple[Set[str], bool]:
        """Columns of this node's frame that registered downstream
        stages consume (transitively), plus an everything flag when a
        host-staged descendant makes the set unknowable.
        Over-approximation is safe: the host copy stays authoritative,
        extra shards are only bytes."""
        needed: Set[str] = set()
        everything = False
        for ref in self._child_refs:
            child = ref()
            if child is None or child._step is None:
                continue
            st = child._step
            if st.stage_bound:
                everything = True
            needed.update(
                st.program.column_for_input(n)
                for n in st.program.input_names
            )
            sub, all_flag = child._needed_below()
            needed |= sub
            everything = everything or all_flag
        return needed, everything

    # -- terminal verbs ------------------------------------------------------

    def _reduce(self, verb: str, program: Program, mode: str = "tree"):
        self._bump("_children")
        if self._materialized is None:
            out = self._cse_reduce(verb, program, mode)
            if out is not None:
                return out
        mat = self._materialize(needed_hint=_reduce_cols(program))
        if verb == "reduce_rows":
            return _DEFAULT.reduce_rows(program, mat, mode=mode)
        return _DEFAULT.reduce_blocks(program, mat)

    def _cse_reduce(self, verb: str, program: Program, mode):
        """Route the fused terminal reduce through the CSE registry
        (round-22 close of the round-19 residual): concurrent requests
        ending in the SAME fused reduce over the SAME chain rendezvous
        and execute once, with the owner's private-ledger delta
        apportioned exactly across every consumer — the same share
        semantics map-terminal plans already have.  The signature is
        the chain's plan signature extended with the reduce's identity
        (verb, mode, program), and the entry additionally guards on the
        reduce program's lifetime.  Falls back to a solo
        ``_fused_terminal_reduce`` whenever the signature cannot be
        built (host stages, CSE off); a ``None`` from the fused path
        (pre-dispatch bail: serial decision, trimmed chain, source
        column read) fails the entry so waiters pay their own way, and
        the caller falls through to materialize-then-reduce."""
        if not cse_enabled():
            return self._fused_terminal_reduce(verb, program, mode)
        tc = self._terminal_chain()
        if tc is None:
            # cheap pre-check: no fusable chain means the fused path
            # bails immediately anyway — don't mint registry entries
            # for plans that always materialize
            return self._fused_terminal_reduce(verb, program, mode)
        _entry, chain, _steps, frame = tc
        base_sig = _plan_signature(chain, frame, None)
        if base_sig is None:
            return self._fused_terminal_reduce(verb, program, mode)
        sig = base_sig + (
            (
                "reduce",
                verb,
                mode,
                id(program),
                getattr(program, "_params_version", 0),
            ),
        )
        claim = _REGISTRY.lookup_or_claim(sig, frame, chain)
        label = (
            "+".join(nd._step.label for nd in chain) + f"+{verb}"
        )
        if claim[0] == "hit":
            observability.note_plan_cse_hit()
            self._last_records = [
                {
                    "stage": 0,
                    "verb": label,
                    "fused": len(chain) + 1,
                    "dispatch": "cse",
                    "reason": "registry_hit",
                    "terminal": verb,
                }
            ]
            return claim[1]
        if claim[0] == "wait":
            _, slot, event = claim
            try:
                while not event.wait(0.05):
                    cancellation.checkpoint()
            except BaseException:
                with _REGISTRY._lock:
                    if slot.get("frame") is None:
                        slot["abandoned"] = True
                raise
            out = slot.get("frame")
            if out is None:
                with _REGISTRY._lock:
                    if slot.get("frame") is None:
                        slot["abandoned"] = True
                out = slot.get("frame")
            if out is not None:
                observability.note_plan_cse_hit()
                self._last_records = [
                    {
                        "stage": 0,
                        "verb": label,
                        "fused": len(chain) + 1,
                        "dispatch": "cse",
                        "reason": "shared_inflight",
                        "terminal": verb,
                    }
                ]
                return out
            # owner failed or bailed to the materialized path: run our
            # own fused attempt (it may bail to materialize too)
            return self._fused_terminal_reduce(verb, program, mode)
        ent = claim[1]
        # the chain guards came from lookup_or_claim; the reduce
        # program's lifetime guards this entry too (its id is in the
        # signature — an id reused by a NEW program must not hit)
        ent.guards.append(weakref.ref(program))
        tok0 = observability.activate_request(None)
        led = observability.RequestLedger(method="plan_cse")
        tok1 = observability.activate_request(led)
        try:
            out = self._fused_terminal_reduce(verb, program, mode)
        except BaseException:
            observability.deactivate_request(tok1)
            observability.deactivate_request(tok0)
            _REGISTRY.fail(sig, ent)
            raise
        observability.deactivate_request(tok1)
        observability.deactivate_request(tok0)
        if out is None:
            # pre-dispatch bail: nothing executed, nothing to share —
            # waiters wake, fall back, and pay their own (cheap) way
            _REGISTRY.fail(sig, ent)
            return None
        out = _ReduceResult(out)
        _REGISTRY.complete(sig, ent, out, led)
        return out

    def _terminal_chain(self):
        """The unmaterialised step chain back to the nearest memo/root,
        or None when a terminal fusion cannot apply: no steps, an
        interior shared subplan (its memoized barrier is worth more than
        the fold), or an unfusable run (host stages, ragged inputs)."""
        chain: List[LazyFrame] = []
        cur = self
        while cur._materialized is None:
            chain.append(cur)
            cur = cur._parent
        chain.reverse()
        frame = cur._materialized
        if not chain or frame.num_rows == 0:
            return None
        if any(nd._children >= 2 for nd in chain[:-1]):
            return None
        steps = [nd._step for nd in chain]
        n, _, _ = _fusable_run(steps, _device_infos(frame))
        if n != len(steps):
            return None
        return cur, chain, steps, frame

    def _fused_terminal_reduce(self, verb: str, program: Program, mode):
        """The round-19 fused terminal fold: when the whole pending
        chain is one fusable run, its dispatch would pool, and every
        reduce base resolves to a chain output, fold each block's
        partial inside the pooled chain dispatch — no intermediate
        frame is ever assembled (no D2H readback, no re-staging H2D) —
        then finish with the engine's own ``_combine_partials``.
        Returns None whenever the eager materialize-then-reduce path
        should run instead (bit-identical either way: the fold shape,
        executables, and combine device are the eager ones)."""
        tc = self._terminal_chain()
        if tc is None:
            return None
        entry, chain, steps, frame = tc
        meta0 = _compose(steps, frame)
        if meta0.trim:
            # trimmed chains have program-defined per-block row counts;
            # the materialized path keeps their contract checks simple
            return None
        # the engine's own setup over the chain's inferred output
        # schema — contract violations surface exactly like eager
        shim = _SchemaShim(frame, meta0.final_infos)
        if verb == "reduce_rows":
            bases, reduced, run = _DEFAULT._reduce_rows_setup(
                program, shim, mode
            )
        else:
            bases, reduced, run = _DEFAULT._reduce_blocks_setup(
                program, shim
            )
        cols = {b: reduced[b].name for b in bases}
        if not all(cols[b] in set(meta0.fetches) for b in bases):
            # the reduce reads a source/passthrough column the chain
            # does not produce: materialize (it must be staged anyway)
            return None
        meta = _compose(steps, frame, keep=set(cols.values()))
        warm = any(nd._runs > 0 for nd in chain) or _chain_warm(steps)
        rec = _choose_dispatch(meta, frame, warm)
        decision = rec.pop("decision")
        reason = rec.pop("reason")
        if decision not in ("pool", "affinity"):
            # serial: the fused-serial chain + eager reduce IS the
            # baseline (device-resident, single device) — no round trip
            # to eliminate
            return None
        sts = {b: dtypes.coerce(reduced[b].scalar_type) for b in bases}
        terminal = _TerminalReduce(run, bases, cols, sts, verb)
        # one more consumption of the shared entry (epochs promotion)
        entry._bump("_mat_uses")
        if entry._mat_uses >= 2:
            entry._ensure_auto_cache(_first_step_cols(chain))
        records: List[Dict[str, Any]] = []
        with observability.verb_span(
            "plan", frame.num_rows, frame.num_blocks
        ) as span:
            cache = frame_cache.active_cache(frame)
            devices = (
                cache.devices
                if cache is not None
                else device_pool.pool_devices()
            )
            (partials, run_rec), measured = _measured(
                lambda: _run_pooled_chain(
                    meta, frame, cache, devices, terminal=terminal
                ),
                frame.num_rows,
            )
            rec.update(run_rec)
            rec.update(measured)
            # feed the calibration table too (keep-pruned fetch key —
            # a different workload from the full chain's); terminal
            # chains only ever measure the pooled side (their serial
            # decision falls back to materialize-then-reduce), so the
            # calibrated override stays inert for them until a serial
            # measurement exists — one-sided entries never decide
            _calib_note(
                meta, frame, decision, measured.get("rows_per_s")
            )
            if len(steps) >= 2:
                observability.note_plan_fused_dispatch()
            observability.note_plan_fused_reduce()
            if meta.pruned:
                observability.note_plan_columns_pruned(len(meta.pruned))
            records.append(
                {
                    "stage": 0,
                    "verb": "+".join(st.label for st in steps)
                    + f"+{verb}",
                    "fused": len(steps) + 1,
                    "dispatch": decision,
                    "reason": reason,
                    "terminal": verb,
                    "pruned": list(meta.pruned),
                    **rec,
                }
            )
            final = _DEFAULT._combine_partials(run, bases, partials)
            out = {b: _np(final[b]) for b in bases}
            span.annotate(
                "planner",
                {
                    "stages": records,
                    "fused_groups": 1,
                    "fused_terminal": verb,
                },
            )
        for nd in chain:
            nd._runs += 1
        self._last_records = records
        return out

    def _aggregate_terminal(
        self,
        program: Program,
        keys: Sequence[str],
        grouped: Optional["LazyGroupedFrame"] = None,
    ) -> TensorFrame:
        """Terminal-pruned aggregate (round 19): materialise the chain
        fetching ONLY the key + reduced columns the aggregate reads
        (everything else is never assembled to host), then run the
        UNCHANGED eager aggregate over it — grouping numerics are the
        eager engine's, bit for bit.

        Repeat aggregates over one ``grouped`` handle stay
        materialize-once: a pruned result is memoized on the handle per
        read set, and a SECOND aggregate with a different read set
        switches to the full (node-memoized) materialisation — the
        round-14 behavior — instead of re-executing the chain per
        program."""
        from .validation import check_reduce_blocks

        tc = self._terminal_chain()
        if tc is None or self._materialized is not None:
            mat = self._materialize(needed_hint=set(keys))
            return _DEFAULT.aggregate(program, GroupedFrame(mat, keys))
        entry, chain, steps, frame = tc
        meta0 = _compose(steps, frame)
        shim = _SchemaShim(frame, meta0.final_infos, trim=meta0.trim)
        reduced = check_reduce_blocks(program, shim, verb="aggregate")
        needed = set(keys) | {ci.name for ci in reduced.values()}
        keep = needed & set(meta0.fetches)
        fz = frozenset(keep) if keep else None
        if grouped is not None:
            hit = grouped._pruned.get(fz)
            if hit is not None:
                return _DEFAULT.aggregate(
                    program, GroupedFrame(hit, keys)
                )
            if grouped._agg_count >= 1:
                # second aggregate with a NEW read set: one full
                # materialisation (memoized on the node) serves this
                # and every later aggregate/frame() for free
                mat = self._materialize(needed_hint=needed)
                grouped._agg_count += 1
                return _DEFAULT.aggregate(
                    program, GroupedFrame(mat, keys)
                )
        mat = self._materialize(
            needed_hint=needed,
            count_use=False,
            keep=keep or None,
        )
        # the counter tracks ACTUAL fetch pruning: keep applies only to
        # a fused tail group dispatched pooled/affinity — a lone eager
        # stage always computes its full fetch set, and the fused-
        # SERIAL leg runs the eager per-stage chain (keep ignored)
        if keep and any(
            r.get("fused", 0) >= 2
            and r.get("dispatch") in ("pool", "affinity")
            for r in self._last_records
        ):
            observability.note_plan_fused_reduce()
        if grouped is not None:
            grouped._pruned[fz] = mat
            grouped._agg_count += 1
        return _DEFAULT.aggregate(program, GroupedFrame(mat, keys))

    # -- surface -------------------------------------------------------------

    @property
    def is_materialized(self) -> bool:
        return self._materialized is not None

    def warmup(self) -> List[str]:
        """Prime the fused-chain executables this plan will actually
        dispatch — bucketed sizes, donating entries, every pool device —
        without executing the plan (:func:`warm_plan`)."""
        return warm_plan(self)

    def explain_plan(self) -> str:
        return explain_plan(self)

    def explain_analyze(self) -> str:
        """Execute the plan under a request ledger and render the
        measured report (``tfs.explain(frame, analyze=True)``)."""
        return explain_analyze(self)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._materialize(count_use=False), name)

    def __repr__(self):
        return self.explain_plan()


class _SchemaShim:
    """Schema-only stand-in for a chain's (never materialised) output
    frame — exactly the surface the engine's reduce/aggregate setup and
    validation read: ``schema``, ``num_rows``, ``block_sizes``.  Derived
    chain outputs shadow same-named source columns; untouched source
    columns pass through (the non-trimmed chain contract).  A TRIMMED
    chain drops every passthrough, so its shim carries ONLY the derived
    columns — merging entry columns would falsely validate keys the
    real output frame will not have."""

    __slots__ = ("schema", "num_rows", "block_sizes")

    def __init__(
        self,
        entry: TensorFrame,
        final_infos: Mapping[str, ColumnInfo],
        trim: bool = False,
    ):
        cols: Dict[str, ColumnInfo] = (
            {} if trim else {ci.name: ci for ci in entry.schema}
        )
        cols.update(final_infos)
        self.schema = Schema(list(cols.values()))
        self.num_rows = entry.num_rows
        self.block_sizes = list(entry.block_sizes)


class LazyGroupedFrame(GroupedFrame):
    """``lazy.group_by(...)`` over an unmaterialised plan: the grouping
    is deferred to ``aggregate``, which knows its read set and prunes
    the chain's fetches to exactly keys + reduced columns
    (:meth:`LazyFrame._aggregate_terminal`).  Accessing ``.frame``
    materialises the full plan (the eager escape hatch)."""

    def __init__(self, lazy: "LazyFrame", keys: Sequence[str]):
        if not keys:
            raise ValidationError("group_by needs at least one key column")
        self.lazy = lazy
        self.keys = list(keys)
        # materialize-once across repeat aggregates: pruned results per
        # read set, and the count that flips to full materialisation
        self._pruned: Dict[Optional[frozenset], TensorFrame] = {}
        self._agg_count = 0

    @property
    def frame(self) -> TensorFrame:
        return self.lazy._materialize(count_use=False)


def _release_cache(cache) -> None:
    """``weakref.finalize`` body for planner-created caches: drop the
    shards and refund the HBM budget at frame GC."""
    cache.release()


def _first_step_cols(chain: Sequence[LazyFrame]) -> Optional[Set[str]]:
    if not chain:
        return None
    st = chain[0]._step
    return {st.program.column_for_input(n) for n in st.program.input_names}


def _reduce_cols(program: Program) -> Set[str]:
    """Frame columns a reduce program will consume — the auto-cache
    hint.  Feed-dict renames resolve to the fed column; unrenamed inputs
    strip the reduce suffix (``x_input`` / ``x_1`` / ``x_2`` -> ``x``)."""
    cols: Set[str] = set()
    for n in program.input_names:
        col = program.column_for_input(n)
        if col != n:
            cols.add(col)
            continue
        for suf in ("_input", "_1", "_2"):
            if n.endswith(suf):
                cols.add(n[: -len(suf)])
                break
        else:
            cols.add(n)
    return cols


# ---------------------------------------------------------------------------
# group dispatch
# ---------------------------------------------------------------------------


def _flush(
    nodes: List[LazyFrame],
    frame: TensorFrame,
    records: List[Dict],
    start_idx: int,
    keep: Optional[Set[str]] = None,
) -> TensorFrame:
    """Execute ``nodes``' steps over ``frame``: maximal fusable runs
    dispatch as ONE chained pass; everything else (host-staged,
    ragged-input, lone stages) runs the plain eager verb — the same
    dispatch the eager path would make.  ``keep`` prunes the fetches of
    a fused group that ENDS the segment (terminal consumers)."""
    i = 0
    while i < len(nodes):
        steps = [nd._step for nd in nodes[i:]]
        n, why, _ = _fusable_run(steps, _device_infos(frame))
        if n >= 2:
            frame = _dispatch_fused(
                nodes[i : i + n],
                frame,
                records,
                start_idx + i,
                keep=keep if i + n == len(nodes) else None,
            )
            i += n
        else:
            frame = _dispatch_single(
                nodes[i],
                frame,
                records,
                start_idx + i,
                why if n == 0 else "single_stage",
            )
            i += 1
    return frame


def _measured(fn, rows: int) -> Tuple[Any, Dict[str, Any]]:
    """Run ``fn()`` and return ``(result, measurement)`` — wall time and
    the resource deltas every plan record carries (round 15: the
    substance behind ``tfs.explain(frame, analyze=True)``).

    Metered through a nested :class:`observability.RequestLedger`, NOT
    a global counters-delta window: the ledger is exact per thread
    (staging lanes inherit the context), so a concurrent request in the
    same process cannot contaminate a stage's h2d/trace attribution.
    The ledger is deliberately never ``finish()``-ed — internal stage
    metering must not fold into the per-tenant request aggregates or
    the slow-request log (an enclosing bridge request's ledger still
    sees every delta via parent chaining)."""
    led = observability.RequestLedger(method="plan_stage")
    token = observability.activate_request(led)
    t0 = time.perf_counter()
    try:
        out = fn()
    finally:
        observability.deactivate_request(token)
    wall = time.perf_counter() - t0
    c = led.snapshot()["counters"]
    m: Dict[str, Any] = {
        "wall_s": round(wall, 6),
        "h2d_bytes": c.get("h2d_bytes_staged", 0),
        "traces": c.get("program_traces", 0),
        "rows": rows,
        "rows_per_s": round(rows / wall, 1) if wall > 0 else None,
    }
    if c.get("pool_blocks"):
        m["pool_blocks"] = c["pool_blocks"]
    if c.get("cache_shard_hits"):
        m["shard_hits"] = c["cache_shard_hits"]
    if c.get("block_retries"):
        m["retries"] = c["block_retries"]
    return out, m


def _dispatch_single(
    node: LazyFrame,
    frame: TensorFrame,
    records: List[Dict],
    idx: int,
    reason: str,
) -> TensorFrame:
    st = node._step

    def run():
        if st.kind == "map_rows":
            return _DEFAULT.map_rows(
                st.program, frame, host_stage=st.host_stage
            )
        return _DEFAULT.map_blocks(
            st.program, frame, trim=st.trim, host_stage=st.host_stage
        )

    out, measured = _measured(run, frame.num_rows)
    node._runs += 1
    records.append(
        {
            "stage": idx,
            "verb": st.label,
            "fused": 1,
            "dispatch": "eager",
            "reason": reason,
            **measured,
        }
    )
    return out


def _dispatch_fused(
    group: List[LazyFrame],
    frame: TensorFrame,
    records: List[Dict],
    idx: int,
    keep: Optional[Set[str]] = None,
) -> TensorFrame:
    steps = [nd._step for nd in group]
    try:
        meta = _compose(steps, frame, keep=keep)
    except ValidationError:
        if keep is None:
            raise
        # the terminal reads no derived column: nothing to prune
        meta = _compose(steps, frame)
    warm = any(nd._runs > 0 for nd in group) or _chain_warm(steps)
    rec = _choose_dispatch(meta, frame, warm)
    decision = rec.pop("decision")
    reason = rec.pop("reason")
    if decision in ("pool", "affinity") and frame.num_rows > 0:
        cache = frame_cache.active_cache(frame)
        devices = (
            cache.devices if cache is not None else device_pool.pool_devices()
        )
        (out, run_rec), measured = _measured(
            lambda: _run_pooled_chain(meta, frame, cache, devices),
            frame.num_rows,
        )
        rec.update(run_rec)
        # the observed payoff of the pool decision: measured per-device
        # occupancy collapses to an effective-parallelism scalar the
        # analyze rendering reports next to the decision's reason
        occ = run_rec.get("device_pool", {}).get("occupancy")
        if occ:
            measured["effective_parallelism"] = round(sum(occ), 2)
    else:
        out, measured = _measured(
            lambda: _run_serial_chain(steps, frame), frame.num_rows
        )
    rec.update(measured)
    # measured-throughput feedback (TFS_PLAN_CALIBRATE reads it back
    # through _choose_dispatch on the next identical chain)
    _calib_note(meta, frame, decision, measured.get("rows_per_s"))
    observability.note_plan_fused_dispatch()
    if meta.pruned:
        observability.note_plan_columns_pruned(len(meta.pruned))
    records.append(
        {
            "stage": idx,
            "verb": "+".join(st.label for st in steps),
            "fused": len(group),
            "dispatch": decision,
            "reason": reason,
            "pruned": list(meta.pruned),
            **rec,
        }
    )
    for nd in group:
        nd._runs += 1
    return out


# ---------------------------------------------------------------------------
# routing + explain
# ---------------------------------------------------------------------------


def root_for(frame: TensorFrame) -> LazyFrame:
    """The ONE shared plan root for a TensorFrame object (get-or-create)
    — used by both ``frame.lazy()`` and the ``TFS_PLAN`` routing, so
    chains built from either entry count as consumers of the same
    subplan (the auto-cache trigger).  Locked: two concurrent bridge
    requests racing the create would otherwise each get a root and
    split the consumer counting."""
    root = getattr(frame, "_tfs_lazy_root", None)
    if root is None:
        with LazyFrame._TREE_LOCK:
            root = getattr(frame, "_tfs_lazy_root", None)
            if root is None:
                root = LazyFrame(source=frame)
                frame._tfs_lazy_root = root
    return root


def maybe_lazy(frame) -> Optional[LazyFrame]:
    """The LazyFrame a module-level map verb should append to, or None
    for the eager path: the frame is already lazy, or ``TFS_PLAN`` is on
    and the frame is a plain TensorFrame."""
    if isinstance(frame, LazyFrame):
        return frame
    if planning_enabled() and isinstance(frame, TensorFrame):
        return root_for(frame)
    return None


def ensure_frame(frame):
    """A concrete TensorFrame for surfaces that cannot stay lazy
    (pipelines, warmup, the bridge)."""
    if isinstance(frame, LazyFrame):
        return frame._materialize(count_use=False)
    return frame


# ---------------------------------------------------------------------------
# plan warmup (round 19 satellite: the fused-chain bucket grid)
# ---------------------------------------------------------------------------


def warm_plan(frame: "LazyFrame") -> List[str]:
    """Prime the executables the optimizer will ACTUALLY dispatch for
    this plan, without executing it.

    ``Executor.warmup`` primes one program's own entries, but a planned
    chain dispatches each stage through the engine's DONATING entries at
    BUCKETED sizes on every pool device — different jit-cache keys, so a
    per-stage warmup still left the first planned run compiling.  This
    walks the pending chain, composes the fused groups, and zeros-
    executes the exact ``_apply_stages`` path once per (bucketed size,
    device) with trace counting suppressed (programs are pure by
    contract), seeding the jit caches — and, with ``TFS_COMPILE_CACHE``
    configured, the persistent cache — the first real dispatch will hit.
    The roofline probe and the bucket-pad proofs are primed too, so the
    pool-vs-serial decision costs nothing at dispatch.  Returns the
    primed (rows x devices) grid labels."""
    import jax

    if not isinstance(frame, LazyFrame):
        raise ValidationError("warm_plan: takes a LazyFrame")
    chain: List[LazyFrame] = []
    cur = frame
    while cur._materialized is None:
        chain.append(cur)
        cur = cur._parent
    chain.reverse()
    src = cur._materialized
    if src is None or not chain or src.num_rows == 0:
        return []
    steps = [nd._step for nd in chain]
    n, _, _ = _fusable_run(steps, _device_infos(src))
    if n < 2:
        st = steps[0]
        if st.stage_bound or st.kind not in ("map_blocks", "map_rows"):
            return []
        fps = _DEFAULT.warmup(
            st.program,
            src,
            rows_level=st.kind == "map_rows",
            host_stage=st.host_stage,
        )
        return list(fps)
    meta = _compose(steps[:n], src)
    pads = _chain_pads(meta, src)
    sizes = src.block_sizes
    exec_sizes = sorted(
        {
            pads[bi] if pads[bi] is not None else s
            for bi, s in enumerate(sizes)
            if s > 0
        }
    )
    if not exec_sizes:
        return []
    cache = frame_cache.active_cache(src)
    if cache is not None:
        devs = [cache.devices[di] for di in sorted(set(cache.assignment))]
    else:
        devs = list(device_pool.pool_devices()) or [None]
    # prime the cost probe so the first dispatch's pool/serial decision
    # is a cache hit instead of a compile
    _fused_intensity(meta.program, src)
    donate_entries = cache is None
    # real sizes each bucket serves: the dispatch slices pads back off,
    # and that slice is its own (per-device) executable to prime
    reals: Dict[int, Set[int]] = {}
    for bi, s in enumerate(sizes):
        if s > 0 and pads[bi] is not None:
            reals.setdefault(pads[bi], set()).add(s)
    primed: List[str] = []
    for n_rows in exec_sizes:
        zeros = {}
        for name in meta.src_inputs:
            col = src.column(name)
            cell = tuple(np.shape(col.data)[1:])
            st_ = dtypes.coerce(col.info.scalar_type)
            zeros[name] = np.zeros((n_rows,) + cell, st_.np_dtype)
        for dev in devs:
            staged = {
                k: jax.device_put(v, dev) for k, v in zeros.items()
            }
            with observability.suppress_trace_count():
                outs = _apply_stages(
                    meta, staged, donate_entries=donate_entries
                )
                for real in sorted(reals.get(n_rows, ())):
                    sliced = {k: v[:real] for k, v in outs.items()}
                    jax.block_until_ready(list(sliced.values()))
            jax.block_until_ready(outs)
            primed.append(
                f"chain[{len(meta.steps)}]x{n_rows}@"
                f"{getattr(dev, 'id', 'default')}"
            )
    return primed


# ---------------------------------------------------------------------------
# planner-aware multi-epoch driver (round 19)
# ---------------------------------------------------------------------------


def _prime_blocks(frame, cache, missing: List[int]) -> None:
    """Best-effort background re-staging of evicted entry shards
    between epochs: spill-backed shards restore from disk, plain shards
    re-stage from the authoritative host columns.  Any failure simply
    leaves the block for the dispatch path's inline re-staging."""
    import jax

    names = None
    for b in cache.blocks:
        if b is not None:
            names = list(b)
            break
    for bi in missing:
        try:
            if cache.shard(bi) is not None:  # spill restore / raced in
                continue
            if names is None:
                return
            dev = cache.devices[cache.assignment[bi]]
            lo, hi = frame.offsets[bi], frame.offsets[bi + 1]
            shard = {}
            for name in names:
                col = frame.column(name)
                a = np.asarray(col.data)[lo:hi]
                st_ = dtypes.coerce(col.info.scalar_type)
                if a.dtype != st_.np_dtype:
                    a = a.astype(st_.np_dtype)
                observability.note_h2d_bytes(a.nbytes)
                shard[name] = jax.device_put(a, dev)
            if not cache.insert(bi, shard):
                return  # budget full: stop, dispatch re-stages inline
        except Exception:  # noqa: BLE001 — priming must never fail a run
            return


def _start_epoch_primer(root: "LazyFrame"):
    mat = root._materialized
    if mat is None:
        return None
    cache = frame_cache.active_cache(mat)
    if cache is None:
        return None
    missing = [bi for bi, b in enumerate(cache.blocks) if b is None]
    if not missing:
        return None
    t = threading.Thread(
        target=_prime_blocks,
        args=(mat, cache, missing),
        daemon=True,
        name="tfs-plan-epoch-primer",
    )
    t.start()
    return t


def iterate_epochs(
    frame, step, epochs: int, job_id: Optional[str] = None
) -> List[Any]:
    """Planner-aware multi-epoch driver (``tfs.iterate_epochs``): run
    ``step(lazy_frame, epoch)`` ``epochs`` times over one shared plan
    root.

    The planner knows the loop shape up front, so it does what the
    round-14 heuristics only discovered mid-loop: the entry frame's
    sharded cache inserts on the FIRST consumption (not the second), so
    epoch 1 onwards reads resident shards — 0 steady-state H2D — and
    between epochs a background primer re-stages any shards the
    ``TFS_HBM_BUDGET`` LRU evicted, through the same staging path, so
    epoch N+1's blocks are resident while epoch N's host work (loss
    handling, param updates) runs.  Steady-state epochs re-trace
    nothing: the chain's executables and fusion metadata are shared
    across epochs.

    ``step`` receives the shared :class:`LazyFrame` root and the epoch
    index; derive chains and reduce/aggregate off it exactly as in a
    hand-written loop (params may change between epochs via
    ``update_params`` — the plan re-executes, the executables stay
    warm).  Returns the per-epoch results.

    ``job_id`` (round 20) makes the loop durable: each epoch's result
    (npz-serializable pytrees — arrays, scalars, nested containers) is
    journaled at the epoch boundary, a resumed loop replays journaled
    epochs' results WITHOUT running ``step`` for them, and a completed
    loop returns its journaled result list exactly once.  ``step`` must
    derive any carried state (params it updates) from the journaled
    results, not from process-local mutation, for the resumed epochs to
    be bit-identical — the epoch-matrix test pins exactly this shape."""
    if epochs < 1:
        raise ValidationError("iterate_epochs: epochs must be >= 1")
    if isinstance(frame, LazyFrame):
        root = frame
    elif isinstance(frame, TensorFrame):
        root = root_for(frame)
    else:
        raise ValidationError(
            "iterate_epochs: takes a TensorFrame or LazyFrame"
        )
    writer = None
    start_epoch = 0
    results: List[Any] = []
    if job_id is not None:
        from .. import recovery

        writer = recovery.adopt(
            job_id,
            "iterate_epochs",
            recovery.job_fingerprint("iterate_epochs", epochs=epochs),
        )
        # completed AND interrupted loops replay journaled epochs from
        # their per-boundary states (kept past complete for this); a
        # torn-state raise here must release the in-process job slot
        with recovery.durable.closing_on_error(writer):
            start_epoch = min(writer.boundary, epochs)
            for e in range(start_epoch):
                results.append(
                    recovery.unpack_tree(
                        writer.load_state(e) or {}, writer.extras()[e]
                    )
                )
                # the epoch analog of a skipped stream window:
                # journaled, replayed, never re-executed
                observability.note_journal_window_skipped()
        if writer.completed:
            writer.close()
            return results
    if epochs >= 2 and root._materialized is not None:
        # declare the loop's >= 2 consumptions up front: the entry
        # auto-cache triggers on the FIRST consumption instead of
        # waiting to observe a second one
        root._mat_uses = max(root._mat_uses, 1)
    primer = None
    try:
        for e in range(start_epoch, epochs):
            cancellation.checkpoint()  # epoch boundary
            results.append(step(root, e))
            if writer is not None:
                from .. import recovery

                arrays, extra = recovery.pack_tree(results[-1])
                writer.append(arrays=arrays, extra=extra)
            # the primer runs CONCURRENTLY with the next epoch (the
            # overlap is the point: re-staging evicted shards rides
            # under epoch N+1's host work; the dispatch path tolerates
            # racing best-effort inserts — worst case a block re-stages
            # inline exactly as it would have without the primer).  At
            # most one primer is in flight.
            if e + 1 < epochs and (primer is None or not primer.is_alive()):
                primer = _start_epoch_primer(root)
    except BaseException:
        if writer is not None:
            writer.close()  # stays resumable from the journal
        raise
    finally:
        if primer is not None:
            primer.join()
    if writer is not None:
        from .. import recovery

        with recovery.durable.closing_on_error(writer):
            writer.complete(keep_states=True)
    return results


# ---------------------------------------------------------------------------
# per-window plans for the streaming verbs (round 19)
# ---------------------------------------------------------------------------


def run_window_chain(
    frame: TensorFrame, steps: Sequence[Tuple[str, Program, bool]]
) -> TensorFrame:
    """Execute a stacked map chain over ONE streaming window through
    plan construction: fusion, dead-column pruning, and the static
    ``analysis.rows_independent`` bucket pads all apply, and the fusion
    metadata / executables are shared across windows (the stage
    Programs are the cache keys).  The CSE registry is bypassed —
    window frames never repeat.  Bit-identical to dispatching the
    stages eagerly per window: the fused chain applies each stage's own
    compiled entry."""
    lz = LazyFrame(source=frame)
    cur = lz
    for kind, program, trim in steps:
        cur = cur._append(kind, program, trim=trim)
    out = cur._materialize(count_use=False, cse=False)
    observability.note_plan_stream_window()
    return out


def explain_plan(frame: LazyFrame) -> str:
    """Render the optimized logical plan WITHOUT executing it: stage
    list, fused groups (computed by the same grouping walk the executor
    uses), pruned columns, cache-insertion barriers, and — after a run —
    the recorded per-group pool/serial decisions."""
    chain: List[LazyFrame] = []
    cur = frame
    while cur._step is not None:
        chain.append(cur)
        cur = cur._parent
    chain.reverse()
    src = cur._materialized if cur._materialized is not None else cur._source
    lines = ["== logical plan (lazy) =="]
    lines.append(
        f"source: {src.num_rows} rows x {len(src.columns)} cols x "
        f"{src.num_blocks} block(s) [{', '.join(src.column_names)}]"
    )
    if not chain:
        lines.append("(no stages: materialises to the source frame)")
        return "\n".join(lines)

    # dry-run grouping: mirror _flush, but threading the statically
    # inferred visible columns instead of executing.  Barriers (>= 2
    # consumers) bound fusion exactly like the executor's flush points;
    # an unfusable host-staged stage makes the schema opaque downstream.
    gid_of: Dict[int, Tuple[Optional[int], Optional[str]]] = {}
    visible: Optional[Dict[str, ColumnInfo]] = _device_infos(src)
    consumed: Set[str] = set()
    barrier_idx = {k for k, nd in enumerate(chain) if nd._children >= 2}
    gid = 0
    i = 0
    while i < len(chain):
        stop = next((b for b in sorted(barrier_idx) if b >= i), None)
        seg_end = len(chain) if stop is None else stop + 1
        steps = [nd._step for nd in chain[i:seg_end]]
        if visible is None:
            n, why, after = 0, "schema opaque after host stage", None
        else:
            n, why, after = _fusable_run(steps, visible)
        if n >= 2:
            for k in range(i, i + n):
                gid_of[k] = (gid, None)
            gid += 1
            visible = after if n == len(steps) else None
            i += n
        else:
            gid_of[i] = (None, why if n == 0 else "single_stage")
            visible = None if n == 0 else after
            i += 1
    for k, nd in enumerate(chain):
        st = nd._step
        g, why = gid_of[k]
        cols = ", ".join(
            dict.fromkeys(
                st.program.column_for_input(n)
                for n in st.program.input_names
            )
        )
        consumed.update(
            st.program.column_for_input(n) for n in st.program.input_names
        )
        tag = f"fused group {g}" if g is not None else f"eager ({why})"
        mark = (
            "  [barrier: >=2 consumers -> auto-cache]"
            if k in barrier_idx
            else ""
        )
        lines.append(
            f" stage {k:<2} {st.label:<20} reads [{cols}]  {tag}{mark}"
        )
    dead = sorted(set(_device_infos(src)) - consumed)
    lines.append(
        "pruned columns (never staged by fused groups): "
        + (", ".join(dead) if dead else "none")
    )
    inserted = [
        f"stage {k} (inserted)"
        for k, nd in enumerate(chain)
        if nd._auto_cached
    ]
    pendings = [
        f"stage {k} ({chain[k]._children} consumers)"
        for k in sorted(barrier_idx)
        if not chain[k]._auto_cached
    ]
    lines.append(
        "cache insertions: "
        + (", ".join(inserted + pendings) if (inserted or pendings) else "none")
    )
    recs = frame._last_records
    if recs:
        lines.append("last run:")
        for r in recs:
            extra = ""
            if r.get("intensity_flops_per_byte") is not None:
                extra = f", intensity={r['intensity_flops_per_byte']}"
            lines.append(
                f"  stage {r['stage']}: {r['verb']} -> {r['dispatch']} "
                f"(reason={r['reason']}{extra})"
            )
    return "\n".join(lines)


def _render_analyze(frame: LazyFrame, executed_now: bool) -> str:
    """The measured half of ``explain(analyze=True)``: per-group wall
    time, bytes staged, pool occupancy, and the pool-vs-serial decision
    with its observed payoff — rendered from the per-stage measurements
    every plan execution records."""
    recs = frame._last_records
    lines = ["== analyze (measured) =="]
    if not executed_now:
        lines.append(
            "(plan was already materialized; measurements are from its "
            "last execution)"
        )
    if not recs:
        lines.append("(no recorded execution — the plan has no stages)")
    tot_wall = 0.0
    tot_h2d = 0
    for r in recs:
        wall = r.get("wall_s")
        tot_wall += wall or 0.0
        tot_h2d += r.get("h2d_bytes") or 0
        head = (
            f" group stage {r['stage']}: {r['verb']} "
            f"[{'fused x' + str(r['fused']) if r.get('fused', 1) >= 2 else 'eager'}]"
        )
        lines.append(head)
        lines.append(
            f"   dispatch={r.get('dispatch')} (reason={r.get('reason')})"
            + (
                f" intensity={r['intensity_flops_per_byte']}"
                if r.get("intensity_flops_per_byte") is not None
                else ""
            )
        )
        lines.append(
            f"   wall={wall}s  h2d_bytes={r.get('h2d_bytes')}  "
            f"traces={r.get('traces')}  rows/s={r.get('rows_per_s')}"
        )
        dp = r.get("device_pool")
        if dp:
            payoff = r.get("effective_parallelism")
            lines.append(
                f"   pool: blocks={dp.get('blocks_per_device')} "
                f"occupancy={dp.get('occupancy')}"
                + (
                    f" -> observed payoff: {payoff}x effective "
                    f"parallelism across {dp.get('devices')} device(s)"
                    if payoff is not None
                    else ""
                )
            )
        if r.get("retries"):
            lines.append(f"   retries={r['retries']}")
        if r.get("pruned"):
            lines.append(f"   pruned={r['pruned']}")
    lines.append(
        f" totals: wall={round(tot_wall, 6)}s  h2d_bytes={tot_h2d}"
    )
    led = getattr(frame, "_last_ledger", None)
    if led:
        c = led.get("counters", {})
        lines.append(
            f" request: cid={led.get('correlation_id')} "
            f"wall={led.get('wall_s')}s "
            f"h2d={c.get('h2d_bytes_staged', 0)} "
            f"traces={c.get('program_traces', 0)} "
            f"retries={c.get('block_retries', 0)} "
            f"blocks_per_device={led.get('blocks_per_device')}"
        )
    return "\n".join(lines)


def explain_analyze(frame: LazyFrame) -> str:
    """``EXPLAIN ANALYZE`` for a planned frame: execute the plan under a
    :func:`observability.request_ledger` (nesting safely inside any
    active bridge request's ledger) and render the logical plan PLUS the
    measured per-stage/per-group report — wall time, bytes staged, pool
    occupancy, and each pool-vs-serial decision with its observed
    payoff.  A plan that already materialized renders its last
    execution's measurements (plans memoize; re-deriving the chain from
    the source re-executes)."""
    executed_now = frame._materialized is None
    with observability.request_ledger(method="explain_analyze") as led:
        frame._materialize(count_use=False)
    if executed_now:
        frame._last_ledger = led.snapshot()
    return (
        explain_plan(frame)
        + "\n"
        + _render_analyze(frame, executed_now)
    )
