"""Execution engine package: verb validation + single-device executor."""

from .engine import (
    Executor,
    aggregate,
    group_by,
    map_blocks,
    map_rows,
    reduce_blocks,
    reduce_rows,
    warmup,
)
from .pipeline import Pipeline, pipeline
from .planner import LazyFrame
from .validation import ValidationError

__all__ = [
    "Executor",
    "aggregate",
    "group_by",
    "LazyFrame",
    "map_blocks",
    "map_rows",
    "Pipeline",
    "pipeline",
    "reduce_blocks",
    "reduce_rows",
    "ValidationError",
    "warmup",
]
