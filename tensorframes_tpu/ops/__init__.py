"""Execution engine package: verb validation + single-device executor."""

from .engine import (
    Executor,
    aggregate,
    group_by,
    map_blocks,
    map_rows,
    reduce_blocks,
    reduce_rows,
    warmup,
)
from .pipeline import Pipeline, pipeline
from .planner import LazyFrame, LazyGroupedFrame, iterate_epochs, warm_plan
from .validation import ValidationError

__all__ = [
    "Executor",
    "aggregate",
    "group_by",
    "iterate_epochs",
    "LazyFrame",
    "LazyGroupedFrame",
    "warm_plan",
    "map_blocks",
    "map_rows",
    "Pipeline",
    "pipeline",
    "reduce_blocks",
    "reduce_rows",
    "ValidationError",
    "warmup",
]
