"""Fused verb pipelines: a chain of verbs compiled into ONE XLA dispatch.

The per-verb engine (``engine.py``) dispatches each verb separately: a chained
``map_blocks_trimmed -> reduce_blocks`` step — the body of every iterative
driver (logreg, kmeans) — pays one dispatch per verb plus a host readback of
the reduced scalars per step.  That is exactly the per-call overhead the
reference measures in its perf suite
(``/root/reference/src/test/scala/org/tensorframes/perf/PerformanceSuite.scala:14-26``)
and works around by fusing compute + pre-aggregation into a single TF graph
(``/root/reference/src/main/python/tensorframes_snippets/kmeans_demo.py:101-168``).
The TPU-native answer is stronger than graph fusion: *the whole verb chain is
one jit trace*, so XLA fuses across verb boundaries, intermediates never leave
HBM, and an iterative driver can run its entire loop on device
(``lax.scan``) with parameters carried between steps — one dispatch and one
readback for K steps, instead of 2K dispatches and K scalar syncs.

Usage::

    pipe = (tfs.pipeline(frame)
            .map_blocks(grad_prog, trim=True)     # block -> 1-row partials
            .reduce_blocks(sum_prog)              # cross-block sum
            .then(sgd_update))                    # traced post-processing
    row  = pipe.run()                             # ONE dispatch; device dict
    out  = pipe.collect()                         # run + host materialise

    # iterative driver: K steps in ONE dispatch, params stay on device
    finals, hist = pipe.iterate(50, carry={"w": "w", "b": "b"},
                                collect=("loss",))

Semantics match the eager verbs exactly (parity-tested in
``tests/test_pipeline.py``); the differences are deliberate and validated at
build time:

* host-only (binary/string) and ragged columns cannot flow *through* a fused
  trace — a program referencing one is rejected with a pointer at the eager
  verbs (host_stage decode belongs outside a fused chain by construction);
  untouched host columns of the source frame are re-attached to map-terminal
  outputs on the host side, where row identity is preserved.
* ``aggregate`` is not fusable (its group structure is data-dependent); use
  the eager verb.

Mesh composition: ``tfs.pipeline(frame, engine=MeshExecutor(mesh))`` runs
the SAME fused chain with the source columns sharded over the executor's
data axis and the whole frame treated as ONE logical block (the mesh
executor's ``global`` semantics) — GSPMD partitions the fused executable
and lowers the reduce stages' cross-shard combines onto ICI collectives.
Size row counts as a multiple of the data axis: other counts degrade to
the largest-divisor sub-mesh (``_shard_for``'s logged fallback — padding
is not semantics-safe for arbitrary cross-row programs).  Per-block
("partition") semantics stay with the eager ``MeshExecutor`` verbs;
``mode="per_block"`` executors are rejected here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import cancellation, dtypes, observability
from ..frame import TensorFrame, is_device_array
from ..program import Program
from ..schema import ColumnInfo, Schema
from ..shape import Shape, UNKNOWN
from ..analysis import rowdep as analysis
from . import (
    bucketing,
    device_pool,
    fault_tolerance,
    frame_cache,
    prefetch,
    validation,
)
from .engine import _DEFAULT
from .validation import ValidationError


@dataclasses.dataclass(frozen=True)
class _Stage:
    kind: str  # map_blocks | map_rows | reduce_blocks | reduce_rows | then
    program: Optional[Program] = None
    trim: bool = False
    mode: str = "tree"
    fn: Optional[Callable] = None
    # build-time bookkeeping
    reduced_bases: Tuple[str, ...] = ()


class _SchemaView:
    """Duck-typed stand-in for a TensorFrame in the validation helpers (they
    only touch ``.schema``)."""

    def __init__(self, infos: Mapping[str, ColumnInfo]):
        self.schema = Schema(list(infos.values()))


def _block_info(name: str, st, cell_shape) -> ColumnInfo:
    return ColumnInfo(name, st, Shape(cell_shape).prepend(UNKNOWN))


def analyzed_outputs(
    program: Program,
    infos: Mapping[str, ColumnInfo],
    cell: bool,
    verb: str = "pipeline",
) -> Dict[str, ColumnInfo]:
    """Shape-infer a map stage's outputs from its input ColumnInfos —
    the schema-tracking step shared by the fused Pipeline builders and
    the lazy planner's composed-program fusion (``ops/planner.py``).
    ``cell``: the program is row-level (map_rows), so specs and output
    shapes are per-cell."""
    specs = {}
    for n, ci in infos.items():
        st = dtypes.coerce(ci.scalar_type)
        shape = (
            tuple(ci.cell_shape)
            if cell
            else (UNKNOWN,) + tuple(ci.cell_shape)
        )
        specs[n] = (st, Shape(shape))
    outs: Dict[str, ColumnInfo] = {}
    for s in program.analyze(specs):
        if s.is_output:
            block_shape = s.shape.prepend(UNKNOWN) if cell else s.shape
            if not cell and block_shape.rank == 0:
                raise ValidationError(
                    f"{verb}.map_blocks: output {s.name!r} is a scalar; "
                    f"block outputs need a lead row axis."
                )
            outs[s.name] = ColumnInfo(s.name, s.scalar_type, block_shape)
    return outs


def _reduce_src_cols(program, bases, suffix: str) -> Dict[str, str]:
    """base -> source chain column for a terminal reduce stage,
    honouring feed-dict renames (round 11): ``inputs={"x_input":
    "data"}`` folds the chain's ``data`` column into output ``x``."""
    out = {}
    for b in bases:
        n = f"{b}{suffix}"
        col = program.column_for_input(n)
        out[b] = b if col == n else col
    return out


class Pipeline:
    """A lazy verb chain over one frame; built by :func:`pipeline`.

    Builder methods return a NEW Pipeline (the receiver stays valid), so
    chains can fork.  Compilation happens at the first ``run``/``collect``/
    ``iterate`` and is cached on the terminal Pipeline object.

    Forks share STAGE STATE, not just structure: stages hold the caller's
    ``Program`` objects by reference, and ``iterate``'s resume contract
    updates those programs' params in place — deliberately, so the
    caller's own handle (and any sibling fork) continues from the trained
    state, exactly like calling ``program.update_params`` yourself.  If a
    fork must iterate from pristine params, give it its own ``Program``
    (``Program(graphdef, **initial_params)``) rather than sharing one
    across forks (ADVICE r4).
    """

    def __init__(
        self,
        frame: TensorFrame,
        stages: Tuple[_Stage, ...] = (),
        visible: Optional[Dict[str, ColumnInfo]] = None,
        from_source: Optional[Dict[str, bool]] = None,
        row_stage: bool = False,
        engine=None,
    ):
        self._frame = frame
        self._stages = stages
        # a MeshExecutor engine switches the chain to mesh-global
        # semantics: one logical block, rows sharded over the data axis
        self._engine = engine
        if visible is None:
            visible = {}
            from_source = {}
            for c in frame.columns:
                if c.info.scalar_type.device_ok and not c.is_ragged:
                    visible[c.info.name] = c.info
                    from_source[c.info.name] = True
        self._visible = visible
        self._from_source = from_source or {}
        self._row_stage = row_stage  # terminal produces a row, not a frame
        # keyed by donate flag: a host-sourced frame stages fresh entry
        # buffers per call and may donate them; a cached frame must not
        self._compiled: Dict[bool, Any] = {}
        self._iter_compiled: Dict[Any, Any] = {}
        # device-pool per-block executable (map-terminal chains), keyed
        # by donate flag like _compiled; _pool_proofs memoizes the
        # chain-level row-independence proofs bucket padding is gated on
        self._pool_compiled: Dict[bool, Any] = {}
        self._pool_proofs: Dict[Any, bool] = {}

    # ------------------------------------------------------------ builders --

    def _require_frame_stage(self, verb: str) -> None:
        if self._row_stage:
            raise ValidationError(
                f"pipeline.{verb}: the chain already ended in a row-producing "
                f"stage (reduce/then); only then/run/collect/iterate may "
                f"follow."
            )

    def _check_inputs(
        self, program: Program, verb: str
    ) -> Dict[str, ColumnInfo]:
        infos: Dict[str, ColumnInfo] = {}
        source_schema = self._frame.schema
        for n in program.input_names:
            col = program.column_for_input(n)
            if col in self._visible:
                infos[n] = self._visible[col]
                continue
            if col in source_schema:
                ci = source_schema[col]
                fcol = self._frame.column(col)
                if not ci.scalar_type.device_ok or fcol.is_ragged:
                    why = (
                        "is host-only (binary/string)"
                        if not ci.scalar_type.device_ok
                        else "is ragged/un-analyzed"
                    )
                    raise ValidationError(
                        f"pipeline.{verb}: column {col!r} {why} and cannot "
                        f"flow through a fused device trace. Use the eager "
                        f"verb (tfs.{verb}) with host_stage/analyze for "
                        f"this column."
                    )
                raise ValidationError(
                    f"pipeline.{verb}: column {col!r} was dropped by an "
                    f"earlier trim stage (trim=True replaces the block with "
                    f"the program outputs only). Available here: "
                    f"{sorted(self._visible)}."
                )
            raise ValidationError(
                f"pipeline.{verb}: program input {n!r} requests column "
                f"{col!r}, which is not available at this point in the "
                f"chain. Available: {sorted(self._visible)}."
            )
        return infos

    def _analyzed_outputs(
        self, program: Program, infos: Mapping[str, ColumnInfo], cell: bool
    ) -> Dict[str, ColumnInfo]:
        """Shape-infer a map stage's outputs to keep schema tracking exact."""
        return analyzed_outputs(program, infos, cell, verb="pipeline")

    def map_blocks(self, fn, trim: bool = False, **kw) -> "Pipeline":
        """Append a block-level map (``tfs.map_blocks``; trim=True for
        ``map_blocks_trimmed``)."""
        self._require_frame_stage("map_blocks")
        program = Program.wrap(fn, **kw)
        infos = self._check_inputs(program, "map_blocks")
        outs = self._analyzed_outputs(program, infos, cell=False)
        visible = dict(outs) if trim else {**self._visible, **outs}
        from_source = (
            {k: False for k in outs}
            if trim
            else {**self._from_source, **{k: False for k in outs}}
        )
        return Pipeline(
            self._frame,
            self._stages + (_Stage("map_blocks", program, trim=trim),),
            visible,
            from_source,
            engine=self._engine,
        )

    def map_blocks_trimmed(self, fn, **kw) -> "Pipeline":
        return self.map_blocks(fn, trim=True, **kw)

    def map_rows(self, fn, **kw) -> "Pipeline":
        """Append a row-level map (``tfs.map_rows``, vmapped in the trace)."""
        self._require_frame_stage("map_rows")
        program = Program.wrap(fn, **kw)
        infos = self._check_inputs(program, "map_rows")
        outs = self._analyzed_outputs(program, infos, cell=True)
        visible = {**self._visible, **outs}
        from_source = {**self._from_source, **{k: False for k in outs}}
        return Pipeline(
            self._frame,
            self._stages + (_Stage("map_rows", program),),
            visible,
            from_source,
            engine=self._engine,
        )

    def reduce_blocks(self, fn, **kw) -> "Pipeline":
        """Append the terminal block reduction (``tfs.reduce_blocks``)."""
        self._require_frame_stage("reduce_blocks")
        if self._frame.num_rows == 0:
            raise ValidationError(
                "pipeline.reduce_blocks: cannot reduce an empty frame (no "
                "identity element is available for an arbitrary block "
                "program)"
            )
        program = Program.wrap(fn, **kw)
        view = _SchemaView(self._visible)
        reduced = validation.check_reduce_blocks(
            program, view, verb="pipeline.reduce_blocks"
        )
        bases = tuple(sorted(reduced))
        probe = max(self._frame.block_sizes) or 1
        summaries = program.analyze(
            {
                f"{b}_input": (
                    dtypes.coerce(reduced[b].scalar_type),
                    (probe,) + tuple(reduced[b].cell_shape),
                )
                for b in bases
            }
        )
        validation.check_reduce_blocks_outputs(
            reduced, summaries, verb="pipeline.reduce_blocks"
        )
        return Pipeline(
            self._frame,
            self._stages
            + (_Stage("reduce_blocks", program, reduced_bases=bases),),
            self._visible,
            self._from_source,
            row_stage=True,
            engine=self._engine,
        )

    def reduce_rows(self, fn, mode: str = "tree", **kw) -> "Pipeline":
        """Append the terminal pairwise reduction (``tfs.reduce_rows``)."""
        self._require_frame_stage("reduce_rows")
        if self._frame.num_rows == 0:
            raise ValidationError(
                "pipeline.reduce_rows: cannot reduce an empty frame (no "
                "identity element is available for an arbitrary pairwise "
                "program)"
            )
        if mode not in ("tree", "sequential"):
            raise ValidationError(
                f"pipeline.reduce_rows: unknown mode {mode!r}; use 'tree' or "
                f"'sequential'"
            )
        program = Program.wrap(fn, **kw)
        view = _SchemaView(self._visible)
        reduced = validation.check_reduce_rows(program, view)
        bases = tuple(sorted(reduced))
        summaries = program.analyze(
            {
                f"{b}_{i}": (
                    dtypes.coerce(reduced[b].scalar_type),
                    tuple(reduced[b].cell_shape),
                )
                for b in bases
                for i in (1, 2)
            }
        )
        validation.check_reduce_rows_outputs(reduced, summaries)
        return Pipeline(
            self._frame,
            self._stages
            + (_Stage("reduce_rows", program, mode=mode, reduced_bases=bases),),
            self._visible,
            self._from_source,
            row_stage=True,
            engine=self._engine,
        )

    def then(self, fn: Callable) -> "Pipeline":
        """Append traced post-processing of the reduced row.

        ``fn(row, params)`` receives the reduced outputs (name -> array) and
        the union of all stage-program params (name -> value) and returns a
        dict of named outputs — the place for parameter updates and derived
        scalars, fused into the same dispatch."""
        if not self._row_stage:
            raise ValidationError(
                "pipeline.then: requires a reduce stage first (then() "
                "post-processes the reduced row)."
            )
        seen: Dict[str, int] = {}
        for i, st in enumerate(self._stages):
            if st.program is not None:
                for pname in st.program.param_names:
                    if pname in seen and seen[pname] != i:
                        raise ValidationError(
                            f"pipeline.then: param name {pname!r} exists on "
                            f"multiple stages; rename one to disambiguate."
                        )
                    seen[pname] = i
        return Pipeline(
            self._frame,
            self._stages + (_Stage("then", fn=fn),),
            self._visible,
            self._from_source,
            row_stage=True,
            engine=self._engine,
        )

    # --------------------------------------------------------------- trace --

    def _needed_source_cols(self) -> List[str]:
        """Source columns the trace must receive: every referenced source
        column, plus — for map-terminal chains — every still-visible source
        column (they pass through into the output frame)."""
        needed = set()
        for st in self._stages:
            if st.program is None:
                continue
            if st.kind in ("map_blocks", "map_rows"):
                refs = [
                    st.program.column_for_input(n)
                    for n in st.program.input_names
                ]
            else:
                # reduce stages read their feed-RESOLVED source columns
                # (round 11): the bases alone would prune a renamed
                # source out of the staged trace inputs
                suffix = "_input" if st.kind == "reduce_blocks" else "_1"
                refs = list(
                    _reduce_src_cols(
                        st.program, st.reduced_bases, suffix
                    ).values()
                )
            needed.update(refs)
        if not self._row_stage:
            needed.update(
                k for k, src in self._from_source.items() if src
            )
        # keep only true source columns (later stages may reference derived)
        src_names = {
            c.info.name
            for c in self._frame.columns
            if c.info.scalar_type.device_ok and not c.is_ragged
        }
        return sorted(needed & src_names)

    @property
    def _mesh_mode(self) -> bool:
        """True when the chain runs mesh-global: one logical block, rows
        sharded over the engine's data axis (duck-typed MeshExecutor)."""
        return self._engine is not None and hasattr(self._engine, "mesh")

    def _body(self, cols: Dict[str, Any], params_list: List[Dict]) -> Any:
        """The traced chain: cols are full source columns; returns either the
        final row dict or the list of per-block column dicts."""
        frame = self._frame
        src_schema = frame.schema
        if self._mesh_mode:
            # mesh-global semantics: the whole frame is ONE logical block
            # (GSPMD partitions the trace over the sharded rows)
            ranges = [(0, frame.num_rows)]
        else:
            ranges = [
                (frame.offsets[i], frame.offsets[i + 1])
                for i in range(frame.num_blocks)
            ]
        blocks: List[Dict[str, Any]] = []
        for lo, hi in ranges:
            # empty blocks flow through map stages (eager parity: map verbs
            # emit one output block per input block, empty included); the
            # reduce stages skip them below, like the engine's guards
            blk = {}
            for name, arr in cols.items():
                st = dtypes.coerce(src_schema[name].scalar_type)
                a = arr[lo:hi]
                if a.dtype != st.np_dtype:
                    a = a.astype(st.np_dtype)
                blk[name] = a
            blocks.append(blk)

        row: Optional[Dict[str, Any]] = None
        for st, params in zip(self._stages, params_list):
            if st.kind in ("map_blocks", "map_rows"):
                blocks = [
                    self._map_stage_block(st, blk, params) for blk in blocks
                ]
            elif st.kind == "reduce_blocks":
                program, bases = st.program, list(st.reduced_bases)
                srcs = _reduce_src_cols(program, bases, "_input")
                partials = [
                    program.call(
                        {f"{b}_input": blk[srcs[b]] for b in bases}, params
                    )
                    for blk in blocks
                    if next(iter(blk.values())).shape[0] > 0
                ]
                if not partials:
                    raise ValidationError(
                        "pipeline.reduce_blocks: every block is empty at "
                        "the reduce stage; nothing to reduce."
                    )
                if len(partials) == 1:
                    row = partials[0]
                else:
                    stacked = {
                        f"{b}_input": jnp.stack([p[b] for p in partials])
                        for b in bases
                    }
                    row = program.call(stacked, params)
            elif st.kind == "reduce_rows":
                program, bases = st.program, list(st.reduced_bases)
                srcs = _reduce_src_cols(program, bases, "_1")
                pairfn = _DEFAULT._pair_call(program, bases)
                fold = (
                    _DEFAULT._tree_fold
                    if st.mode == "tree"
                    else _DEFAULT._seq_fold
                )
                partials = [
                    fold(pairfn, {b: blk[srcs[b]] for b in bases}, params)
                    for blk in blocks
                    if next(iter(blk.values())).shape[0] > 0
                ]
                if not partials:
                    raise ValidationError(
                        "pipeline.reduce_rows: every block is empty at "
                        "the reduce stage; nothing to reduce."
                    )
                if len(partials) == 1:
                    row = partials[0]
                else:
                    stacked = {
                        b: jnp.stack([p[b] for p in partials]) for b in bases
                    }
                    row = fold(pairfn, stacked, params)
            elif st.kind == "then":
                merged: Dict[str, Any] = {}
                for stg, p in zip(self._stages, params_list):
                    if stg.program is not None:
                        merged.update(p)
                out = st.fn(row, merged)
                if not isinstance(out, Mapping):
                    raise ValidationError(
                        "pipeline.then: fn must return a dict of named "
                        f"outputs, got {type(out).__name__}"
                    )
                row = {k: jnp.asarray(v) for k, v in out.items()}
            else:  # pragma: no cover
                raise AssertionError(st.kind)
        return row if self._row_stage else blocks

    def _map_stage_block(
        self, st: _Stage, blk: Dict[str, Any], params: Mapping[str, Any]
    ) -> Dict[str, Any]:
        """One map stage applied to ONE block dict (traced) — shared by the
        fused whole-frame body and the device-pool per-block body, so the
        two execution paths cannot drift semantically."""
        if st.kind == "map_blocks":
            n_rows = len(next(iter(blk.values())))
            inputs = {
                n: blk[st.program.column_for_input(n)]
                for n in st.program.input_names
            }
            outs = st.program.call(inputs, params)
            if not st.trim:
                for name, v in outs.items():
                    if v.ndim == 0 or v.shape[0] != n_rows:
                        raise ValidationError(
                            f"pipeline.map_blocks: output {name!r} "
                            f"has shape {v.shape} but the block has "
                            f"{n_rows} rows; use trim=True to change "
                            f"the row count."
                        )
                return {
                    **{k: v for k, v in blk.items() if k not in outs},
                    **outs,
                }
            counts = {
                v.shape[0] if v.ndim else None for v in outs.values()
            }
            if len(counts) != 1 or None in counts:
                raise ValidationError(
                    f"pipeline.map_blocks_trimmed: outputs "
                    f"disagree on row count: "
                    f"{ {k: v.shape for k, v in outs.items()} }"
                )
            return dict(outs)
        if st.kind == "map_rows":
            program = st.program
            inputs = {
                n: blk[program.column_for_input(n)]
                for n in program.input_names
            }
            outs = jax.vmap(
                lambda ins, p=params, pr=program: pr.call(ins, p),
                in_axes=(0,),
            )(inputs)
            return {
                **{k: v for k, v in blk.items() if k not in outs},
                **outs,
            }
        raise AssertionError(st.kind)  # pragma: no cover

    def _block_chain(
        self, cols_blk: Dict[str, Any], params_list: List[Dict]
    ) -> Dict[str, Any]:
        """The map-stage chain over ONE block (traced): the device-pool
        per-block body.  Mirrors ``_body``'s per-block handling exactly —
        same entry casts, same stage application via
        :meth:`_map_stage_block`."""
        src_schema = self._frame.schema
        blk = {}
        for name, a in cols_blk.items():
            st = dtypes.coerce(src_schema[name].scalar_type)
            blk[name] = a if a.dtype == st.np_dtype else a.astype(st.np_dtype)
        for st_, params in zip(self._stages, params_list):
            blk = self._map_stage_block(st_, blk, params)
        return blk

    def _params_list(self) -> List[Dict[str, Any]]:
        return [
            dict(st.program._params) if st.program is not None else {}
            for st in self._stages
        ]

    def with_frame(self, frame: TensorFrame) -> "Pipeline":
        """Re-bind this chain to a new source frame with the same
        column layout — the streaming window loop's entry point
        (``streaming.run_pipeline`` runs ``pipe.with_frame(window).
        run()`` per window).

        Stages are shared BY REFERENCE: their ``Program`` objects — and
        therefore every ``cached_jit``/AOT executable those programs
        hold — stay hot across windows, which is what makes a
        per-window pipeline cheap (full windows share one row count, so
        one executable serves the stream).  The per-Pipeline compiled
        plans are deliberately NOT carried over: they may close over the
        bound frame, and a stale closure would silently read the old
        window's data."""
        if frame.column_names != self._frame.column_names:
            raise ValidationError(
                f"pipeline.with_frame: the new frame's columns "
                f"{frame.column_names} do not match the chain's source "
                f"columns {self._frame.column_names}"
            )
        return Pipeline(
            frame,
            self._stages,
            dict(self._visible),
            dict(self._from_source),
            self._row_stage,
            self._engine,
        )

    # ----------------------------------------------------------- execution --

    def run(self):
        """Compile (once) and dispatch the fused chain — ONE jit call.

        On the fused (default) path, returns device-resident results — a
        dict of arrays for row-terminal chains, a TensorFrame with device
        columns for map-terminal chains — with no host sync here;
        materialise with ``collect()`` / ``np.asarray`` when the values
        are needed.

        Device pool (``ops/device_pool.py``): a MAP-terminal chain over a
        host-fresh multi-block frame dispatches the same fused per-block
        body across all local devices instead of one whole-frame trace —
        blocks are independent, so the chain parallelizes exactly like
        the eager map verbs.  On THAT path the columns come back
        host-resident, assembled in block order, and the call
        synchronizes on the last block (overlapped per-block readback) —
        the pool trades the async device-resident contract for
        cross-device parallelism.  Row-terminal chains always keep the
        single fused dispatch: their cross-block combine shape IS the
        executable."""
        if not self._stages:
            raise ValidationError("pipeline.run: empty pipeline (no stages)")
        plan = self._pool_plan()
        if plan is not None:
            return self._run_pooled(*plan)
        with observability.verb_span(
            "pipeline", self._frame.num_rows, self._frame.num_blocks
        ) as span:
            cols, donate = self._entry_cols()
            if donate not in self._compiled:
                self._compiled[donate] = jax.jit(
                    lambda cols, params_list: self._body(cols, params_list),
                    **({"donate_argnums": (0,)} if donate else {}),
                )
            span.mark("validate")
            span.annotate("donate_entry", donate)
            out = self._compiled[donate](cols, self._params_list())
            del cols  # staged entry buffers: donated or dead either way
            span.mark("dispatch")
            if self._row_stage:
                return out
            frame = TensorFrame.from_blocks(out)
            # host-only / ragged source columns pass through unchanged when
            # the chain preserves row identity (no trim stage)
            if not any(s.trim for s in self._stages):
                extra = [
                    c
                    for c in self._frame.columns
                    if c.info.name not in frame.column_names
                    and c.info.name not in self._visible
                ]
                if extra:
                    frame = TensorFrame(
                        list(frame.columns) + extra, frame.offsets
                    )
            return frame

    def _pool_plan(self):
        """``(devices, entry layout, cache)`` for a pooled run, or None
        to take the fused whole-frame dispatch.  Pooling needs: a
        map-terminal chain (map stages only), no mesh engine, >= 2
        blocks, and a fully host-resident entry set.  Two ways in:

        * a host-fresh frame with >= 2 pool devices — the round-8 plan
          (per-device staging lanes, donated entry buffers);
        * a SHARDED-cached frame (``ops/frame_cache.py``; its host
          columns stay authoritative, so the entry set still reads as
          host-resident) — the run follows the cache's own device set
          and block-affinity assignment, pool knob or not, with no
          lanes, no donation and no H2D for resident shards.

        A single-device (round-2) cached frame still bypasses pooling:
        its columns live on ONE device and splitting them would shuffle
        HBM.  The knob and layout are resolved ONCE here and threaded
        through the whole pooled run, so a mid-call env flip cannot
        yield an inconsistent plan."""
        if (
            self._row_stage
            or self._mesh_mode
            or self._frame.num_blocks < 2
            or any(
                st.kind not in ("map_blocks", "map_rows")
                for st in self._stages
            )
        ):
            return None
        cache = frame_cache.active_cache(self._frame)
        devices = (
            cache.devices if cache is not None else device_pool.pool_devices()
        )
        if len(devices) < 2:
            return None
        layout, all_host = self._entry_layout()
        if not layout or not all_host:
            return None
        return devices, layout, cache

    def _pool_pads(self, sizes: List[int], layout) -> List[Optional[int]]:
        """Bucket targets for the pooled per-block chain (engine
        ``_bucket_plan`` analog), or all-None for exact shapes.

        Without padding an uneven frame compiles one chain executable
        per (block size, device); with it every block lands on one
        bucket signature per device.  Gating mirrors the engine: block
        bucketing enabled, no trim stage (padded rows must slice back,
        which needs row identity), and the WHOLE per-block chain proven
        row-independent by the jaxpr proof at the exact (real, padded)
        sizes — posed once on the composite ``_block_chain`` over the
        entry columns, so a cross-row ``map_blocks`` stage anywhere in
        the chain keeps exact shapes."""
        nb = len(sizes)
        none: List[Optional[int]] = [None] * nb
        if not bucketing.enabled() or any(st.trim for st in self._stages):
            return none
        targets = [
            bucketing.bucket_for(n) if n > 0 else None for n in sizes
        ]
        targets = [
            t if t is not None and t != sizes[i] else None
            for i, t in enumerate(targets)
        ]
        if all(t is None for t in targets):
            return none
        proof_sizes = tuple(
            sorted(
                {sizes[i] for i, t in enumerate(targets) if t is not None}
                | {t for t in targets if t is not None}
            )
        )
        sig = tuple(
            sorted(
                (n, tuple(np.shape(d)[1:]), str(np.dtype(dt)))
                for n, (d, dt) in layout.items()
            )
        )
        key = (proof_sizes, sig)
        if key not in self._pool_proofs:
            params_list = self._params_list()
            probe = Program(
                lambda **cols: self._block_chain(cols, params_list),
                sorted(layout),
            )
            specs = analysis.input_specs_for(probe, layout)
            try:
                ok = specs is not None and analysis.rows_independent(
                    probe, specs, proof_sizes
                )
            except analysis.AnalysisXCheckError:
                raise
            except Exception:
                ok = False
            self._pool_proofs[key] = ok
        return targets if self._pool_proofs[key] else none

    def _run_pooled(self, devices, layout, cache=None):
        """Map-terminal chain over the device pool: the fused per-block
        body (:meth:`_block_chain`) dispatches once per block on the
        block's assigned device, with per-device staging lanes and the
        bounded overlapped-readback window — the pipeline face of the
        engine's ``_map_dispatch_pool``.  Entry buffers are fresh host
        slices staged per block, so they donate exactly like the fused
        path's entry columns.

        ``cache`` (round 10, ``ops/frame_cache.py``): a sharded-cached
        entry frame runs AFFINITY dispatch instead — each block executes
        on the device already holding its shard, with no staging lanes,
        no donation (shards are shared state) and zero H2D for resident
        shards; evicted blocks and retry/quarantine recovery re-stage
        from the authoritative host columns.

        Donation-adoption: when sharding is on (entry cache present, or
        ``TFS_CACHE_SHARDED`` resolves devices), each block's OUTPUT
        buffers — already living on the block's execution device — are
        adopted as the cached shards of the result frame, so the next
        epoch of an iterative chain (``run`` feeding ``run``) starts
        sharded-cached and stages nothing.  The overlapped D2H readback
        still assembles the authoritative host columns; adopted shards
        are bytes-accounted against ``TFS_HBM_BUDGET``."""
        frame = self._frame
        with observability.verb_span(
            "pipeline", frame.num_rows, frame.num_blocks
        ) as span:
            donate = prefetch.donate_inputs() and cache is None
            if donate not in self._pool_compiled:
                self._pool_compiled[donate] = jax.jit(
                    lambda blk, params_list: self._block_chain(
                        blk, params_list
                    ),
                    **({"donate_argnums": (0,)} if donate else {}),
                )
            run = self._pool_compiled[donate]
            span.mark("validate")
            span.annotate("donate_entry", donate)
            sizes = frame.block_sizes
            nb = frame.num_blocks
            assignment = (
                list(cache.assignment)
                if cache is not None
                else device_pool.assign(sizes, len(devices))
            )
            pool = device_pool.PoolRun(
                devices, assignment, prefetch.prefetch_depth() or 1,
                affinity=cache is not None,
            )
            # block-level fault tolerance (ops/fault_tolerance.py): the
            # pooled per-block chain retries exactly like the eager map
            # verbs — re-staged entry buffers, quarantine redirects, by-
            # index reassembly.  None (the default) keeps this loop
            # byte-identical to the retry-free round-8 path.
            session = fault_tolerance.frame_session(
                nb, verb="pipeline", pool=pool
            )
            offsets = frame.offsets
            host_cols = {
                name: np.asarray(data) if not is_device_array(data) else data
                for name, (data, _) in layout.items()
            }

            pads = self._pool_pads(sizes, layout)

            def stage_block(bi, dev):
                lo, hi = offsets[bi], offsets[bi + 1]
                staged = {}
                for name, (data, dt) in layout.items():
                    a = host_cols[name][lo:hi]
                    if a.dtype != dt:
                        a = a.astype(dt)
                    if pads[bi] is not None:
                        a = bucketing.pad_rows(a, pads[bi])
                    observability.note_h2d_bytes(a.nbytes)
                    staged[name] = jax.device_put(a, dev)
                return staged

            def stage_cached(bi, dev_i):
                """Entry dict for one block of the sharded-cached frame:
                resident shard columns pass through on their device
                (bucket-padded device-side when needed); missing columns
                and evicted blocks re-stage from the host copy."""
                shard = (
                    cache.shard(bi) if dev_i == assignment[bi] else None
                )
                lo, hi = offsets[bi], offsets[bi + 1]
                staged = {}
                used = False
                for name, (data, dt) in layout.items():
                    v = shard.get(name) if shard is not None else None
                    if v is not None:
                        if pads[bi] is not None:
                            v = bucketing.pad_rows(v, pads[bi])
                        staged[name] = v
                        used = True
                        continue
                    a = host_cols[name][lo:hi]
                    if a.dtype != dt:
                        a = a.astype(dt)
                    if pads[bi] is not None:
                        a = bucketing.pad_rows(a, pads[bi])
                    observability.note_h2d_bytes(a.nbytes)
                    staged[name] = jax.device_put(a, devices[dev_i])
                return staged, used

            if cache is None:
                lanes = device_pool.lanes(devices, assignment, stage_block)
                lane_iters = [iter(l) for l in lanes]
                lane_dead = [False] * len(devices)
            else:
                lanes = []
            params_list = self._params_list()
            out_blocks: List[Optional[Dict[str, Any]]] = [None] * nb
            # donation-adoption: collect each block's device-resident
            # outputs when sharding is on (the result frame adopts them)
            adopt_outs = (
                [None] * nb
                if (
                    cache is not None
                    or len(frame_cache.shard_devices(None)) >= 2
                )
                else None
            )
            eff_assign: List[int] = []
            shard_hits = 0
            for bi in range(nb):
                cancellation.checkpoint()  # block boundary (pooled chain)
                di = assignment[bi]
                if cache is not None:
                    di_eff = pool.effective_device(di) if session else di
                    staged, used = (
                        stage_cached(bi, di_eff)
                        if (session is None or di_eff == di)
                        else (None, False)
                    )
                    if used:
                        shard_hits += 1
                        observability.note_cache_shard_hit()
                    elif session is not None and di_eff != di:
                        session.note_cache_restage()
                    if session is None:
                        outs = run(staged, params_list)
                        del staged
                    else:
                        holder = {"v": staged}
                        del staged

                        def attempt(a, dev_i, _bi=bi, _h=holder, _di=di):
                            # attempt 0 may consume the shard-backed
                            # entries; every retry (and any quarantine
                            # redirect) re-stages from the authoritative
                            # host columns on the CURRENT device
                            ins = (
                                _h.pop("v", None)
                                if (a == 0 and dev_i == _di)
                                else None
                            )
                            _h.clear()
                            if ins is None:
                                ins = stage_block(_bi, devices[dev_i])
                            return run(ins, params_list)

                        outs = session.run(
                            bi,
                            sizes[bi],
                            attempt,
                            device=lambda _di=di: pool.effective_device(
                                _di
                            ),
                        )
                        di_eff = pool.effective_device(di)
                elif session is None:
                    staged = next(lane_iters[di])
                    outs = run(staged, params_list)
                    del staged
                    di_eff = di
                else:
                    staged = _DEFAULT._lane_next(
                        lane_iters[di], lane_dead, di, session, pool
                    )
                    holder = {"v": staged}
                    del staged

                    def attempt(a, dev_i, _bi=bi, _h=holder, _di=di):
                        # attempt 0 may consume the lane-staged entry
                        # buffers; every retry (and any quarantine
                        # redirect) re-stages fresh host slices — a
                        # donated-then-failed buffer is never re-used
                        ins = (
                            _h.pop("v", None)
                            if (a == 0 and dev_i == _di)
                            else None
                        )
                        _h.clear()
                        if ins is None:
                            ins = stage_block(_bi, devices[dev_i])
                        return run(ins, params_list)

                    outs = session.run(
                        bi,
                        sizes[bi],
                        attempt,
                        device=lambda _di=di: pool.effective_device(_di),
                    )
                    di_eff = pool.effective_device(di)
                if pads[bi] is not None:
                    # bucket-padded chain: slice the pad rows back off
                    # (the _pool_pads proof guarantees real rows' values)
                    outs = {k: v[: sizes[bi]] for k, v in outs.items()}
                if adopt_outs is not None:
                    adopt_outs[bi] = outs
                eff_assign.append(di_eff)
                pool.submit(bi, di_eff, sizes[bi], outs, out_blocks)
            pool.finish(out_blocks)
            span.annotate(
                "device_pool",
                pool.record(
                    sum(l.stats["stage_s"] for l in lanes),
                    sum(l.stats["wait_s"] for l in lanes),
                ),
            )
            if session is not None and session.events():
                span.annotate("fault_tolerance", session.record())
            span.mark("dispatch")
            out_frame = TensorFrame.from_blocks(out_blocks)
            # host-only / ragged source columns pass through unchanged when
            # the chain preserves row identity (no trim stage) — same rule
            # as the fused path.  Rebuild BEFORE adoption: the adopted
            # cache must ride the frame object actually returned.
            if not any(s.trim for s in self._stages):
                extra = [
                    c
                    for c in frame.columns
                    if c.info.name not in out_frame.column_names
                    and c.info.name not in self._visible
                ]
                if extra:
                    out_frame = TensorFrame(
                        list(out_frame.columns) + extra, out_frame.offsets
                    )
            adopted = (
                frame_cache.adopt(out_frame, devices, eff_assign, adopt_outs)
                if adopt_outs is not None
                else None
            )
            fc_rec: Dict[str, Any] = {}
            if cache is not None:
                fc_rec = cache.record()
                fc_rec["shard_hits"] = shard_hits
            if adopted is not None:
                fc_rec["adopted_blocks"] = adopted.resident_blocks()
                fc_rec["adopted_bytes_per_device"] = (
                    adopted.resident_bytes_per_device()
                )
            if fc_rec:
                span.annotate("frame_cache", fc_rec)
            return out_frame

    def _entry_layout(self) -> Tuple[Dict[str, Any], bool]:
        """``name -> (column data, effective entry dtype)`` plus whether
        every entry column is host-resident — the ONE walk behind both
        :meth:`_entry_cols` (which stages the data) and :meth:`warmup`
        (which builds matching specs), so a warmed executable's
        signature can never drift from the staged one.  Device-resident
        columns keep their own dtype (they are staged untouched;
        ``_body`` casts per block) and disable donation."""
        layout: Dict[str, Any] = {}
        all_host = True
        for name in self._needed_source_cols():
            c = self._frame.column(name)
            data = c.data
            if is_device_array(data):
                all_host = False
                dt = data.dtype
            else:
                dt = dtypes.coerce(c.info.scalar_type).np_dtype
            layout[name] = (data, dt)
        return layout, all_host

    def _entry_cols(self) -> Tuple[Dict[str, Any], bool]:
        """Source columns for the trace, staged onto the device.

        Host columns are cast then ``device_put`` back to back (async —
        the per-column transfers queue together on the link instead of
        being issued lazily by the jit call).  Returns ``(cols, donate)``:
        ``donate`` is True when every staged buffer is a fresh transfer
        this call created, so ``run``/``iterate`` may donate the entry
        arguments and the staged copies die with the dispatch (steady-
        state HBM holds one staged set).  Device-resident (cached)
        columns are shared frame state and disable donation; mesh
        placement keeps its own sharded path."""
        layout, all_host = self._entry_layout()
        cols = {}
        for name, (data, dt) in layout.items():
            if not is_device_array(data):
                data = np.asarray(data)
                if data.dtype != dt:
                    data = data.astype(dt)
            if self._mesh_mode:
                # rows land sharded over the engine's data axis; GSPMD
                # propagates from these input shardings through the trace
                data = self._engine._place_rows(jnp.asarray(data))
            cols[name] = data
        if self._mesh_mode or not cols:
            return cols, False
        return prefetch.stage_columns(cols), (
            all_host and prefetch.donate_inputs()
        )

    def warmup(self) -> "Pipeline":
        """AOT-lower and compile the fused ``run()``/``collect()``
        executable at the frame's entry signature without dispatching it
        — the pipeline face of the persistent-executable-cache cold
        start (``TFS_COMPILE_CACHE`` / ``Program.aot_compile``).

        With the cache configured, a fresh serving process calls
        ``pipe.warmup()`` before traffic arrives and the fused
        executable deserializes from disk instead of running XLA; the
        subsequent ``run()`` re-traces (cheap) and fetches the same
        backend artifact.  NOT covered: ``iterate()`` compiles a
        different executable (the chain scanned over steps) — its first
        call in a cached process still fetches from disk *if a previous
        process ran the same iterate*, but this method does not prime
        it.  Single-process / mesh-less chains only: a mesh-global
        chain's executable depends on the live sharding, which staging
        establishes."""
        if not self._stages:
            raise ValidationError("pipeline.warmup: empty pipeline")
        if self._mesh_mode:
            raise ValidationError(
                "pipeline.warmup: mesh-global chains compile against live "
                "shardings; warm them by running once."
            )
        layout, all_host = self._entry_layout()
        donate = bool(layout) and all_host and prefetch.donate_inputs()
        specs = {
            name: jax.ShapeDtypeStruct(tuple(np.shape(data)), dt)
            for name, (data, dt) in layout.items()
        }
        if donate not in self._compiled:
            self._compiled[donate] = jax.jit(
                lambda cols, params_list: self._body(cols, params_list),
                **({"donate_argnums": (0,)} if donate else {}),
            )
        param_specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(jnp.shape(a), jnp.asarray(a).dtype),
            self._params_list(),
        )
        with observability.suppress_trace_count():
            self._compiled[donate].lower(specs, param_specs).compile()
        return self

    def collect(self):
        """``run()`` + host materialisation (the one sync)."""
        out = self.run()
        if self._row_stage:
            host = jax.device_get(out)
            return {k: np.asarray(v) for k, v in host.items()}
        return out.uncache()

    def iterate(
        self,
        num_steps: int,
        carry: Mapping[str, str],
        collect: Sequence[str] = (),
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Run the chain ``num_steps`` times in ONE dispatch (``lax.scan``),
        feeding outputs back into stage params between steps.

        ``carry``: output name -> param name.  After each step, the named
        output becomes the new value of every stage param with that name —
        the on-device form of the ``update_params`` iterative-driver
        contract (the reference re-broadcasts a re-built graph per step,
        ``kmeans_demo.py:68-80``; the eager engine updates params per
        dispatch; here the update never leaves the device).

        ``collect``: output names whose per-step values are stacked and
        returned as history (e.g. the loss curve).

        Returns ``(final_params, history)`` — ``final_params`` maps each
        carried param name to its final device value (the stage programs are
        also updated in place, so ``run()``/``iterate()`` continue from the
        new state); ``history`` maps each collected name to a ``[num_steps,
        ...]`` device array."""
        if not self._row_stage:
            raise ValidationError(
                "pipeline.iterate: requires a row-terminal chain "
                "(reduce/then) so step outputs can feed back into params."
            )
        if not carry:
            raise ValidationError(
                "pipeline.iterate: carry={} would loop without feedback; "
                "use run() in a host loop instead."
            )
        targets: List[Tuple[int, str, str]] = []  # (stage idx, param, output)
        for out_name, param_name in carry.items():
            hits = [
                i
                for i, st in enumerate(self._stages)
                if st.program is not None
                and param_name in st.program.param_names
            ]
            if not hits:
                raise ValidationError(
                    f"pipeline.iterate: carry target param {param_name!r} "
                    f"does not exist on any stage program."
                )
            for i in hits:
                targets.append((i, param_name, out_name))

        cols, donate = self._entry_cols()
        key = (num_steps, tuple(sorted(carry.items())), tuple(collect), donate)
        if key not in self._iter_compiled:

            def loop(cols, params_list):
                def step(pl, _):
                    row = self._body(cols, pl)
                    for name in list(carry) + list(collect):
                        if name not in row:
                            raise ValidationError(
                                f"pipeline.iterate: {name!r} is not an "
                                f"output of the chain; outputs are "
                                f"{sorted(row)}."
                            )
                    new_pl = [dict(p) for p in pl]
                    for i, pname, oname in targets:
                        old = new_pl[i][pname]
                        new = row[oname]
                        if not hasattr(old, "shape"):
                            raise ValidationError(
                                f"pipeline.iterate: param {pname!r} is a "
                                f"pytree, not a single array; only "
                                f"leaf-array params can be carried — bind "
                                f"the leaves as separate params."
                            )
                        if new.shape != old.shape:
                            raise ValidationError(
                                f"pipeline.iterate: carried output "
                                f"{oname!r} has shape {new.shape} but param "
                                f"{pname!r} has shape {old.shape}; shapes "
                                f"must match for a stable loop carry."
                            )
                        new_pl[i][pname] = new.astype(old.dtype)
                    return new_pl, {k: row[k] for k in collect}

                final_pl, hist = jax.lax.scan(
                    step, params_list, None, length=num_steps
                )
                finals = {}
                for i, pname, _ in targets:
                    finals[pname] = final_pl[i][pname]
                return finals, hist

            self._iter_compiled[key] = jax.jit(
                loop, **({"donate_argnums": (0,)} if donate else {})
            )

        with observability.verb_span(
            "pipeline.iterate", self._frame.num_rows, self._frame.num_blocks
        ) as span:
            span.mark("validate")
            span.annotate("donate_entry", donate)
            finals, hist = self._iter_compiled[key](cols, self._params_list())
            del cols
            span.mark("dispatch")
            # resume contract: stage programs pick up the final params
            for i, pname, _ in targets:
                self._stages[i].program.update_params(**{pname: finals[pname]})
            return finals, hist


def pipeline(frame: TensorFrame, engine=None) -> Pipeline:
    """Start a fused verb chain over ``frame`` (see :class:`Pipeline`).

    ``engine``: pass a ``parallel.MeshExecutor`` to run the chain
    mesh-global — source columns sharded over its data axis, reduce
    combines on ICI (module docstring)."""
    if getattr(frame, "_tfs_lazy", False):
        # explicit Pipeline over a lazy frame: materialise the plan
        # first — a Pipeline is its own fusion surface
        from . import planner

        frame = planner.ensure_frame(frame)
    if (
        engine is not None
        and hasattr(engine, "mesh")
        and getattr(engine, "mode", "global") != "global"
    ):
        raise ValidationError(
            "pipeline: a fused chain has exactly one logical block, so "
            "only mode='global' MeshExecutors compose with it; per-block "
            "(partition) semantics need the eager MeshExecutor verbs."
        )
    return Pipeline(frame, engine=engine)
