"""Shape-canonical execution: geometric row/cell bucket padding.

The engine compiles one XLA executable per *input signature* (shapes +
dtypes), so every distinct block row count costs a full trace + compile.
``TensorFrame.repartition`` deals near-equal blocks that differ by one
row (``frame.py``), so an uneven frame compiles every block program at
least twice; ragged ``map_rows`` traces once per distinct cell shape —
"bounded" only if the data cooperates; and a new frame size is a new
signature even when the program is identical.  Compile cost therefore
scales O(frames x shapes) when the ROADMAP north-star needs it O(1)
amortized per program.

This module supplies the canonicalization policy shared by the verbs:

* :func:`bucket_for` rounds a row count (or a ragged cell's lead dim) up
  to a small geometric bucket set — powers of two by default, overridden
  with ``TFS_BLOCK_BUCKETS`` (comma-separated ladder; counts above the
  ladder round up to a multiple of its top rung; ``0``/``off`` disables
  canonicalization entirely).
* :func:`pad_rows` pads the lead axis up to the bucket by repeating the
  edge row (never zeros: pad rows flow through the real program, and
  edge values are guaranteed to be in the program's valid domain).
  Outputs are sliced back to the true row count by the caller.

Safety: padding is applied only where the pad rows provably cannot
change real rows' results —

* ``map_rows``: rows are independent *by construction* (the cell program
  is vmapped over the lead axis), so map-rows blocks pad freely;
* ``map_blocks``: gated on the shared row-independence gate
  (``analysis.rows_independent`` — the memoized size-generic
  classification, with the exact-size probe as the ``UNKNOWN``
  fallback; envelope caveats in ``analysis/rowdep.py``) — cross-row
  programs (block reductions, sorts, block-size literals) keep their
  exact shapes;
* ragged ``map_rows`` cells: gated on the same proof applied along the
  ragged cell axis (``engine._map_rows_ragged``), with the uniform
  inputs bound as trace params (constant within a row, so the proof's
  "group" class);
* reduce/aggregate paths keep their own identity-padding machinery
  (``engine._segment_pad_rows``: pads are the reduction *identity*, only
  for recognized monoid plans) and fall back to exact shapes when the
  monoid cannot absorb pads — value padding through an arbitrary
  reduction is never sound, so those verbs do not use this module's row
  padding.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import numpy as np
from .. import envutil

logger = logging.getLogger("tensorframes_tpu.bucketing")

ENV_VAR = "TFS_BLOCK_BUCKETS"

# minimum bucket: padding below this costs nothing measurable and keeps
# tiny uneven tails (1..8 rows) on one executable
_MIN_BUCKET = 8

# malformed knob values already warned about (warn once per value, not
# once per verb call)
_warned: set = set()


def _warn_once(raw: str, why: str) -> None:
    if raw not in _warned:
        _warned.add(raw)
        logger.warning(
            "%s=%r is malformed (%s); falling back to the default "
            "power-of-two buckets. Use a comma-separated ladder of "
            "positive ints (e.g. '64,512,4096') or '0' to disable.",
            ENV_VAR,
            raw,
            why,
        )


def bucket_ladder() -> Optional[Tuple[int, ...]]:
    """The explicit bucket ladder from ``TFS_BLOCK_BUCKETS``, or ``()``
    for the default power-of-two policy, or ``None`` when bucketing is
    disabled (``TFS_BLOCK_BUCKETS=0``/``off``).  Read per call: the env
    knob toggles mid-process (bench A/B legs, tests).

    Malformed values never silently change which executables run: a
    value that does not parse as a ladder of positive ints (and is not a
    disable token) logs a warning naming the value and falls back to
    the DEFAULT policy — the same behavior as not setting the knob."""
    raw = envutil.env_raw(ENV_VAR)
    if not raw:
        return ()
    if raw.lower() in ("0", "off", "none", "false"):
        return None
    try:
        rungs = sorted({int(x) for x in raw.split(",") if x.strip()})
    except ValueError:
        _warn_once(raw, "unparseable entry")
        return ()
    if not rungs:
        _warn_once(raw, "no bucket sizes")
        return ()
    if rungs[0] <= 0:
        _warn_once(raw, "non-positive bucket size")
        return ()
    return tuple(rungs)


def enabled() -> bool:
    return bucket_ladder() is not None


def bucket_for(n: int) -> int:
    """Smallest bucket >= ``n``: the canonical executed lead-dim size.

    Default ladder is powers of two (floored at a small minimum bucket);
    an explicit ``TFS_BLOCK_BUCKETS`` ladder is honored verbatim, with
    counts above its top rung rounded up to a multiple of that rung (so
    oversized blocks still land on O(1) distinct shapes).  ``n <= 0``
    and disabled bucketing return ``n`` unchanged."""
    ladder = bucket_ladder()
    if ladder is None or n <= 0:
        return n
    if ladder:
        for b in ladder:
            if b >= n:
                return b
        top = ladder[-1]
        return -(-n // top) * top
    if n <= _MIN_BUCKET:
        return _MIN_BUCKET
    return 1 << (n - 1).bit_length()


def coalesced_blocks(total_rows: int, n_lanes: int) -> int:
    """Block count for a coalesced micro-batch (``bridge/coalescer.py``):
    spread the combined rows over up to ``n_lanes`` device-pool lanes,
    but never deal a block below the minimum bucket — sub-bucket blocks
    would all pad to ``_MIN_BUCKET`` anyway and just multiply dispatch
    overhead.  The resulting blocks land on the SAME geometric ladder as
    every other verb (``bucket_for``), so concurrent tenants' batches
    share hot executables regardless of who arrived together."""
    if n_lanes <= 1 or total_rows <= _MIN_BUCKET:
        return 1
    return max(1, min(int(n_lanes), total_rows // _MIN_BUCKET))


def pad_rows(arr, target: int):
    """Pad ``arr``'s lead axis up to ``target`` rows by repeating the
    edge (last) row.  Host arrays pad in numpy (cheap, runs on the
    prefetch staging thread); device arrays pad with ``jnp`` on the
    consumer thread (the Prefetcher contract keeps jit entry points off
    the worker, and the engine only routes host-resident blocks to the
    worker).  No-op when already at or above ``target``."""
    n = arr.shape[0]
    if n >= target:
        return arr
    if isinstance(arr, np.ndarray):
        return np.concatenate([arr, np.repeat(arr[-1:], target - n, axis=0)])
    import jax.numpy as jnp

    return jnp.concatenate(
        [arr, jnp.repeat(arr[-1:], target - n, axis=0)]
    )
