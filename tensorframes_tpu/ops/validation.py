"""Verb pre-flight validation — the ``SchemaTransforms`` layer.

The reference treats validation *and its error-message quality* as half the
product (``DebugRowOps.scala:53-275``; SURVEY.md §7 step 3 calls this out
explicitly).  Every check here mirrors a reference check:

* map verbs: each program input must name an existing, fully-analyzed,
  device-feedable column (``DebugRowOps.scala:318-346``);
* ``reduce_rows``: the pairwise ``x_1``/``x_2`` naming contract — for every
  output ``x`` the program must consume exactly ``x_1`` and ``x_2`` with the
  cell shape and dtype of column ``x`` (``DebugRowOps.scala:172-262``,
  ``Operations.scala:86-96``);
* ``reduce_blocks``/``aggregate``: the ``x_input`` block contract — for every
  output ``x`` the program consumes ``x_input`` = a block of ``x`` cells and
  emits one ``x`` cell (``DebugRowOps.scala:80-170``, ``ReduceBlockSchema``
  at L36-40).

All failures raise ``ValidationError`` with messages that name the offending
column, list what's available, and say what to do (run ``analyze``, fix the
name, ...).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Tuple

import jax

from .. import dtypes
from ..frame import TensorFrame
from ..program import GraphNodeSummary, Program
from ..schema import ColumnInfo, Schema
from ..shape import Shape, UNKNOWN


class ValidationError(ValueError):
    """A verb's schema contract was violated (reference: the require(...)
    failures in SchemaTransforms).

    ``code``: the stable ``TFSxxx`` diagnostic code (round 17,
    ``docs/ANALYSIS.md``) — the same taxonomy ``tfs.check`` reports
    pre-dispatch, attached here so the dispatch-time failure and the
    static diagnostic are the SAME error, not two prose variants."""

    def __init__(self, message: str, code: str = None):
        super().__init__(message)
        self.code = code


def _column_for_input(
    frame: TensorFrame,
    program: Program,
    input_name: str,
    verb: str,
    host_staged: bool = False,
    allow_ragged: bool = False,
) -> ColumnInfo:
    col_name = program.column_for_input(input_name)
    schema = frame.schema
    if col_name not in schema:
        raise ValidationError(
            f"{verb}: program input {input_name!r} requests column "
            f"{col_name!r}, which does not exist in the frame. Available "
            f"columns: {schema.names}. (Program inputs are matched to columns "
            f"by name; pass feed_dict={{input: column}} to rename.)",
            code="TFS103",
        )
    ci = schema[col_name]
    if host_staged:
        # a host_stage fn materialises this input on the host, so binary /
        # ragged / un-analyzed columns are all legal here — the stage's
        # output is what reaches the device
        return ci
    if not ci.scalar_type.device_ok:
        raise ValidationError(
            f"{verb}: column {col_name!r} has host-only scalar type "
            f"{ci.scalar_type} and cannot be fed to a device program "
            f"directly. Pass host_stage={{{input_name!r}: decode_fn}} to run "
            f"a host-side preprocessing stage (e.g. JPEG decode -> uint8 "
            f"pixels) before the device program — the reference's in-graph "
            f"DecodeJpeg contract (read_image.py:164-167).",
            code="TFS104",
        )
    if not ci.is_analyzed:
        if allow_ragged:
            # map_rows resolves ragged cells per row via size-bucketing
            # (the reference's per-row lead-dim resolution,
            # TFDataOps.scala:86-103); block verbs stay strict
            return ci
        raise ValidationError(
            f"{verb}: column {col_name!r} has un-analyzed cell shape "
            f"{ci.cell_shape}. Run tensorframes_tpu.analyze(frame) first, "
            f"construct the frame from uniform arrays, or use map_rows "
            f"(which buckets ragged rows by shape).",
            code="TFS105",
        )
    return ci


def check_map_inputs(
    program: Program,
    frame: TensorFrame,
    verb: str,
    host_staged=(),
    allow_ragged: bool = False,
) -> Dict[str, ColumnInfo]:
    """Validate the inputs of map_blocks/map_rows; returns input->ColumnInfo.

    ``host_staged``: input names whose data is produced by a host
    preprocessing stage rather than fed from the column directly."""
    staged = set(host_staged)
    unknown = staged - set(program.input_names)
    if unknown:
        raise ValidationError(
            f"{verb}: host_stage given for names {sorted(unknown)} that are "
            f"not program inputs; inputs are {program.input_names}",
            code="TFS112",
        )
    out = {}
    for n in program.input_names:
        out[n] = _column_for_input(
            frame,
            program,
            n,
            verb,
            host_staged=n in staged,
            allow_ragged=allow_ragged,
        )
    return out


def check_reduce_rows(program: Program, frame: TensorFrame) -> Dict[str, ColumnInfo]:
    """Enforce the pairwise x_1/x_2 contract; returns output name -> ColumnInfo.

    Reference: ``reduceRowsSchema`` (``DebugRowOps.scala:172-262``).
    """
    inputs = set(program.input_names)
    outputs: Dict[str, ColumnInfo] = {}
    suffixed = {}
    for n in inputs:
        if n.endswith("_1") or n.endswith("_2"):
            suffixed.setdefault(n[:-2], set()).add(n[-1])
        else:
            raise ValidationError(
                f"reduce_rows: program input {n!r} does not follow the "
                f"pairwise naming convention: every input must be named "
                f"'<col>_1' or '<col>_2' (Operations.scala:86-96).",
                code="TFS106",
            )
    for base, halves in suffixed.items():
        if halves != {"1", "2"}:
            raise ValidationError(
                f"reduce_rows: column {base!r} must be consumed as BOTH "
                f"{base}_1 and {base}_2; found only suffix(es) "
                f"{sorted(halves)}.",
                code="TFS106",
            )
        # feed-dict rename (round 11): both halves of a pair must feed
        # from the SAME column (the pairwise fold has one source)
        c1 = program.column_for_input(f"{base}_1")
        c2 = program.column_for_input(f"{base}_2")
        col = base if c1 == f"{base}_1" else c1
        col2 = base if c2 == f"{base}_2" else c2
        if col != col2:
            raise ValidationError(
                f"reduce_rows: inputs {base}_1/{base}_2 must feed from one "
                f"column; the feed maps them to {col!r} and {col2!r}.",
                code="TFS107",
            )
        schema = frame.schema
        if col not in schema:
            raise ValidationError(
                f"reduce_rows: inputs {base}_1/{base}_2 refer to column "
                f"{col!r}, which does not exist. Available: {schema.names}.",
                code="TFS103",
            )
        ci = schema[col]
        if not ci.is_analyzed:
            raise ValidationError(
                f"reduce_rows: column {col!r} has un-analyzed cell shape "
                f"{ci.cell_shape}; run analyze(frame) first.",
                code="TFS105",
            )
        outputs[base] = ci
    return outputs


def check_reduce_rows_outputs(
    reduced: Mapping[str, ColumnInfo],
    summaries: List[GraphNodeSummary],
) -> None:
    out_names = {s.name for s in summaries if s.is_output}
    expected = set(reduced)
    if out_names != expected:
        raise ValidationError(
            f"reduce_rows: program outputs {sorted(out_names)} must exactly "
            f"match the reduced columns {sorted(expected)} (each output x is "
            f"the combined value of x_1 and x_2).",
            code="TFS109",
        )
    for s in summaries:
        if s.is_output:
            ci = reduced[s.name]
            if tuple(s.shape) != tuple(ci.cell_shape):
                raise ValidationError(
                    f"reduce_rows: output {s.name!r} has shape {s.shape} but "
                    f"column {s.name!r} has cell shape {ci.cell_shape}; a "
                    f"pairwise reducer must preserve the cell shape.",
                    code="TFS109",
                )


def check_reduce_blocks(
    program: Program, frame: TensorFrame, verb: str = "reduce_blocks"
) -> Dict[str, ColumnInfo]:
    """Enforce the x_input block contract; returns output name -> ColumnInfo.

    Reference: ``reduceBlocksSchema`` (``DebugRowOps.scala:80-170``).
    """
    outputs: Dict[str, ColumnInfo] = {}
    for n in program.input_names:
        if not n.endswith("_input"):
            raise ValidationError(
                f"{verb}: program input {n!r} does not follow the block "
                f"naming convention: every input must be named '<col>_input' "
                f"and consume a whole block of column <col> "
                f"(Operations.scala:98-108).",
                code="TFS108",
            )
        base = n[: -len("_input")]
        # feed-dict rename (round 11): ``inputs={"x_input": "data"}``
        # feeds the block of column ``data`` — the naming convention is
        # the default mapping, not a restriction.  The returned
        # ColumnInfo keeps the RESOLVED column name, which is what the
        # engine's block reads key on.
        col = program.column_for_input(n)
        if col == n:
            col = base
        schema = frame.schema
        if col not in schema:
            raise ValidationError(
                f"{verb}: input {n!r} refers to column {col!r}, which does "
                f"not exist. Available: {schema.names}.",
                code="TFS103",
            )
        ci = schema[col]
        if not ci.is_analyzed:
            raise ValidationError(
                f"{verb}: column {col!r} has un-analyzed cell shape "
                f"{ci.cell_shape}; run analyze(frame) first.",
                code="TFS105",
            )
        if not ci.scalar_type.device_ok:
            raise ValidationError(
                f"{verb}: column {col!r} is host-only ({ci.scalar_type}) and "
                f"cannot be reduced on device.",
                code="TFS104",
            )
        outputs[base] = ci
    return outputs


def check_reduce_blocks_outputs(
    reduced: Mapping[str, ColumnInfo],
    summaries: List[GraphNodeSummary],
    verb: str = "reduce_blocks",
) -> None:
    out_names = {s.name for s in summaries if s.is_output}
    expected = set(reduced)
    if out_names != expected:
        raise ValidationError(
            f"{verb}: program outputs {sorted(out_names)} must exactly match "
            f"the reduced columns {sorted(expected)} (each output x is the "
            f"block-reduction of x_input).",
            code="TFS109",
        )
    for s in summaries:
        if s.is_output:
            ci = reduced[s.name]
            if tuple(s.shape) != tuple(ci.cell_shape):
                raise ValidationError(
                    f"{verb}: output {s.name!r} has shape {s.shape} but column "
                    f"{s.name!r} has cell shape {ci.cell_shape}; a block "
                    f"reducer must emit one cell per block so the reduction "
                    f"can be re-applied across blocks.",
                    code="TFS109",
                )
