"""Sharded HBM frame cache: block-affinity placement + LRU byte budget.

``frame.cache()`` (round 2) pins a frame's columns in device memory so
iterative pipelines pay zero H2D traffic — the Spark ``df.cache()``
analog the reference's demos rely on (``kmeans_demo.py`` caches before
iterating).  But the round-2 cache lives on ONE device, and the engine
deliberately kept device-resident frames off the device pool
(``engine.py``: "splitting a cached column across the pool would shuffle
HBM") — so the exact workloads caching exists for forfeited the whole
round-8 multi-device speedup.

This module removes that trade by changing the *placement unit* from the
column to the **block shard**: ``cache(sharded=True)`` (or
``TFS_CACHE_SHARDED=auto`` while a device pool is active) places each
block's column slices directly on that block's pool device — the same
deterministic least-loaded assignment the scheduler uses
(:func:`tensorframes_tpu.ops.device_pool.assign`), so a later verb's
block->device plan MATCHES the residency plan and every block executes
on the device that already holds it.  The engine's affinity dispatch
(``engine._map_dispatch_sharded``) then runs device-resident frames
across the whole pool with no staging lanes and no H2D.

Design rules:

* **The host copy stays authoritative.**  A sharded cache never replaces
  the frame's host columns — the shards are an acceleration layer.  That
  is what makes LRU eviction free (drop the shard, the bytes are still
  on host), fault-tolerance re-staging possible (a quarantined device's
  cached blocks rebuild on a healthy device from host), and retry
  semantics unchanged (every retry re-stages fresh host buffers).
* **Shards are shared state: never donated, never mutated.**  The
  affinity dispatch always uses the non-donating executables, exactly
  like the round-2 single-device cache.
* **Bounded HBM** (``TFS_HBM_BUDGET`` bytes, 0/unset = unlimited): every
  resident shard is bytes-accounted in one process-wide LRU; inserting
  past the budget evicts the least-recently-used shard (any cache, any
  frame) back to its authoritative host copy and counts
  ``cache_evictions``.  An evicted block simply re-stages from host on
  its next use — correctness never depends on residency.
* **Donation-adoption** (``Pipeline`` pooled chains): a pooled map
  chain's per-device output buffers are adopted in place as the cached
  shards of the successor frame — the next epoch of an iterative
  pipeline reads them straight from HBM with zero re-staging — while the
  overlapped D2H readback still materialises the authoritative host
  copy.  Adopted shards obey the same budget.

Knobs:

* ``TFS_CACHE_SHARDED`` — ``auto`` (default: shard when the device pool
  resolves >= 2 devices), ``1``/``always`` (shard whenever >= 2 local
  devices exist, pool knob or not), ``0``/``off`` (never shard;
  ``cache()`` keeps the round-2 single-device behavior).
* ``TFS_HBM_BUDGET`` — resident-shard byte budget (accepts plain bytes
  or ``K``/``M``/``G`` suffixes; 0/unset = unlimited).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import weakref
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .. import observability
from .. import envutil
from ..envutil import parse_bytes, warn_once
from . import device_pool

logger = logging.getLogger("tensorframes_tpu.frame_cache")

ENV_SHARDED = "TFS_CACHE_SHARDED"
ENV_BUDGET = "TFS_HBM_BUDGET"
ENV_TENANT_BUDGET = "TFS_CACHE_TENANT_BUDGET"

def _warn_once(key: str, msg: str, *args) -> None:
    warn_once(logger, "frame_cache:" + key, msg, *args)


def tenant_budget() -> int:
    """Per-tenant resident-shard byte budget
    (``TFS_CACHE_TENANT_BUDGET``; 0 = no per-tenant cap, round 19).

    Layered UNDER ``TFS_HBM_BUDGET``: a tenant whose resident shards
    would exceed this cap evicts its OWN least-recently-used shards
    first, so one tenant's epoch loop cannot flush every other
    tenant's warm shards out of the shared LRU.  Tenant identity is
    billed from real PR 10 ledger usage: the request ledger active when
    a cache is built/adopted names the owning tenant."""
    raw = envutil.env_raw(ENV_TENANT_BUDGET)
    if not raw.strip():
        return 0
    parsed = parse_bytes(raw)
    if parsed is None:
        _warn_once(
            "tenant_budget:" + raw,
            "%s=%r is malformed; use bytes or a K/M/G suffix. "
            "Treating as no per-tenant cap.",
            ENV_TENANT_BUDGET,
            raw,
        )
        return 0
    return parsed


def _request_tenant() -> Optional[str]:
    """The tenant the active request chain attributes work to (nested
    ledgers may leave ``tenant`` on an outer ledger only)."""
    led = observability.current_request()
    while led is not None:
        if led.tenant:
            return led.tenant
        led = led.parent
    return None


def hbm_budget() -> int:
    """Resident-shard byte budget (``TFS_HBM_BUDGET``; 0 = unlimited).

    Accepts plain bytes or a ``K``/``M``/``G`` binary suffix
    (``envutil.parse_bytes``).  Read per call so tests and bench legs
    can flip it mid-process."""
    raw = envutil.env_raw(ENV_BUDGET)
    if not raw.strip():
        return 0
    parsed = parse_bytes(raw)
    if parsed is None:
        _warn_once(
            "budget:" + raw,
            "%s=%r is malformed; use bytes or a K/M/G suffix. "
            "Treating as unlimited.",
            ENV_BUDGET,
            raw,
        )
        return 0
    return parsed


def shard_devices(explicit: Optional[bool] = None) -> List[Any]:
    """The devices a new sharded cache would place on, or ``[]`` when
    sharding should not engage.

    ``explicit=None`` follows ``TFS_CACHE_SHARDED``: ``auto`` shards
    exactly when the device pool resolves (>= 2 devices), so a cached
    frame's residency plan matches the scheduler that will consume it;
    ``1``/``always`` shards over all local devices even with the pool
    knob off; ``0``/``off`` never shards.  ``explicit=True``/``False``
    (the ``cache(sharded=)`` argument) overrides the env the same way."""
    raw = envutil.env_raw(ENV_SHARDED, "auto").lower()
    if explicit is None:
        if raw in ("0", "off", "false", "no", "none"):
            return []
        if raw in ("1", "always", "true", "yes", "force"):
            explicit = True
        elif raw in ("", "auto"):
            return device_pool.pool_devices()
        else:
            _warn_once(
                "sharded:" + raw,
                "%s=%r is malformed; use 'auto', '1'/'always' or "
                "'0'/'off'. Falling back to 'auto'.",
                ENV_SHARDED,
                raw,
            )
            return device_pool.pool_devices()
    if not explicit:
        return []
    devs = device_pool.pool_devices()
    if devs:
        return devs
    import jax

    devs = list(jax.local_devices())
    return devs if len(devs) >= 2 else []


def _delete_spill_files(spill, tag: str, spilled: set) -> None:
    """GC finalizer body for spill-backed caches: remove whatever shard
    files are still on disk (``delete`` tolerates already-gone keys)."""
    for bi in list(spilled):
        spill.delete(f"{tag}-{bi}")


def array_nbytes(a) -> int:
    """Byte size of one (host or device) array."""
    nb = getattr(a, "nbytes", None)
    if nb is not None:
        return int(nb)
    arr = np.asarray(a)
    return int(arr.nbytes)


class FrameCache:
    """Per-frame shard bookkeeping: ``blocks[bi]`` is a dict of
    device-resident column arrays for block ``bi`` (or ``None`` when the
    block was evicted / never fit the budget), all living on
    ``devices[assignment[bi]]``.

    A cache is attached to exactly one :class:`~tensorframes_tpu.frame.
    TensorFrame` (``frame._cache``) whose host columns remain the
    authoritative copy; the engine consults :func:`active_cache` per
    verb and falls back to host staging for any non-resident block.

    ``spill`` (round 12, out-of-core streaming): a
    :class:`tensorframes_tpu.streaming.spill.SpillStore` (or any object
    with ``put``/``get``/``delete``).  With it set, the cache's frame is
    declared to have NO durable host copy (a streamed window the reader
    has moved past), so the budget LRU's eviction path cannot simply
    drop a shard — :meth:`evict` writes the shard's bytes to disk first
    and :meth:`shard` restores them (disk -> host -> affinity device,
    re-charged against the budget) on the block's next use.  Without
    ``spill`` the round-10 behavior is untouched: eviction is free
    because the host columns are authoritative.

    Known scope limit, deliberate for round 12: a ``TensorFrame``
    object still pins its host column arrays for its own lifetime, so
    while a windowed frame is LIVE its host copy could also serve
    re-staging — the disk copy pays off against lifecycle, not liveness
    (it is what survives once host-column release for windowed caches
    lands; ROADMAP open item).  The mechanism, counters, and tests are
    the contract this round establishes."""

    def __init__(
        self,
        devices: Sequence[Any],
        assignment: Sequence[int],
        adopted: bool = False,
        spill: Optional[Any] = None,
    ):
        self.devices = list(devices)
        self.assignment = list(assignment)
        self.blocks: List[Optional[Dict[str, Any]]] = [None] * len(
            self.assignment
        )
        self.nbytes: List[int] = [0] * len(self.assignment)
        self.adopted = adopted
        self.spill = spill
        # per-tenant budget attribution (round 19): the request ledger
        # active at build/adopt time names the owner; None bills to the
        # shared (un-tenanted) pool, which has no per-tenant cap
        self.tenant: Optional[str] = _request_tenant()
        self._spilled: set = set()
        self._spill_tag = f"shard-{os.getpid()}-{id(self):x}"
        if spill is not None:
            # a cache dropped without uncache() must not leak its spill
            # files on disk; the finalizer holds no reference back to
            # the cache (the set is shared, not captured via self)
            weakref.finalize(
                self, _delete_spill_files, spill, self._spill_tag,
                self._spilled,
            )

    # -- residency -----------------------------------------------------------

    def insert(self, bi: int, shard: Dict[str, Any]) -> bool:
        """Account block ``bi``'s shard against the HBM budget and make
        it resident; returns False (shard dropped) when the budget
        cannot hold it even after evicting every other resident shard."""
        nbytes = sum(array_nbytes(v) for v in shard.values())
        if not _budget.charge(self, bi, nbytes):
            return False
        self.blocks[bi] = dict(shard)
        self.nbytes[bi] = nbytes
        return True

    def _spill_key(self, bi: int) -> str:
        return f"{self._spill_tag}-{bi}"

    def shard(self, bi: int) -> Optional[Dict[str, Any]]:
        """Block ``bi``'s resident shard (LRU-touched), or None.  A
        spill-backed cache restores an evicted shard from disk —
        disk -> host -> the block's affinity device, re-charged against
        the budget (which may evict another shard) — so a windowed
        frame's bytes survive LRU churn instead of vanishing.  The disk
        copy is KEPT after a restore: shards are immutable, so it stays
        valid and the next eviction of this block is a free pointer
        drop instead of a full re-serialize (``_spilled`` therefore
        means "valid disk copy exists", resident or not)."""
        s = self.blocks[bi]
        if s is not None:
            _budget.touch(self, bi)
            return s
        if self.spill is not None and bi in self._spilled:
            host = self.spill.get(self._spill_key(bi))
            if host is None:  # spill file lost: nothing to restore
                self._spilled.discard(bi)
                return None
            import jax

            dev = self.devices[self.assignment[bi]]
            staged = {}
            for name, arr in host.items():
                observability.note_h2d_bytes(arr.nbytes)
                staged[name] = jax.device_put(arr, dev)
            if self.insert(bi, staged):
                observability.trace_instant(
                    "spill_restore", "cache", block=bi
                )
                return self.blocks[bi]
            # the budget cannot hold it even now — the disk copy stays
            # the only copy; the caller falls back
        return None

    def evict(self, bi: int) -> None:
        """Drop block ``bi``'s shard (budget eviction / release path).
        With a durable host copy that is free; a spill-backed cache
        (windowed frame, no host authority) writes the shard to
        ``TFS_SPILL_DIR`` first so the bytes survive — unless a valid
        disk copy from an earlier eviction already exists (shards are
        immutable, so re-writing identical bytes would be pure I/O
        waste in exactly the tight-budget thrash regime spill serves)."""
        shard = self.blocks[bi]
        spilled_now = False
        if (
            shard is not None
            and self.spill is not None
            and bi not in self._spilled
        ):
            host = {k: np.asarray(v) for k, v in shard.items()}
            self.spill.put(self._spill_key(bi), host)
            self._spilled.add(bi)
            spilled_now = True
        if shard is not None:
            observability.trace_instant(
                "evict",
                "cache",
                block=bi,
                bytes=self.nbytes[bi],
                spilled=spilled_now,
            )
        self.blocks[bi] = None
        self.nbytes[bi] = 0

    def block_host(self, bi: int, name: str) -> np.ndarray:
        """Block ``bi``'s column ``name`` as a HOST array, read from the
        resident shard or the spill file WITHOUT charging the budget —
        the read-only materialisation path behind released host columns
        (:class:`SpillBackedColumnData`)."""
        s = self.blocks[bi]
        if s is not None and name in s:
            return np.asarray(s[name])
        if self.spill is not None and bi in self._spilled:
            host = self.spill.get(self._spill_key(bi))
            if host is not None and name in host:
                return host[name]
        raise RuntimeError(
            f"released column {name!r}: block {bi} has neither a "
            f"resident shard nor a spill copy (spill file lost?)"
        )

    def release(self) -> None:
        """Drop every shard and refund the budget (``uncache()``)."""
        _budget.release(self)
        for bi in range(len(self.blocks)):
            self.blocks[bi] = None
            self.nbytes[bi] = 0
        if self.spill is not None:
            for bi in sorted(self._spilled):
                self.spill.delete(self._spill_key(bi))
            self._spilled.clear()

    # -- stats ---------------------------------------------------------------

    def resident_blocks(self) -> int:
        return sum(1 for b in self.blocks if b is not None)

    def resident_bytes_per_device(self) -> List[int]:
        out = [0] * len(self.devices)
        for bi, b in enumerate(self.blocks):
            if b is not None:
                out[self.assignment[bi]] += self.nbytes[bi]
        return out

    def record(self) -> dict:
        """The ``frame_cache`` span annotation body."""
        rec = {
            "devices": len(self.devices),
            "blocks": len(self.blocks),
            "resident_blocks": self.resident_blocks(),
            "resident_bytes_per_device": self.resident_bytes_per_device(),
            "adopted": self.adopted,
        }
        if self.spill is not None:
            rec["spilled_blocks"] = len(self._spilled)
        return rec


class _HbmBudget:
    """Process-wide LRU over every resident shard of every live cache.

    Entries hold weak cache references so a frame dropped without
    ``uncache()`` cannot pin budget forever — its entries fall out on
    the next charge walk.  ``charge`` evicts least-recently-used shards
    (across caches) until the new shard fits; a shard larger than the
    whole budget is refused rather than thrashing everything out."""

    def __init__(self):
        self._lock = threading.Lock()
        # key: (id(cache), bi) ->
        #     (weakref(cache), bi, nbytes, tenant, pinned)
        # ``pinned`` (round 22): the entry is accounting for memory that
        # CANNOT be evicted-and-restored (a live decode sequence's KV
        # pages — evicting them would corrupt in-flight generation, not
        # just cost a re-stage).  Pinned entries are skipped by every
        # eviction walk; when a PINNED charge cannot fit after evicting
        # all unpinned shards, charge() returns False and the caller
        # surfaces a typed admission refusal instead of OOMing mid-step.
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self.total_bytes = 0
        # per-tenant resident bytes (round 19, TFS_CACHE_TENANT_BUDGET)
        self.tenant_bytes: Dict[str, int] = {}
        # per-tenant LRU key index (ordered set mirroring _entries'
        # recency for that tenant's shards): the self-first eviction's
        # victim lookup is O(1) instead of a scan of every tenant's
        # entries under the global lock
        self.tenant_keys: Dict[str, "collections.OrderedDict"] = {}

    def _drop(self, key) -> Optional[tuple]:
        """Unaccount one entry (lock held); returns ``(cache, bi)``
        when the caller should run the cache's eviction hook, or None
        for dead/refunded entries.  The hook runs OUTSIDE the lock —
        spill-backed eviction does disk I/O (``FrameCache.evict``), and
        a process-wide lock must never wait on a disk write."""
        ref, bi, nbytes, tenant, _pinned = self._entries.pop(key)
        self.total_bytes -= nbytes
        if tenant is not None:
            left = self.tenant_bytes.get(tenant, 0) - nbytes
            if left > 0:
                self.tenant_bytes[tenant] = left
            else:
                self.tenant_bytes.pop(tenant, None)
            keys = self.tenant_keys.get(tenant)
            if keys is not None:
                keys.pop(key, None)
                if not keys:
                    self.tenant_keys.pop(tenant, None)
        cache = ref()
        return (cache, bi) if cache is not None else None

    def _prune(self) -> None:
        """Drop entries whose cache was garbage-collected without an
        explicit ``uncache()`` — their shards are already freed, so they
        must not keep pinning budget."""
        for key in [k for k, v in self._entries.items() if v[0]() is None]:
            self._drop(key)

    def _lru_victim(self, keys) -> Optional[tuple]:
        """Oldest UNPINNED key in ``keys`` (lock held), or None when
        everything remaining is pinned (live KV pages are not evictable
        — round 22)."""
        for k in keys:
            entry = self._entries.get(k)
            if entry is not None and not entry[4]:
                return k
        return None

    def charge(
        self, cache: FrameCache, bi: int, nbytes: int, pinned: bool = False
    ) -> bool:
        budget = hbm_budget()
        t_budget = tenant_budget()
        tenant = getattr(cache, "tenant", None)
        evictions: list = []
        admitted = True
        with self._lock:
            self._prune()
            key = (id(cache), bi)
            if key in self._entries:
                self._drop(key)  # re-insert: refund, no eviction hook
            if budget and nbytes > budget:
                # refusal, not eviction: the shard was never resident,
                # so the eviction counter (LRU churn evidence) stays put
                return False
            if tenant is not None and t_budget and nbytes > t_budget:
                return False  # one shard over the whole tenant cap
            if tenant is not None and t_budget:
                # over-budget tenants evict their OWN LRU shards first
                # (round 19): other tenants' warm shards stay resident
                while (
                    admitted
                    and self.tenant_bytes.get(tenant, 0) + nbytes > t_budget
                ):
                    keys = self.tenant_keys.get(tenant)
                    vkey = self._lru_victim(keys or ())
                    if vkey is None:
                        # the tenant's remaining residency is all pinned
                        # pages (round 22): a further PINNED charge is a
                        # typed per-tenant admission refusal; an
                        # unpinned shard falls through to the global
                        # walk (accounting drift tolerance, as before)
                        admitted = not pinned
                        break
                    victim = self._drop(vkey)
                    if victim is not None:
                        evictions.append(victim)
            if admitted and budget:
                while self.total_bytes + nbytes > budget:
                    vkey = self._lru_victim(self._entries)
                    if vkey is None:
                        # nothing evictable is left.  Pinned charge:
                        # refuse instead of over-committing live decode
                        # memory (the caller surfaces retry_after_ms).
                        # Unpinned shard: keep the PR 5 semantics
                        # (insert once the walk is exhausted).
                        admitted = not pinned
                        break
                    victim = self._drop(vkey)
                    if victim is not None:
                        evictions.append(victim)
            if admitted:
                self._entries[key] = (
                    weakref.ref(cache), bi, nbytes, tenant, pinned
                )
                self.total_bytes += nbytes
                if tenant is not None:
                    self.tenant_bytes[tenant] = (
                        self.tenant_bytes.get(tenant, 0) + nbytes
                    )
                    self.tenant_keys.setdefault(
                        tenant, collections.OrderedDict()
                    )[key] = None
        # eviction hooks after the lock is released: a reader that races
        # in between sees either the still-resident shard (fine: shards
        # are immutable) or the evicted/spilled state.  Hooks run on the
        # refusal path too — their entries were already unaccounted, so
        # skipping them would leave resident shards the budget no longer
        # tracks.
        for victim, vbi in evictions:
            victim.evict(vbi)
            observability.note_cache_eviction()
        return admitted

    def touch(self, cache: FrameCache, bi: int) -> None:
        with self._lock:
            key = (id(cache), bi)
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                tenant = entry[3]
                if tenant is not None:
                    keys = self.tenant_keys.get(tenant)
                    if keys is not None and key in keys:
                        keys.move_to_end(key)

    def release(self, cache: FrameCache) -> None:
        with self._lock:
            for key in [
                k for k in self._entries if k[0] == id(cache)
            ]:
                self._drop(key)  # refund only: release() is not eviction


_budget = _HbmBudget()


def budget_bytes_resident() -> int:
    """Total bytes currently accounted by the LRU (test/bench surface;
    dead caches are pruned first so the number reflects live shards)."""
    with _budget._lock:
        _budget._prune()
    return _budget.total_bytes


def budget_bytes_by_tenant() -> Dict[str, int]:
    """Resident bytes per tenant (the ``TFS_CACHE_TENANT_BUDGET``
    accounting; un-tenanted caches are not listed)."""
    with _budget._lock:
        _budget._prune()
        return dict(_budget.tenant_bytes)


# ---------------------------------------------------------------------------
# host-column release for windowed frames (round 18)
# ---------------------------------------------------------------------------
#
# A windowed frame's host columns were, until this round, pinned for the
# frame object's whole lifetime even after a spill-backed sharded cache
# held every byte in HBM or on disk — defeating the HBM-resident path
# for epochs over windowed frames (the round-12 "known scope limit").
# ``release_host_columns`` swaps the cached columns' host arrays for a
# lazy stand-in that re-materialises block slices from the shard / spill
# copies on demand, so the frame stays fully usable (any verb, any
# fallback path) while its host bytes drop to zero.

ENV_RELEASE_HOST = "TFS_RELEASE_HOST"


def release_host_enabled() -> bool:
    """``TFS_RELEASE_HOST``: unset/``auto`` = release windowed frames'
    host columns once a spill-backed sharded cache covers them;
    ``0``/``off`` = keep the pre-round-18 pinning."""
    raw = envutil.env_raw(ENV_RELEASE_HOST, "auto").lower()
    return raw not in ("0", "off", "false", "no")


class SpillBackedColumnData:
    """Lazy host stand-in for a released windowed column: ``len`` /
    ``shape`` / ``dtype`` answer from metadata, slicing re-materialises
    exactly the covering blocks from the cache's shard or spill copies
    (``FrameCache.block_host``), and ``__array__`` rebuilds the whole
    column — so every host fallback path still works, it just pays a
    read instead of holding the bytes."""

    _tfs_released = True

    def __init__(self, cache: FrameCache, name: str, offsets, dtype,
                 cell_shape):
        self._cache = cache
        self._name = name
        self._offsets = tuple(int(o) for o in offsets)
        self.dtype = np.dtype(dtype)
        self._cell = tuple(int(d) for d in cell_shape)
        self._n = self._offsets[-1]

    @property
    def shape(self):
        return (self._n,) + self._cell

    @property
    def ndim(self) -> int:
        return 1 + len(self._cell)

    @property
    def nbytes(self) -> int:
        total = self._n * self.dtype.itemsize
        for d in self._cell:
            total *= d
        return total

    def __len__(self) -> int:
        return self._n

    def _materialize(self, start: int, stop: int) -> np.ndarray:
        if start >= stop:
            return np.empty((0,) + self._cell, self.dtype)
        offs = self._offsets
        parts = []
        for bi in range(len(offs) - 1):
            lo, hi = offs[bi], offs[bi + 1]
            if hi <= start or lo >= stop:
                continue
            block = self._cache.block_host(bi, self._name)
            parts.append(block[max(start - lo, 0):stop - lo])
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            start, stop, step = idx.indices(self._n)
            if step != 1:
                return self._materialize(0, self._n)[idx]
            return self._materialize(start, stop)
        if isinstance(idx, (int, np.integer)):
            i = int(idx)
            if i < 0:
                i += self._n
            return self._materialize(i, i + 1)[0]
        # fancy indexing and everything else: full materialisation
        return self._materialize(0, self._n)[idx]

    def __iter__(self):
        offs = self._offsets
        for bi in range(len(offs) - 1):
            if offs[bi + 1] > offs[bi]:
                yield from self._cache.block_host(bi, self._name)

    def __array__(self, dtype=None, copy=None):
        arr = self._materialize(0, self._n)
        return arr if dtype is None else arr.astype(dtype)

    def __repr__(self):
        return (
            f"SpillBackedColumnData[{self._name}: shape={self.shape}, "
            f"{self.dtype}]"
        )


def is_released(data) -> bool:
    """Whether ``data`` is a released-column stand-in."""
    return getattr(data, "_tfs_released", False)


def release_host_columns(frame) -> int:
    """Release ``frame``'s cached host column arrays: every cached
    block's bytes are guaranteed a durable home first (resident shards
    spill on eviction; never-resident blocks are spilled here), then
    each cached column's ``data`` becomes a :class:`SpillBackedColumnData`.
    Returns the host bytes released (0 when nothing was releasable).

    Requires a spill-backed sharded cache whose block count matches the
    frame — anything else leaves the frame untouched (host columns
    without a disk fallback must stay authoritative)."""
    cache = getattr(frame, "_cache", None)
    if (
        cache is None
        or cache.spill is None
        or len(cache.assignment) != frame.num_blocks
    ):
        return 0
    cached_names = None
    for shard in cache.blocks:
        if shard is not None:
            cached_names = set(shard)
            break
    if cached_names is None:
        # nothing resident: names come from the spill copies, or give up
        for bi in sorted(cache._spilled):
            host = cache.spill.get(cache._spill_key(bi))
            if host is not None:
                cached_names = set(host)
                break
    if not cached_names:
        return 0
    # durability first: a block that never fit the budget (insert
    # refused) has neither shard nor spill copy — write it now, from
    # the host bytes we are about to drop
    for bi in range(frame.num_blocks):
        if cache.blocks[bi] is None and bi not in cache._spilled:
            block = frame.block(bi)
            host = {
                n: np.asarray(block[n]) for n in sorted(cached_names)
            }
            cache.spill.put(cache._spill_key(bi), host)
            cache._spilled.add(bi)
    released = 0
    for col in frame.columns:
        name = col.info.name
        d = col.data
        if (
            name in cached_names
            and isinstance(d, np.ndarray)
            and d.dtype != object
        ):
            released += d.nbytes
            col.data = SpillBackedColumnData(
                cache, name, frame.offsets, d.dtype, d.shape[1:]
            )
    if released:
        observability.trace_instant(
            "release_host", "cache", bytes=released,
            blocks=frame.num_blocks,
        )
    return released


# ---------------------------------------------------------------------------
# frame attachment
# ---------------------------------------------------------------------------


def attach(frame, cache: Optional[FrameCache]):
    """Attach ``cache`` to ``frame`` (or detach with None); returns the
    frame.  The attribute lives on the frame object, not the columns, so
    derived frames (select/repartition/verb outputs) never inherit a
    stale shard layout — their offsets may no longer match."""
    frame._cache = cache
    return frame


def active_cache(frame) -> Optional[FrameCache]:
    """The frame's sharded cache when it is usable: attached, block
    count matching the frame's current partitioning, and at least one
    resident — or spill-restorable — shard.  Anything else (fully
    evicted with no spill, repartitioned-away) returns None and the
    host paths take over.  The spilled clause matters for windowed
    frames: a spill-backed cache whose every shard was evicted to disk
    must still dispatch through the affinity path, where ``shard()``
    restores blocks from ``TFS_SPILL_DIR`` — otherwise the spilled
    bytes would be unreachable dead weight."""
    cache = getattr(frame, "_cache", None)
    if cache is None:
        return None
    if len(cache.assignment) != frame.num_blocks:
        return None
    if cache.resident_blocks() == 0 and not cache._spilled:
        return None
    return cache


def build(
    frame,
    col_names: Sequence[str],
    devices: Optional[Sequence[Any]] = None,
    spill: Optional[Any] = None,
) -> Optional[FrameCache]:
    """Stage ``col_names``'s block slices onto their block-affinity
    devices and return the resulting cache (None when sharding cannot
    engage: < 2 devices or a 0-block frame).

    Placement reuses :func:`device_pool.assign` on the frame's block
    sizes — deterministic least-loaded, the SAME plan the pooled
    dispatch computes — so execution affinity is placement affinity.
    Transfers are async ``device_put`` calls issued back to back per
    device (the ``stage_columns`` policy, at block granularity) and are
    the one H2D cost a cached loop ever pays (counted in
    ``h2d_bytes_staged``).

    ``spill``: a disk store for evicted shards — passed by
    ``frame.cache()`` for windowed frames (no durable host authority;
    see :class:`FrameCache`)."""
    import jax

    if devices is None:
        devices = shard_devices(True)
    devices = list(devices)
    if (
        not col_names
        or len(devices) < 2
        or frame.num_blocks < 1
        or frame.num_rows == 0
    ):
        return None
    assignment = device_pool.assign(frame.block_sizes, len(devices))
    cache = FrameCache(devices, assignment, spill=spill)
    names = list(col_names)
    for bi in range(frame.num_blocks):
        block = frame.block(bi)
        dev = devices[assignment[bi]]
        shard = {}
        for name in names:
            arr = np.asarray(block[name])
            observability.note_h2d_bytes(arr.nbytes)
            shard[name] = jax.device_put(arr, dev)
        cache.insert(bi, shard)
    return cache


def adopt(
    frame,
    devices: Sequence[Any],
    assignment: Sequence[int],
    out_blocks: Sequence[Optional[Dict[str, Any]]],
) -> Optional[FrameCache]:
    """Adopt a pooled run's per-device OUTPUT buffers as ``frame``'s
    cached shards (donation-adoption): the buffers already live on their
    block's execution device, so the successor frame of an iterative
    chain is born sharded-cached — its next epoch reads HBM directly,
    zero re-staging.  The host columns assembled by the overlapped D2H
    readback remain the authoritative copy.  Returns the attached cache
    (budget-guarded per block), or None when nothing was adoptable."""
    if len(devices) < 2 or not out_blocks:
        return None
    cache = FrameCache(devices, list(assignment), adopted=True)
    adopted = 0
    for bi, outs in enumerate(out_blocks):
        if not outs:
            continue
        if cache.insert(bi, outs):
            adopted += 1
    if adopted == 0:
        return None
    attach(frame, cache)
    return cache
