"""tensorframes_tpu.analysis — static program analysis (round 17).

The reference's distinguishing subsystem is the *analysis* pass: columns
are annotated with tensor shapes and the graph is validated against the
schema **before** any executor runs (``TensorFlowOps.analyzeGraphTF``,
PAPER.md §0).  Rounds 1–16 inverted that: correctness properties were
discovered at dispatch time by compile probes, and contract violations
surfaced scattered and late (some only after a compile).  This package
closes the gap with three layers:

* :mod:`.rowdep` — **size-generic row-independence classification**: one
  abstract-interpretation pass over a program's jaxpr classifies every
  output as ``ROW_INDEPENDENT`` / ``CROSS_ROW`` / ``SIZE_DEPENDENT`` /
  ``UNKNOWN`` once per (program, input signature), so the five
  row-independence gates (engine streaming/bucketing/OOM-split, pipeline
  chain pads, planner chain pads, bridge coalescer, dist pad+mask)
  answer new size questions with ZERO probe traces.  The per-size
  compile probe (``segment_compile.cached_rows_independent``) remains
  the soundness oracle: verdict ``UNKNOWN`` falls back to it, and
  ``TFS_ANALYZE_XCHECK=1`` runs both and raises on any
  analyzer-says-independent / probe-disproves disagreement.
* :mod:`.contracts` — **pre-dispatch contract verification**:
  ``tfs.check(frame, program, verb)`` statically validates feeds /
  fetches / dtypes / ragged compatibility / reduce-monoid and
  decode-prelude constraints / GraphDef imports into structured
  diagnostics ``{code, severity, summary, location, advice}`` with
  stable ``TFSxxx`` codes (see ``docs/ANALYSIS.md``).  The bridge's
  ungated ``check`` RPC serves it remotely so tenants validate before
  burning admission budget.
* ``tools/tfs_lint.py`` — the **repo self-lint** enforcing the
  cross-cutting invariants this codebase promises (knob routing/pinning
  /docs, counter declaration, checkpoint coverage); wired as
  ``run_tests.sh lint``.

Import discipline: :mod:`.rowdep` is imported eagerly (the engine depends
on it); :mod:`.contracts` pulls the verb/builder layers, so ``check`` is
re-exported lazily to keep ``ops`` <-> ``analysis`` import order acyclic.
"""

from __future__ import annotations

from .rowdep import (  # noqa: F401
    CROSS_ROW,
    ROW_INDEPENDENT,
    SIZE_DEPENDENT,
    UNKNOWN,
    AnalysisXCheckError,
    Classification,
    classify,
    enabled,
    input_specs_for,
    rows_independent,
    xcheck_enabled,
)

__all__ = [
    "ROW_INDEPENDENT",
    "CROSS_ROW",
    "SIZE_DEPENDENT",
    "UNKNOWN",
    "AnalysisXCheckError",
    "Classification",
    "classify",
    "enabled",
    "xcheck_enabled",
    "rows_independent",
    "input_specs_for",
    "check",
    "check_relational",
    "Diagnostic",
    "CODES",
]


def check(*args, **kwargs):
    """Pre-dispatch contract verification — see
    :func:`tensorframes_tpu.analysis.contracts.check`.  Lazy so importing
    the analysis core (engine dependency) never drags the builder layer
    in and cycles the ``ops`` import."""
    from . import contracts

    return contracts.check(*args, **kwargs)


def check_relational(*args, **kwargs):
    """Relational (join/shuffle) contract verification — see
    :func:`tensorframes_tpu.analysis.contracts.check_relational`.  Lazy
    for the same ``ops`` import-order reason as :func:`check`."""
    from . import contracts

    return contracts.check_relational(*args, **kwargs)


def __getattr__(name):
    if name in ("Diagnostic", "CODES"):
        from . import contracts

        return getattr(contracts, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
