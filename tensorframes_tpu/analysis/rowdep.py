"""Size-generic row-independence classification (the analysis core).

Every fast path that reshapes a block's lead axis — bucketing's
pad-and-slice, chunked h2d streaming, the OOM block split, pipeline /
planner chain pads, the coalescer's merged dispatch, dist's pad+mask —
is gated on one question: *is this program row-independent?* (each output
row a function of the same input row only).  Rounds 4–16 answered it
with a compile probe, :func:`segment_compile.rows_independent_at`, posed
at the EXACT executed sizes — sound, but paid per (signature, sizes)
key: every new bucket signature re-traces the program at least twice.

This module answers it **once per (program, input signature)** with an
abstract-interpretation pass over the program's jaxpr: the program is
traced at the canonical probe sizes and each variable is propagated
through a small label lattice, batching-rule style::

    const < row < size < cross        (+ unresolved)

* ``const`` — derived from trace constants / params only;
* ``row``   — lead axis is the row axis and every row depends only on
  the same row of the inputs (the probe's "row" class);
* ``size``  — the VALUE tracks the block size (a count literal family
  like ``mean``'s ``/n``, or an n-tracking parameter on a non-shape
  primitive): padding would change semantics at any size;
* ``cross`` — rows mix (a block-axis reduction, a primitive outside the
  row-independence whitelist, a constant broadcast onto the row axis —
  everything the probe structurally rejects).

Each program *output* classifies as :data:`ROW_INDEPENDENT`,
:data:`CROSS_ROW`, :data:`SIZE_DEPENDENT` or :data:`UNKNOWN`; the
program-level verdict is the meet (a single non-independent output, or
any whitelist violation anywhere in the jaxpr — mirroring the probe's
global strictness — makes the program non-independent).

Soundness contract: a verdict other than ``UNKNOWN`` is only issued when
the same answer is *forced* for every size set the probe could be posed
at — definitive negatives come from size-monotone evidence (whitelist
membership is size-independent; count families and n-tracking params
are strictly monotone in n, so no two distinct sizes can make them
coincide), and ``ROW_INDEPENDENT`` replicates the probe's acceptance
conditions at the canonical probes.  Anything ambiguous (structure that
varies across the probes — python control flow branching on the block
size — unresolvable literals, non-monotone shape classes) is
``UNKNOWN`` and falls back to the per-size probe, which stays the
soundness oracle.  Residual envelope, shared with the segment
recognizer's ``_PROBES`` (segment_compile.py): a program whose python
control flow branches only beyond the largest canonical probe (97) is
outside the classifier's view; ``TFS_ANALYZE_XCHECK=1`` runs classifier
AND probe on every question and raises :class:`AnalysisXCheckError` on
any analyzer-says-independent / probe-disproves disagreement, which is
the differential fence ``run_tests.sh lint`` drives over the corpus.

Knobs (``docs/ANALYSIS.md``): ``TFS_ANALYZE`` (unset/``auto``/``1`` =
on, ``0``/``off`` = every question probes as before) and
``TFS_ANALYZE_XCHECK`` (differential mode).  Evidence counters:
``analysis_static_hits`` / ``analysis_probe_fallbacks``.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes, envutil, observability
from ..ops import segment_compile
from ..ops.segment_compile import (
    _Bail,
    _ELEMENTWISE,
    _REDUCE_KINDS,
    _SHAPEY,
    _fit_family,
    _match_param,
    _trace,
)

logger = logging.getLogger("tensorframes_tpu.analysis")

# program-output verdicts (the public classification alphabet)
ROW_INDEPENDENT = "ROW_INDEPENDENT"
CROSS_ROW = "CROSS_ROW"
SIZE_DEPENDENT = "SIZE_DEPENDENT"
UNKNOWN = "UNKNOWN"

ENV_ANALYZE = "TFS_ANALYZE"
ENV_XCHECK = "TFS_ANALYZE_XCHECK"

# canonical classification probes: 2+3+5 pin the row/cell dims and the
# count-literal families, 97 catches python control flow branching on
# the block size at small thresholds — the same envelope (and the same
# residual assumption) as the segment recognizer's _PROBES
_ANALYZE_PROBES = (2, 3, 5, 97)

_OFF_TOKENS = ("0", "off", "false", "no", "none")
_TRUTHY = ("1", "true", "yes", "on")

# internal label lattice (join = max rank); None = unresolved
_RANK = {"const": 0, "row": 1, "size": 2, "cross": 3}


class AnalysisXCheckError(AssertionError):
    """Differential mode caught the classifier claiming ROW_INDEPENDENT
    where the exact-size compile probe disproves it — an analyzer bug
    (or a program outside the documented probe envelope); file the
    jaxpr, do not ship the classification."""


def enabled() -> bool:
    """Whether the static classifier answers row-independence questions
    (``TFS_ANALYZE``; on unless explicitly disabled).  Read per call:
    bench A/B legs and tests flip it mid-process."""
    return envutil.env_raw(ENV_ANALYZE).lower() not in _OFF_TOKENS


def xcheck_enabled() -> bool:
    """Whether every classifier answer is differentially checked against
    the compile probe (``TFS_ANALYZE_XCHECK=1``)."""
    return envutil.env_raw(ENV_XCHECK).lower() in _TRUTHY


@dataclasses.dataclass(frozen=True)
class Classification:
    """One program's size-generic row-dependence classification.

    ``outputs``: per-output verdict; ``verdict``: the program-level meet
    the dispatch gates consume; ``reason``: the first decisive evidence
    (human-facing, stable enough for ``tfs.check`` advice strings)."""

    verdict: str
    outputs: Dict[str, str]
    reason: str
    probes: Tuple[int, ...] = _ANALYZE_PROBES

    @property
    def independent(self) -> bool:
        return self.verdict == ROW_INDEPENDENT


def _cell_sig(input_specs: Mapping[str, Any]) -> Tuple:
    return tuple(
        sorted(
            (n, tuple(s.shape[1:]), str(s.dtype))
            for n, s in input_specs.items()
        )
    )


def input_specs_for(
    program, columns: Mapping[str, Any]
) -> Optional[Dict[str, jax.ShapeDtypeStruct]]:
    """The one shared builder of the probe/classifier input-spec dict
    the five row-independence gates used to hand-roll: program input
    name -> ``ShapeDtypeStruct((2,) + cell, dtype)``.

    ``columns`` maps each program input name to its schema
    ``ColumnInfo``, an ``(array_like, dtype)`` pair (the pipeline's
    layout form), or an existing ``ShapeDtypeStruct``.  Returns ``None``
    when any input has no entry, a non-device scalar type, or a cell
    shape that is not statically known (ragged / un-analyzed) — the
    callers' "cannot even pose the proof" early-out."""
    specs: Dict[str, jax.ShapeDtypeStruct] = {}
    for name in program.input_names:
        src = columns.get(name)
        if src is None:
            return None
        if isinstance(src, jax.ShapeDtypeStruct):
            cell = tuple(src.shape[1:])
            np_dtype = src.dtype
        elif hasattr(src, "cell_shape"):  # schema.ColumnInfo
            if not src.scalar_type.device_ok:
                return None
            cell = tuple(src.cell_shape)
            np_dtype = dtypes.coerce(src.scalar_type).np_dtype
        else:  # (array_like, dtype) layout pair
            data, dt = src
            cell = tuple(np.shape(data))[1:]
            np_dtype = np.dtype(dt)
        if any(d is None or d < 0 for d in cell):
            return None
        specs[name] = jax.ShapeDtypeStruct((2,) + cell, np_dtype)
    return specs


def classify(program, input_specs: Mapping[str, Any]) -> Classification:
    """Classify ``program``'s outputs once per (program, cell
    signature); memoized on ``program._derived`` so every later
    row-independence question — at ANY size set — is a dict lookup.

    ``input_specs``: program input name -> ShapeDtypeStruct whose lead
    dim is a placeholder (the classifier re-poses the cell shapes at its
    own canonical probe sizes)."""
    key = ("analysis", _cell_sig(input_specs))
    cache = program._derived
    if key not in cache:
        cache[key] = _classify(program, input_specs)
    return cache[key]


def _classify(program, input_specs) -> Classification:
    sizes = _ANALYZE_PROBES
    names = sorted(input_specs)
    cells = {
        nm: (tuple(s.shape[1:]), s.dtype) for nm, s in input_specs.items()
    }
    try:
        param_specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                jnp.shape(a), jnp.asarray(a).dtype
            ),
            program.params,
        )
        traces = []
        for n in sizes:
            specs = {
                nm: jax.ShapeDtypeStruct((n,) + cell, dt)
                for nm, (cell, dt) in cells.items()
            }
            traces.append(_trace(program, specs, param_specs))
    except _Bail:
        return _unknown({}, "jaxpr shape not analyzable (literal outputs "
                            "or call-boundary literals)")
    except Exception as e:  # noqa: BLE001 — tracing user code
        envutil.warn_once(
            logger,
            f"analysis:trace:{type(e).__name__}",
            "analysis: classification trace failed (%s: %s); programs "
            "of this shape fall back to the per-size compile probe",
            type(e).__name__,
            e,
        )
        return _unknown({}, f"trace failed: {type(e).__name__}: {e}")
    try:
        return _interpret(program, traces, names, sizes)
    except _Bail:
        return _unknown({}, "jaxpr structure not analyzable")
    except Exception as e:  # noqa: BLE001 — classify() must stay total:
        # the five dispatch gates call it bare where the old probe gate
        # swallowed everything; a latent lattice bug must degrade to the
        # probe fallback, not crash a verb (or the OOM-split recovery)
        envutil.warn_once(
            logger,
            f"analysis:interpret:{type(e).__name__}",
            "analysis: lattice interpretation failed for program %r "
            "(%s: %s); falling back to the per-size compile probe — "
            "likely an analyzer bug, please report the jaxpr",
            getattr(program, "name", "?"),
            type(e).__name__,
            e,
        )
        return _unknown({}, f"interpretation failed: {type(e).__name__}: {e}")


def _unknown(outputs: Dict[str, str], reason: str) -> Classification:
    return Classification(UNKNOWN, dict(outputs), reason)


def _interpret(program, traces, names, sizes) -> Classification:
    t0 = traces[0]
    out_names = sorted(t0["out_shape"])
    all_unknown = {nm: UNKNOWN for nm in out_names}

    # ---- structural identity across the canonical probes -------------------
    for t in traces[1:]:
        if (
            len(t["eqns"]) != len(t0["eqns"])
            or t["outs"] != t0["outs"]
            or len(t["consts"]) != len(t0["consts"])
            or len(t["lits"]) != len(t0["lits"])
        ):
            return _unknown(
                all_unknown,
                "trace structure varies with the block size (python "
                "control flow branches on the row count)",
            )
        for (i0, c0), (i, c) in zip(t0["consts"], t["consts"]):
            if i0 != i or not np.array_equal(np.asarray(c0), np.asarray(c)):
                return _unknown(
                    all_unknown, "captured constants vary with the block size"
                )

    # ---- literal classification --------------------------------------------
    # slot -> "const" | "size" | None (unresolved)
    lit_label: List[Optional[str]] = []
    problems: List[Tuple[str, str]] = []  # ("size"|"cross"|"unknown", why)
    for slot in range(len(t0["lits"])):
        vals = [np.asarray(t["lits"][slot]) for t in traces]
        v0 = vals[0]
        if all(
            v.shape == v0.shape and np.array_equal(v0, v) for v in vals[1:]
        ):
            lit_label.append("const")
        elif all(v.ndim == 0 for v in vals) and _fit_family(
            [v[()] for v in vals], sizes
        ):
            # strictly monotone count family (k*n, k/n, k*(n-1), k/(n-1)):
            # no two distinct sizes coincide, so the probe rejects at any
            # size set too — a definitive SIZE_DEPENDENT
            lit_label.append("size")
            problems.append(
                ("size", "a literal tracks the block row count (count "
                         "family, e.g. mean's /n)")
            )
        else:
            lit_label.append(None)
            problems.append(
                ("unknown", "a literal varies with the block size outside "
                            "the monotone count families")
            )

    # ---- per-var shape class (row vs group), across all probes -------------
    all_shapes = [t["shapes"] for t in traces]

    def var_class(i: int) -> Optional[str]:
        ss = [sh[i] for sh in all_shapes]
        if not all(len(s) == len(ss[0]) for s in ss[1:]):
            return None
        n_dims = []
        for d in range(len(ss[0])):
            dims = tuple(s[d] for s in ss)
            if all(x == dims[0] for x in dims[1:]):
                continue
            if dims == sizes:
                n_dims.append(d)
            else:
                return None  # non-monotone / non-lead size tracking
        if not n_dims:
            return "group"
        if n_dims == [0]:
            return "row"
        return None

    # ---- label propagation --------------------------------------------------
    labels: Dict[int, Optional[str]] = {}
    kw_leaf_count = len(names)
    for i in range(t0["n_invars"]):
        labels[i] = "row" if i < kw_leaf_count else "const"
    for i, _c in t0["consts"]:
        labels[i] = "const"
        if var_class(i) != "group":
            problems.append(
                ("unknown", "a captured constant carries a row-sized axis")
            )
            labels[i] = None

    def join(ls: Sequence[Optional[str]]) -> Optional[str]:
        out = "const"
        for l in ls:
            if l is None:
                return None
            if _RANK[l] > _RANK[out]:
                out = l
        return out

    for ei, e0 in enumerate(t0["eqns"]):
        ealigned = [t["eqns"][ei] for t in traces]
        name = e0.prim.name
        if any(
            e.prim.name != name
            or e.invals != e0.invals
            or e.outvars != e0.outvars
            for e in ealigned[1:]
        ):
            return _unknown(
                all_unknown,
                "trace structure varies with the block size (python "
                "control flow branches on the row count)",
            )
        keys = sorted(e0.params)
        if any(sorted(e.params) != keys for e in ealigned[1:]):
            return _unknown(all_unknown, "equation parameters vary in kind "
                                         "with the block size")
        tracks = False
        unresolved_param = False
        for k in keys:
            vals = [e.params[k] for e in ealigned]
            try:
                _t, tk = _match_param(vals, sizes)
            except _Bail:
                if not all(v is None for v in vals):
                    unresolved_param = True
                tk = False
            tracks = tracks or tk

        in_labels = [
            lit_label[iv[1]] if isinstance(iv, tuple) else labels.get(iv)
            for iv in e0.invals
        ]
        lbl = join(in_labels)
        whitelisted = (
            name in _ELEMENTWISE or name in _SHAPEY or name in _REDUCE_KINDS
        )
        if unresolved_param:
            problems.append(
                ("unknown", f"{name}: a parameter varies with the block "
                            f"size outside the monotone forms")
            )
            lbl = None
        if lbl is not None:
            if tracks and name not in _SHAPEY:
                # an n-tracking VALUE parameter (e.g. integer_pow y=n):
                # strictly monotone, so definitive at every size set
                problems.append(
                    ("size", f"{name}: a parameter tracks the block row "
                             f"count")
                )
                lbl = "size" if _RANK[lbl] < _RANK["size"] else lbl
            if not whitelisted:
                # outside the probe's whitelist — the probe rejects this
                # structurally at EVERY size set (whitelist membership
                # does not depend on n), so a definitive negative
                problems.append(
                    ("cross", f"{name}: primitive outside the "
                              f"row-independence whitelist")
                )
                lbl = "cross"
            elif name in _REDUCE_KINDS and lbl == "row":
                axes = e0.params.get("axes", ())
                if 0 in axes:
                    problems.append(
                        ("cross", f"{name}: reduction over the block axis")
                    )
                    lbl = "cross"
            elif name == "rev" and lbl == "row" and 0 in e0.params.get(
                "dimensions", ()
            ):
                # row-axis reversal: row-shaped but position-dependent
                # (the round-17 probe soundness fix, mirrored)
                problems.append(
                    ("cross", "rev: reversal along the block axis")
                )
                lbl = "cross"
        out_classes = [var_class(ov) for ov in e0.outvars]
        for ov, oc in zip(e0.outvars, out_classes):
            vlbl = lbl
            if vlbl is not None and oc is None:
                problems.append(
                    ("unknown", f"{name}: output shape class unresolved")
                )
                vlbl = None
            elif vlbl == "row" and oc != "row":
                # a row value whose output lost the row axis (the probe's
                # out-class check rejects this at every size set)
                problems.append(
                    ("cross", f"{name}: row operand, non-row output")
                )
                vlbl = "cross"
            elif vlbl == "const" and oc == "row":
                # a group-side value broadcast onto the row axis (e.g.
                # zeros_like): every row equal, but structurally outside
                # the probe's acceptance — definitive, the broadcast
                # shape tracks n monotonically at every size set
                problems.append(
                    ("cross", f"{name}: group value broadcast onto the "
                              f"row axis")
                )
                vlbl = "cross"
            labels[ov] = vlbl

    # ---- per-output verdicts -----------------------------------------------
    out_ids = t0["outs"]
    outputs: Dict[str, str] = {}
    for nm, ov in zip(out_names, out_ids):
        lbl = labels.get(ov)
        cls = var_class(ov)
        if lbl is None or cls is None:
            outputs[nm] = UNKNOWN
        elif lbl == "cross":
            outputs[nm] = CROSS_ROW
        elif lbl == "size":
            outputs[nm] = SIZE_DEPENDENT
        elif lbl == "row" and cls == "row":
            outputs[nm] = ROW_INDEPENDENT
        else:  # const output (no row axis): not row-preserving
            outputs[nm] = CROSS_ROW

    # ---- program verdict (the probe's global strictness) -------------------
    cross = next((why for kind, why in problems if kind == "cross"), None)
    size = next((why for kind, why in problems if kind == "size"), None)
    unknown = next(
        (why for kind, why in problems if kind == "unknown"), None
    )
    if cross is None and any(v == CROSS_ROW for v in outputs.values()):
        cross = "output is not row-preserving"
    if size is None and any(
        v == SIZE_DEPENDENT for v in outputs.values()
    ):
        size = "output value depends on the block size"
    if cross is not None:
        return Classification(CROSS_ROW, outputs, cross)
    if size is not None:
        return Classification(SIZE_DEPENDENT, outputs, size)
    if unknown is not None or any(
        v != ROW_INDEPENDENT for v in outputs.values()
    ):
        return Classification(
            UNKNOWN, outputs, unknown or "unresolved output class"
        )
    return Classification(
        ROW_INDEPENDENT, outputs,
        "every equation row-preserving at every canonical probe",
    )


def rows_independent(
    program, input_specs: Mapping[str, Any], sizes: Sequence[int]
) -> bool:
    """The shared row-independence gate: answer from the memoized static
    classification when it is decisive (zero traces after the one-time
    classification), fall back to the exact-size compile probe
    (``segment_compile.cached_rows_independent``) on ``UNKNOWN`` — and,
    under ``TFS_ANALYZE_XCHECK=1``, run BOTH and raise
    :class:`AnalysisXCheckError` on an unsound disagreement."""
    if not enabled():
        return segment_compile.cached_rows_independent(
            program, input_specs, sizes
        )
    cls = classify(program, input_specs)
    if cls.verdict == UNKNOWN:
        observability.note_analysis_probe_fallback()
        return segment_compile.cached_rows_independent(
            program, input_specs, sizes
        )
    observability.note_analysis_static_hit()
    answer = cls.verdict == ROW_INDEPENDENT
    if xcheck_enabled():
        probed = segment_compile.cached_rows_independent(
            program, input_specs, sizes
        )
        if answer and not probed:
            raise AnalysisXCheckError(
                f"analysis xcheck: classifier says ROW_INDEPENDENT but "
                f"the compile probe disproves it at sizes "
                f"{tuple(sizes)} (outputs {cls.outputs}; reason: "
                f"{cls.reason}) — file the program's jaxpr"
            )
        if probed and not answer:
            # conservative-direction disagreement: sound (the slow path
            # runs), but worth one log line — it means a fast path the
            # probe would grant is being left on the table
            envutil.warn_once(
                logger,
                f"analysis:conservative:{cls.verdict}:{cls.reason}",
                "analysis xcheck: classifier verdict %s (%s) where the "
                "probe proves independence at %s; the exact path still "
                "runs, but the classification is over-conservative",
                cls.verdict,
                cls.reason,
                tuple(sizes),
            )
    return answer
