"""``tfs.check`` — pre-dispatch contract verification (round 17).

The reference validates graph-vs-schema compatibility *before* any
executor runs and treats the error-message quality as half the product
(``DebugRowOps.scala:53-275``, SURVEY.md §7).  Our dispatch path has the
same checks (``ops/validation.py``, the GraphDef importer, shape-hint
refinement) but they fail scattered and late — some only after a trace
or a compile, and over the bridge only after an admission slot was
burnt.  ``check(frame, program, verb)`` runs them ALL statically and
returns structured diagnostics instead of raising at the first one::

    [Diagnostic(code="TFS103", severity="error",
                summary="map_blocks: program input 'x' requests ...",
                location="map_blocks:input:x",
                advice="pass feed_dict={input: column} ..."), ...]

Codes are stable (``TFSxxx``, table in ``docs/ANALYSIS.md``) and the
SAME codes ride on the dispatch-time exceptions (``ValidationError.code``,
``GraphImportError.code``), so a front-end can branch on the code
whether it validated early or failed late.  Severities: ``error`` (the
verb WILL refuse at dispatch), ``warn`` (dispatch proceeds but a
documented contract is at risk), ``info`` (performance-relevant facts —
e.g. the row-dependence classification that decides whether bucketing /
coalescing fast paths can engage).

The bridge serves this as the ungated ``check`` RPC (``bridge/server``):
a tenant validates a program against a registered frame without paying
admission, idempotency, or compile costs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

from . import rowdep

# the stable diagnostic registry: code -> (title, default severity).
# NEVER renumber — codes are a wire contract (bridge check RPC) and ride
# on dispatch-time exceptions; add new codes at the end of each band.
# Bands: TFS10x program/schema contracts, TFS11x trace-time, TFS12x
# GraphDef import, TFS13x analysis facts (info).
CODES: Dict[str, tuple] = {
    "TFS101": ("unknown verb", "error"),
    "TFS102": ("program construction failed", "error"),
    "TFS103": ("input names a missing column", "error"),
    "TFS104": ("host-only column fed to a device program", "error"),
    "TFS105": ("un-analyzed / ragged cell shape for a block verb",
               "error"),
    "TFS106": ("reduce_rows pairwise naming contract violated", "error"),
    "TFS107": ("reduce pair halves feed different columns", "error"),
    "TFS108": ("reduce_blocks/aggregate _input naming contract violated",
               "error"),
    "TFS109": ("reduce output does not match the column cell contract",
               "error"),
    "TFS110": ("shape hint contradicts the inferred shape", "error"),
    "TFS111": ("program failed to trace", "error"),
    "TFS112": ("host_stage names a non-input", "error"),
    "TFS120": ("GraphDef op has no lowering", "error"),
    "TFS121": ("GraphDef decode-prelude contract violated", "error"),
    "TFS122": ("GraphDef output shape not describable", "error"),
    "TFS123": ("GraphDef structurally invalid", "error"),
    "TFS130": ("program is not row-independent", "info"),
    "TFS131": ("row-dependence unknown (dispatch will probe)", "info"),
    # TFS14x: relational contracts (round 18, tensorframes_tpu/relational/)
    "TFS140": ("shuffle/join key column missing or duplicated", "error"),
    "TFS141": ("join key columns have mismatched dtypes", "error"),
    "TFS142": ("shuffle/join key cells are ragged / non-hashable",
               "error"),
    "TFS143": ("join output column name collision", "error"),
}

_SEV_RANK = {"error": 0, "warn": 1, "info": 2}

_VERBS = (
    "map_blocks", "map_blocks_trimmed", "map_rows", "reduce_blocks",
    "reduce_rows", "aggregate",
)


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One structured finding: stable ``code``, ``severity`` in
    ``error``/``warn``/``info``, human ``summary``, a ``location`` path
    (``verb:input:x``, ``program``, ``graphdef``), and ``advice`` — the
    "what to do" half the reference's error messages carry."""

    code: str
    severity: str
    summary: str
    location: str
    advice: str

    def as_dict(self) -> Dict[str, str]:
        return dataclasses.asdict(self)


def _diag(code: str, summary: str, location: str, advice: str,
          severity: Optional[str] = None) -> Diagnostic:
    sev = severity or CODES[code][1]
    return Diagnostic(code, sev, summary, location, advice)


def _from_exception(e: BaseException, default_code: str, location: str,
                    advice: str = "") -> Diagnostic:
    code = getattr(e, "code", None) or default_code
    if code not in CODES:
        code = default_code
    return _diag(code, str(e), location, advice)


def check_relational(
    frame,
    verb: str,
    keys: Optional[Sequence[str]] = None,
    right=None,
    how: str = "inner",
) -> List[Diagnostic]:
    """Relational contract verification (round 18): the ``TFS14x``
    checks for ``verb`` in ``shuffle``/``join`` — key presence,
    duplication, scalar/hashable cells, cross-side dtype match, and
    output-name collisions — statically, against the schemas alone.
    Worst-first, like :func:`check`; the same codes ride the
    dispatch-time ``ValidationError`` the verbs raise."""
    diags: List[Diagnostic] = []
    keys = list(keys or ())
    loc = f"{verb}:key"
    if not keys:
        return [_diag(
            "TFS140", f"{verb} needs a key column", loc,
            "pass on=<column> (join) / key=<column> (shuffle)",
        )]
    if len(keys) > len(set(keys)):
        diags.append(_diag(
            "TFS140",
            f"{verb}: key columns {keys} name a column more than once",
            loc, "each key column may appear once",
        ))
    if len(set(keys)) > 1:
        diags.append(_diag(
            "TFS140",
            f"{verb}: multi-column keys are not supported yet "
            f"({keys}); combine the columns into one key first",
            loc, "re-key on a single column",
        ))
    key = keys[0]

    def _side(f, side: str):
        schema = f.schema
        if key not in schema:
            diags.append(_diag(
                "TFS140",
                f"{verb}: key column {key!r} does not exist on the "
                f"{side} side. Available columns: {schema.names}",
                f"{loc}:{side}",
                "the key must name an existing column on both sides",
            ))
            return None
        ci = schema[key]
        if ci.cell_shape.rank != 0:
            diags.append(_diag(
                "TFS142",
                f"{verb}: {side} key column {key!r} holds cells of "
                f"shape {ci.cell_shape}; keys must be scalar",
                f"{loc}:{side}",
                "hash-partitioning needs one hashable cell per row",
            ))
        col = f.column(key)
        if col.is_ragged and not isinstance(col.data, np.ndarray):
            diags.append(_diag(
                "TFS142",
                f"{verb}: {side} key column {key!r} holds ragged "
                f"cells; analyze/bucket the frame first",
                f"{loc}:{side}",
                "ragged cells have no stable byte representation to "
                "hash",
            ))
        return ci

    lci = _side(frame, "left")
    if verb == "join" and right is not None:
        rci = _side(right, "right")
        if lci is not None and rci is not None and (
            lci.scalar_type.name != rci.scalar_type.name
        ):
            diags.append(_diag(
                "TFS141",
                f"join: key column {key!r} has dtype "
                f"{lci.scalar_type.name} on the left and "
                f"{rci.scalar_type.name} on the right",
                loc,
                "byte-equality joins need one representation; cast "
                "one side first",
            ))
        collide = sorted(
            (set(frame.column_names) & set(right.column_names)) - {key}
        )
        if collide:
            diags.append(_diag(
                "TFS143",
                f"join: non-key column name(s) {collide} exist on "
                f"both sides",
                f"{verb}:columns",
                "rename or drop one side's columns before joining",
            ))
    diags.sort(key=lambda d: (_SEV_RANK[d.severity], d.code))
    return diags


def check(
    frame,
    program,
    verb: str,
    host_stage: Optional[Mapping[str, Any]] = None,
    fetches: Optional[Sequence[str]] = None,
    inputs: Optional[Mapping[str, str]] = None,
    shapes: Optional[Mapping[str, Sequence[int]]] = None,
    outputs: Optional[Mapping[str, str]] = None,
    keys: Optional[Sequence[str]] = None,
    right=None,
    how: str = "inner",
) -> List[Diagnostic]:
    """Statically verify ``program`` against ``frame``'s schema for
    ``verb``; returns diagnostics sorted worst-first (empty = the
    dispatch-time validation layer will accept it).

    ``program`` accepts everything the verbs accept: a python function,
    DSL nodes, an existing :class:`Program`, or frozen GraphDef bytes
    (with ``fetches``/``inputs``/``shapes``/``outputs`` — the OpBuilder
    surface).  ``keys``: the grouping columns for ``aggregate``.
    Nothing is compiled and nothing dispatches: the only traces are
    ``eval_shape`` (no FLOPs) and the one-time row-dependence
    classification, both excluded from the retrace counters."""
    if verb in ("join", "shuffle"):
        # relational verbs carry no program: the TFS14x key contracts
        # are the whole static surface (round 18)
        return check_relational(frame, verb, keys, right=right, how=how)
    diags: List[Diagnostic] = []
    if verb not in _VERBS:
        return [_diag(
            "TFS101",
            f"unknown verb {verb!r}",
            "verb",
            f"one of {', '.join(_VERBS)} (or the relational verbs "
            f"join/shuffle)",
        )]

    # ---- program construction (GraphDef import included) -------------------
    from ..builder import compile_program  # lazy: builder pulls the engine
    from ..graphdef.importer import GraphImportError
    from ..graphdef.ops import UnsupportedOpError
    from ..program import Program, ProgramError

    if not isinstance(program, Program) or fetches or inputs or shapes:
        try:
            program = compile_program(
                program, fetches=fetches, inputs=inputs, shapes=shapes,
                outputs=outputs, what=f"check({verb})",
            )
        except UnsupportedOpError as e:
            return diags + [_from_exception(
                e, "TFS120", "graphdef",
                "register a lowering in graphdef/ops.py, or export the "
                "graph without this op",
            )]
        except GraphImportError as e:
            return diags + [_from_exception(
                e, "TFS123", "graphdef",
                "fix the GraphDef (the importer validates fetches, "
                "placeholders, decode preludes, and acyclicity)",
            )]
        except ProgramError as e:
            return diags + [_from_exception(
                e, "TFS102", "program",
                "programs declare named inputs and named fetches; see "
                "Program.wrap",
            )]
        except Exception as e:  # noqa: BLE001 — user construction code
            return diags + [_from_exception(e, "TFS102", "program", "")]

    trim = verb == "map_blocks_trimmed"
    base_verb = "map_blocks" if trim else verb

    from ..ops import validation
    from .. import dtypes
    from ..shape import UNKNOWN, Shape

    staged = set(host_stage or ()) | set(
        getattr(program, "host_prelude", {}) or {}
    )

    # ---- schema contracts ---------------------------------------------------
    infos: Dict[str, Any] = {}
    if base_verb in ("map_blocks", "map_rows"):
        unknown_staged = sorted(
            set(host_stage or ()) - set(program.input_names)
        )
        if unknown_staged:
            diags.append(_diag(
                "TFS112",
                f"{base_verb}: host_stage given for names "
                f"{unknown_staged} that are not program inputs; inputs "
                f"are {program.input_names}",
                f"{verb}:host_stage",
                "host_stage keys must name program inputs",
            ))
        for n in program.input_names:
            try:
                infos[n] = validation._column_for_input(
                    frame, program, n, base_verb,
                    host_staged=n in staged,
                    allow_ragged=base_verb == "map_rows",
                )
            except validation.ValidationError as e:
                diags.append(_from_exception(
                    e, "TFS103", f"{verb}:input:{n}",
                    "match program inputs to frame columns by name, or "
                    "pass feed_dict={input: column}",
                ))
    else:
        try:
            if base_verb == "reduce_rows":
                infos = validation.check_reduce_rows(program, frame)
            else:
                infos = validation.check_reduce_blocks(
                    program, frame, verb=base_verb
                )
        except validation.ValidationError as e:
            diags.append(_from_exception(
                e, "TFS108" if base_verb != "reduce_rows" else "TFS106",
                f"{verb}:inputs",
                "reduce_rows consumes '<col>_1'/'<col>_2' pairs; "
                "reduce_blocks/aggregate consume '<col>_input' blocks",
            ))
    if base_verb == "aggregate":
        schema = frame.schema
        for k in keys or ():
            if k not in schema:
                diags.append(_diag(
                    "TFS103",
                    f"aggregate: grouping key {k!r} does not exist in "
                    f"the frame. Available columns: {schema.names}",
                    f"{verb}:key:{k}",
                    "group_by keys must name frame columns",
                ))

    if any(d.severity == "error" for d in diags):
        diags.sort(key=lambda d: (_SEV_RANK[d.severity], d.code))
        return diags

    # ---- trace-time contracts (eval_shape; no FLOPs, no compile) -----------
    specs: Dict[str, Any] = {}
    for n in program.input_names:
        if base_verb in ("map_blocks", "map_rows"):
            ci = infos.get(n)
        else:  # reduce verbs: infos keyed by output base name
            base = n[: -len("_input")] if n.endswith("_input") else n[:-2]
            ci = infos.get(base)
        if ci is None or n in staged:
            specs = {}
            break  # host-staged cell shapes are only known at run time
        cell = tuple(ci.cell_shape)
        if base_verb == "map_rows" and any(d == UNKNOWN for d in cell):
            specs = {}
            break  # ragged map_rows resolves per row-bucket at run time
        if base_verb in ("map_blocks", "reduce_blocks", "aggregate"):
            shape = Shape((UNKNOWN,) + cell)
        elif base_verb == "reduce_rows":
            shape = Shape(cell)
        else:  # map_rows: the cell program
            shape = Shape(cell)
        specs[n] = (ci.scalar_type, shape)
    summaries = None
    if specs:
        try:
            summaries = program.analyze(specs)
        except Exception as e:  # noqa: BLE001 — user program under trace
            msg = str(e)
            code = "TFS110" if "hint" in msg else "TFS111"
            diags.append(_from_exception(
                e, code, "program",
                "the program must trace at the schema's shapes/dtypes "
                "before any verb can run it" if code == "TFS111" else
                "shape hints refine unknown dims; they may never "
                "contradict inferred shapes",
            ))
    if summaries is not None and base_verb in (
        "reduce_rows", "reduce_blocks", "aggregate"
    ):
        try:
            if base_verb == "reduce_rows":
                validation.check_reduce_rows_outputs(infos, summaries)
            else:
                validation.check_reduce_blocks_outputs(
                    infos, summaries, verb=base_verb
                )
        except validation.ValidationError as e:
            diags.append(_from_exception(
                e, "TFS109", f"{verb}:outputs",
                "a reducer's outputs must exactly match the reduced "
                "columns and preserve their cell shapes, so the "
                "reduction can be re-applied across blocks",
            ))

    # ---- row-dependence classification (info) ------------------------------
    if (
        base_verb == "map_blocks"
        and not trim
        and not staged
        and not any(d.severity == "error" for d in diags)
    ):
        cls_specs = rowdep.input_specs_for(program, infos)
        if cls_specs is not None:
            cls = rowdep.classify(program, cls_specs)
            if cls.verdict == rowdep.UNKNOWN:
                diags.append(_diag(
                    "TFS131",
                    f"row-dependence not statically classifiable "
                    f"({cls.reason}); dispatch will prove it per size "
                    f"with the compile probe",
                    f"{verb}:program",
                    "size-branching python control flow defeats the "
                    "static classifier; the per-size probe stays sound",
                ))
            elif cls.verdict != rowdep.ROW_INDEPENDENT:
                diags.append(_diag(
                    "TFS130",
                    f"program is {cls.verdict} ({cls.reason}); "
                    f"per-output: {cls.outputs}",
                    f"{verb}:program",
                    "cross-row / size-dependent programs keep exact "
                    "per-size executables: bucket padding, chunked h2d "
                    "streaming, OOM splitting, and bridge coalescing "
                    "are all disabled for them",
                ))

    diags.sort(key=lambda d: (_SEV_RANK[d.severity], d.code))
    return diags
