"""Frame -> training-batch ingestion: the data plane feeding the training
stack.

The reference's defining property is that the DataFrame feeds every tensor
program; its demos iterate Spark partitions into each step
(``kmeans_demo.py:208-255``).  The TPU-native equivalent is a loader that
turns a :class:`~.frame.TensorFrame` into a stream of device-resident,
mesh-sharded batches:

* columns are staged ONCE to host pinned buffers at construction; each
  batch does one async ``device_put`` per column — with a mesh, a
  *sharded* ``device_put`` so every device receives only its shard (the
  dp-sharded input pipeline);
* ``prefetch`` keeps N batches in flight: ``device_put`` is asynchronous,
  so host slicing of batch k+1 overlaps device compute on batch k — the
  host->HBM pipelining the async dispatch model gives for free;
* per-epoch shuffling is a host-side index permutation (deterministic in
  ``seed`` and epoch).

Multi-host: build the frame with
``parallel.multihost.frame_from_process_local`` and feed whole-frame
steps, or run one loader per process over the process-local rows with
``mesh`` set — each host stages only its own shard.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Mapping, Optional, Sequence

import numpy as np

from .frame import TensorFrame

__all__ = [
    "FrameLoader",
    "lm_split",
    "lm_split_packed",
    "pack_examples",
    "packed_frame",
]


@dataclasses.dataclass
class FrameLoader:
    """Batches a TensorFrame's columns for iterative training/eval.

    ``spec``: mesh partition entries for the batch axis (default
    ``("dp",)`` — batch sharded over dp, cells replicated).  Ignored
    without ``mesh``.
    """

    frame: TensorFrame
    batch_size: int
    columns: Optional[Sequence[str]] = None
    shuffle: bool = False
    seed: int = 0
    drop_remainder: bool = True
    mesh: Optional[object] = None
    spec: Sequence[object] = ("dp",)
    prefetch: int = 2

    def __post_init__(self):
        names = list(self.columns or self.frame.column_names)
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self._host: Dict[str, np.ndarray] = {}
        for n in names:
            col = self.frame.column(n)
            if col.is_ragged:
                raise ValueError(
                    f"column {n!r} is not a uniform array: run "
                    f"tfs.analyze(frame) first if the cells share a shape, "
                    f"or pad/bucket a truly ragged column before loading"
                )
            if not col.info.scalar_type.device_ok:
                raise ValueError(
                    f"column {n!r} has host-only dtype "
                    f"{col.info.scalar_type.name}; decode it with a map "
                    f"verb + host_stage first"
                )
            # one host staging copy, reused every epoch
            self._host[n] = np.asarray(col.data)
        self._names = names
        n_rows = self.frame.num_rows
        if self.drop_remainder:
            self._num_batches = n_rows // self.batch_size
        else:
            self._num_batches = -(-n_rows // self.batch_size)
        if self._num_batches == 0:
            raise ValueError(
                f"frame has {n_rows} rows < batch_size {self.batch_size}"
            )
        self._sharding = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            self._sharding = NamedSharding(
                self.mesh, PartitionSpec(*self.spec)
            )

    def __len__(self) -> int:
        return self._num_batches

    def _order(self, epoch: int) -> np.ndarray:
        n = self.frame.num_rows
        if not self.shuffle:
            return np.arange(n)
        return np.random.RandomState(
            (self.seed * 1_000_003 + epoch) % (2**32)
        ).permutation(n)

    def epoch(self, epoch: int = 0) -> Iterator[Dict[str, object]]:
        """Yield one epoch of batches (dicts of device arrays)."""
        import jax

        order = self._order(epoch) if self.shuffle else None
        pending: List[Dict[str, object]] = []
        for b in range(self._num_batches):
            lo, hi = b * self.batch_size, (b + 1) * self.batch_size
            batch = {}
            for n in self._names:
                # unshuffled: plain slice (a view — device_put is the only
                # copy); shuffled: one gather per batch
                cut = (
                    self._host[n][lo:hi]
                    if order is None
                    else self._host[n][order[lo:hi]]
                )
                batch[n] = (
                    jax.device_put(cut, self._sharding)
                    if self._sharding is not None
                    else jax.device_put(cut)
                )
            pending.append(batch)
            if len(pending) > max(self.prefetch, 0):
                yield pending.pop(0)
        yield from pending

    def __iter__(self) -> Iterator[Dict[str, object]]:
        return self.epoch(0)

    def forever(self) -> Iterator[Dict[str, object]]:
        """Epochs back to back (reshuffled each epoch when enabled)."""
        e = 0
        while True:
            yield from self.epoch(e)
            e += 1


def lm_split(batch: Mapping[str, object], column: str = "tokens"):
    """A [B, L+1] token batch -> (inputs [B, L], targets [B, L]) for the
    next-token objective (``train.make_train_step`` signature)."""
    toks = batch[column]
    return toks[:, :-1], toks[:, 1:]


def pack_examples(
    examples: Sequence[np.ndarray],
    seq_len: int,
    pad_id: int = 0,
):
    """Greedy best-fit packing of variable-length token sequences into
    fixed [N, seq_len] rows (each piece goes to the open row with the
    least sufficient space) — no per-example padding waste, the standard
    LM pretraining input shape (static shapes for XLA; the attention mask
    keeps segments independent — ``transformer.apply(segment_ids=...)``).

    Returns ``(tokens, segment_ids, positions)`` int32 arrays:

    * ``tokens``: packed ids, ``pad_id`` in underfull tails;
    * ``segment_ids``: 1, 2, ... per example within a row, 0 = padding;
    * ``positions``: restart at 0 at each segment start (RoPE sees every
      example from its own origin).

    Examples longer than ``seq_len`` are split into ``seq_len`` chunks
    (each chunk becomes its own segment).
    """
    pieces: List[np.ndarray] = []
    for ex in examples:
        ex = np.asarray(ex).ravel()
        for i in range(0, len(ex), seq_len):
            pieces.append(ex[i : i + seq_len])
    # BEST-fit with rows bucketed by remaining space: placing a piece is
    # an O(seq_len) bucket scan (smallest sufficient space wins) instead
    # of a scan over all open rows — linear in corpus size (review r3)
    rows: List[List[np.ndarray]] = []
    space: List[int] = []
    by_space: Dict[int, List[int]] = {}
    for p in pieces:
        need = len(p)
        r = None
        for free in range(need, seq_len + 1):
            bucket = by_space.get(free)
            if bucket:
                r = bucket.pop()
                break
        if r is None:
            rows.append([])
            space.append(seq_len)
            r = len(rows) - 1
        rows[r].append(p)
        space[r] -= need
        if space[r] > 0:
            by_space.setdefault(space[r], []).append(r)
    N = len(rows)
    tokens = np.full((N, seq_len), pad_id, np.int32)
    segments = np.zeros((N, seq_len), np.int32)
    positions = np.zeros((N, seq_len), np.int32)
    for r, segs in enumerate(rows):
        at = 0
        for s, p in enumerate(segs, start=1):
            tokens[r, at : at + len(p)] = p
            segments[r, at : at + len(p)] = s
            positions[r, at : at + len(p)] = np.arange(len(p))
            at += len(p)
    return tokens, segments, positions


def lm_split_packed(tokens, segment_ids, positions):
    """Packed [N, L] arrays -> (inputs, targets, segs, pos) for the
    next-token objective: the target at position i is token i+1 ONLY when
    both belong to the same (non-padding) segment; everything else is -1
    (ignored by ``transformer.cross_entropy``).  Works on numpy or device
    arrays (device inputs stay on device — ``train.fit(packed=True)``
    calls this per batch)."""
    import jax.numpy as jnp

    xp = jnp if not isinstance(tokens, np.ndarray) else np
    inp = tokens[:, :-1]
    tgt = tokens[:, 1:]
    same = (segment_ids[:, 1:] == segment_ids[:, :-1]) & (
        segment_ids[:, :-1] > 0
    )
    tgt = xp.where(same, tgt, -1)
    return inp, tgt, segment_ids[:, :-1], positions[:, :-1]


def packed_frame(
    examples: Sequence[np.ndarray],
    seq_len: int,
    num_blocks: int = 1,
    pad_id: int = 0,
):
    """Pack a variable-length corpus straight into an analyzed
    :class:`~.frame.TensorFrame` with ``tokens``/``segments``/``positions``
    columns of width ``seq_len + 1`` (one extra position so the
    next-token split yields ``seq_len``-wide training rows), ready for
    ``FrameLoader`` + ``train.fit(packed=True)``."""
    from .analyze import analyze
    from .frame import TensorFrame

    toks, segs, pos = pack_examples(examples, seq_len + 1, pad_id)
    return analyze(
        TensorFrame.from_arrays(
            {"tokens": toks, "segments": segs, "positions": pos},
            num_blocks=num_blocks,
        )
    )
