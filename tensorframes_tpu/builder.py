"""OpBuilder: the fluent verb-builder protocol.

Re-design of the reference's Py4J surface ``PythonOpBuilder``
(``/root/reference/src/main/scala/org/tensorframes/impl/PythonInterface.scala:86-170``):
the python client accumulates a graph (bytes or file path), shape hints,
requested fetches, and a placeholder->column feed map, then dispatches
``buildDF`` (frame-returning verbs) or ``buildRow`` (reducing verbs).  The
reference needs this builder because every attribute crosses a Py4J socket;
here there is no process boundary, but the protocol is kept as the stable
programmatic surface mirroring ``map_blocks / map_rows / reduce_blocks /
reduce_rows / aggregate_blocks`` (``PythonInterface.scala:46-68``) — the
entry point an external front-end (e.g. a Spark bridge) would drive.

    out = (OpBuilder.map_blocks(frame, trim=False)
           .graph_from_file("model.pb")
           .fetches(["out"])
           .inputs({"x": "col"})
           .shape("out", [-1, 10])
           .build_df())
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

from .frame import TensorFrame
from .ops import engine
from .ops.engine import Executor, GroupedFrame
from .program import Program, ProgramError


def compile_program(
    source: Any,
    fetches: Optional[Sequence[str]] = None,
    inputs: Optional[Mapping[str, str]] = None,
    shapes: Optional[Mapping[str, Sequence[int]]] = None,
    outputs: Optional[Mapping[str, str]] = None,
    is_graphdef: Optional[bool] = None,
    what: str = "program",
) -> Program:
    """Build a :class:`Program` from any accepted source — GraphDef
    bytes, a python function, DSL nodes, or an existing Program — with
    the builder's feed/fetch/shape-hint semantics.  This is the one
    program-construction path shared by :class:`OpBuilder` and the
    bridge's warm program pool (``bridge/coalescer.py``), so a program
    built once can be cached and reused across requests instead of
    re-importing the GraphDef per call."""
    if is_graphdef is None:
        is_graphdef = isinstance(source, (bytes, bytearray))
    if is_graphdef:
        from .graphdef import import_graphdef

        if not fetches:
            raise ProgramError(
                f"{what}: GraphDef programs need fetches before build"
            )
        program = import_graphdef(
            source,
            fetches=list(fetches),
            inputs=dict(inputs) if inputs else None,
            outputs=dict(outputs) if outputs else None,
        )
    else:
        if outputs:
            raise ProgramError(
                "outputs renames apply to GraphDef programs only"
            )
        program = Program.wrap(
            source, list(fetches) if fetches else fetches,
            dict(inputs) if inputs else None,
        )
    if shapes:
        # the ShapeDescription override: hints refine engine-inferred
        # shapes in analyze() and are checked against real outputs at
        # run time (contradictions raise)
        program = program.with_shape_hints(shapes)
    return program


class OpBuilder:
    """Accumulates program source + hints for one verb invocation.

    Mirrors the reference builder's accessors: ``graph``/``graph_from_file``
    (``PythonInterface.scala:110-118``), ``shape`` (L97-103), ``fetches``
    (L105-108), ``inputs`` (L120-127), ``build_df``/``build_row``
    (L129-151)."""

    def __init__(
        self,
        verb: str,
        frame: Any,
        trim: bool = False,
        engine_: Optional[Executor] = None,
    ):
        self._verb = verb
        self._frame = frame
        self._trim = trim
        self._engine = engine_
        self._source: Any = None  # callable | Program | GraphDef bytes/path
        self._is_graphdef = False
        self._fetches: Optional[List[str]] = None
        self._feed: Dict[str, str] = {}
        self._out_renames: Dict[str, str] = {}
        self._shapes: Dict[str, Sequence[int]] = {}
        self._host_stage: Dict[str, Any] = {}

    # -- verb factories (PythonInterface.scala:46-68) ------------------------

    @staticmethod
    def map_blocks(
        frame: TensorFrame, trim: bool = False, engine_: Optional[Executor] = None
    ) -> "OpBuilder":
        return OpBuilder("map_blocks", frame, trim, engine_)

    @staticmethod
    def map_rows(
        frame: TensorFrame, engine_: Optional[Executor] = None
    ) -> "OpBuilder":
        return OpBuilder("map_rows", frame, engine_=engine_)

    @staticmethod
    def reduce_blocks(
        frame: TensorFrame, engine_: Optional[Executor] = None
    ) -> "OpBuilder":
        return OpBuilder("reduce_blocks", frame, engine_=engine_)

    @staticmethod
    def reduce_rows(
        frame: TensorFrame, engine_: Optional[Executor] = None
    ) -> "OpBuilder":
        return OpBuilder("reduce_rows", frame, engine_=engine_)

    @staticmethod
    def aggregate_blocks(
        grouped: GroupedFrame, engine_: Optional[Executor] = None
    ) -> "OpBuilder":
        return OpBuilder("aggregate", grouped, engine_=engine_)

    # -- accumulators --------------------------------------------------------

    def graph(self, source) -> "OpBuilder":
        """Attach the program: a python function, a Program, DSL node(s), or
        serialized GraphDef bytes."""
        if isinstance(source, (bytes, bytearray)):
            self._is_graphdef = True
        self._source = source
        return self

    def graph_from_file(self, path: str) -> "OpBuilder":
        """Attach a frozen GraphDef from a file path — the reference's
        default transport (``core.py:38-49`` writes a temp file to avoid
        shipping bytes through Py4J)."""
        self._source = path
        self._is_graphdef = True
        return self

    def fetches(self, names: Sequence[str]) -> "OpBuilder":
        self._fetches = list(names)
        return self

    def inputs(self, feed: Mapping[str, str]) -> "OpBuilder":
        """placeholder/input name -> frame column name."""
        self._feed.update(feed)
        return self

    def outputs(self, renames: Mapping[str, str]) -> "OpBuilder":
        """fetch ref -> result column name (GraphDef programs only): the
        output-direction rename for frozen graphs whose node names don't
        match the verb naming contract."""
        self._out_renames.update(renames)
        return self

    def shape(self, name: str, shape: Sequence[int]) -> "OpBuilder":
        """Output-shape hint (the ``ShapeDescription`` override mechanism,
        ``ShapeDescription.scala:3-16``)."""
        self._shapes[name] = list(shape)
        return self

    def host_stage(self, input_name: str, fn) -> "OpBuilder":
        """Attach a host preprocessing fn for one input (binary decode —
        the host half of the reference's in-graph DecodeJpeg feed,
        ``read_image.py:164-167``)."""
        self._host_stage[input_name] = fn
        return self

    # -- dispatch ------------------------------------------------------------

    def _program(self) -> Program:
        if self._source is None:
            raise ProgramError(
                f"{self._verb} builder: no graph attached; call .graph(...) "
                f"or .graph_from_file(...)"
            )
        return compile_program(
            self._source,
            fetches=self._fetches,
            inputs=self._feed or None,
            shapes=self._shapes or None,
            outputs=self._out_renames or None,
            is_graphdef=self._is_graphdef,
            what=self._verb,
        )

    def build_df(self) -> TensorFrame:
        """Run a frame-returning verb (``buildDF``,
        ``PythonInterface.scala:144-151``)."""
        program = self._program()
        if self._verb == "map_blocks":
            return engine.map_blocks(
                program,
                self._frame,
                trim=self._trim,
                host_stage=self._host_stage or None,
                engine=self._engine,
            )
        if self._verb == "map_rows":
            return engine.map_rows(
                program,
                self._frame,
                host_stage=self._host_stage or None,
                engine=self._engine,
            )
        if self._verb == "aggregate":
            if self._host_stage:
                raise ProgramError(
                    "host_stage is only supported on the map verbs "
                    "(map_blocks/map_rows); preprocess with a map first, "
                    "then aggregate the result"
                )
            return engine.aggregate(program, self._frame, engine=self._engine)
        raise ProgramError(
            f"{self._verb} returns a row, not a frame; use build_row()"
        )

    def build_row(self) -> Dict[str, np.ndarray]:
        """Run a reducing verb to a single row (``buildRow``,
        ``PythonInterface.scala:129-139``)."""
        if self._host_stage:
            raise ProgramError(
                "host_stage is only supported on the map verbs "
                "(map_blocks/map_rows); preprocess with a map first, then "
                "reduce the result"
            )
        program = self._program()
        if self._verb == "reduce_blocks":
            return engine.reduce_blocks(
                program, self._frame, engine=self._engine
            )
        if self._verb == "reduce_rows":
            return engine.reduce_rows(program, self._frame, engine=self._engine)
        raise ProgramError(
            f"{self._verb} returns a frame, not a row; use build_df()"
        )
