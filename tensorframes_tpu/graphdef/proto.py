"""TF framework proto messages: GraphDef / NodeDef / AttrValue / TensorProto.

Schema-directed decode/encode over the ``wire`` codec, covering the subset of
the public TF wire format the framework interchanges (field numbers are fixed
by the public .proto definitions the reference vendors — SURVEY.md §2.5:
``graph.proto``, ``attr_value.proto``, ``tensor.proto``,
``tensor_shape.proto``, ``types.proto``).  Both directions are implemented so
tests can round-trip golden graphs without TensorFlow installed (replacing
the reference's python-TF subprocess diffing, ``dsl/ExtractNodes.scala``).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from .. import dtypes as dt
from ..shape import Shape, UNKNOWN
from . import wire


# -- TensorShapeProto (tensor_shape.proto: dim=2{size=1,name=2}, unknown_rank=3)


def parse_shape(buf: bytes) -> Optional[Shape]:
    dims: List[int] = []
    unknown_rank = False
    for field, wt, v in wire.fields(buf):
        if field == 2 and wt == wire.WIRE_LEN:
            size = 0
            for f2, _, v2 in wire.fields(v):
                if f2 == 1:
                    size = wire.decode_signed_varint(v2)
            dims.append(size)
        elif field == 3:
            unknown_rank = bool(v)
    return None if unknown_rank else Shape(dims)


def encode_shape(shape: Shape) -> bytes:
    out = bytearray()
    for d in shape:
        dim = bytearray()
        if d != 0:
            wire.write_varint_field(dim, 1, d)
        wire.write_len_field(out, 2, bytes(dim))
    return bytes(out)


# -- TensorProto (tensor.proto) ---------------------------------------------

_TYPED_FIELDS = {
    # field -> (tf enum, struct fmt for packed / None for varint, np dtype)
    5: (dt.TF_FLOAT, "<f", np.float32),
    6: (dt.TF_DOUBLE, "<d", np.float64),
    7: (dt.TF_INT32, None, np.int32),
    10: (dt.TF_INT64, None, np.int64),
    11: (dt.TF_BOOL, None, np.bool_),
}


@dataclasses.dataclass
class TensorProto:
    dtype: int
    shape: Shape
    value: np.ndarray  # decoded host value (object array for strings)

    @staticmethod
    def parse(buf: bytes) -> "TensorProto":
        dtype = 0
        shape = Shape(())
        content = b""
        typed: Dict[int, List] = {}
        strings: List[bytes] = []
        for field, wt, v in wire.fields(buf):
            if field == 1:
                dtype = int(v)
            elif field == 2 and wt == wire.WIRE_LEN:
                s = parse_shape(v)
                shape = s if s is not None else Shape(())
            elif field == 4 and wt == wire.WIRE_LEN:
                content = v
            elif field == 8 and wt == wire.WIRE_LEN:
                strings.append(v)
            elif field in _TYPED_FIELDS:
                _, fmt, _npd = _TYPED_FIELDS[field]
                if wt == wire.WIRE_LEN and fmt:
                    typed.setdefault(field, []).extend(
                        wire.unpack_packed(v, fmt)
                    )
                elif wt == wire.WIRE_LEN and fmt is None:
                    typed.setdefault(field, []).extend(
                        wire.unpack_packed_varints(v)
                    )
                elif wt == wire.WIRE_VARINT:
                    typed.setdefault(field, []).append(
                        wire.decode_signed_varint(v)
                    )
                elif wt == wire.WIRE_FIXED32:
                    typed.setdefault(field, []).append(
                        struct.unpack("<f", v)[0]
                    )
                elif wt == wire.WIRE_FIXED64:
                    typed.setdefault(field, []).append(
                        struct.unpack("<d", v)[0]
                    )
        n = shape.num_elements()
        if dtype == dt.TF_STRING:
            arr = np.empty(len(strings), dtype=object)
            for i, s in enumerate(strings):
                arr[i] = s
            if n is not None and n != len(strings) and len(strings) == 1:
                arr = np.full(tuple(shape), strings[0], dtype=object)
            elif n is not None:
                arr = arr.reshape(tuple(shape))
            return TensorProto(dtype, shape, arr)
        st = dt.from_tf_enum(dtype)
        npd = st.np_dtype
        if content:
            arr = np.frombuffer(content, dtype=npd.newbyteorder("<")).astype(
                npd
            )
        else:
            vals = None
            for field, (en, _f, _npd) in _TYPED_FIELDS.items():
                if en == dtype and field in typed:
                    vals = typed[field]
            if vals is None:
                vals = next(iter(typed.values())) if typed else []
            arr = np.asarray(vals, dtype=npd)
        if n is not None:
            if arr.size == n:
                arr = arr.reshape(tuple(shape))
            elif arr.size == 1:
                # proto scalar-broadcast convention: one value fills the shape
                arr = np.full(tuple(shape), arr.reshape(())[()], dtype=npd)
            elif arr.size == 0:
                arr = np.zeros(tuple(shape), dtype=npd)
            else:
                raise wire.WireError(
                    f"TensorProto has {arr.size} values for shape {shape}"
                )
        return TensorProto(dtype, shape, arr)

    @staticmethod
    def from_numpy(arr: np.ndarray) -> "TensorProto":
        arr = np.asarray(arr)
        st = dt.from_numpy(arr.dtype)
        return TensorProto(st.tf_enum, Shape(arr.shape), arr)

    def encode(self) -> bytes:
        out = bytearray()
        wire.write_varint_field(out, 1, self.dtype)
        wire.write_len_field(out, 2, encode_shape(self.shape))
        arr = np.asarray(self.value)
        if self.dtype == dt.TF_STRING:
            for s in arr.reshape(-1):
                wire.write_len_field(
                    out, 8, s if isinstance(s, bytes) else str(s).encode()
                )
        else:
            st = dt.from_tf_enum(self.dtype)
            # tensor_content: raw little-endian — the layout DenseTensor.scala
            # (reference L73-115) writes
            wire.write_len_field(
                out,
                4,
                arr.astype(st.np_dtype.newbyteorder("<"), copy=False).tobytes(),
            )
        return bytes(out)


# -- AttrValue (attr_value.proto) -------------------------------------------

AttrVal = Union[bytes, int, float, bool, Shape, TensorProto, list, None]


@dataclasses.dataclass
class AttrValue:
    kind: str  # 's','i','f','b','type','shape','tensor','list',
    #            'type_list','func','none'
    value: AttrVal

    @staticmethod
    def parse(buf: bytes) -> "AttrValue":
        for field, wt, v in wire.fields(buf):
            if field == 2:
                return AttrValue("s", v)
            if field == 3:
                return AttrValue("i", wire.decode_signed_varint(v))
            if field == 4:
                return AttrValue("f", struct.unpack("<f", v)[0])
            if field == 5:
                return AttrValue("b", bool(v))
            if field == 6:
                return AttrValue("type", int(v))
            if field == 7:
                return AttrValue("shape", parse_shape(v))
            if field == 8:
                return AttrValue("tensor", TensorProto.parse(v))
            if field == 10:  # NameAttrList — branch functions of If/While
                fname = ""
                fattrs: Dict[str, "AttrValue"] = {}
                for f2, _, v2 in wire.fields(v):
                    if f2 == 1:
                        fname = v2.decode()
                    elif f2 == 2:
                        k2 = ""
                        av2 = AttrValue("none", None)
                        for f3, _, v3 in wire.fields(v2):
                            if f3 == 1:
                                k2 = v3.decode()
                            elif f3 == 2:
                                av2 = AttrValue.parse(v3)
                        fattrs[k2] = av2
                return AttrValue("func", (fname, fattrs))
            if field == 1:  # ListValue
                items: List = []
                kind = "list"
                for f2, wt2, v2 in wire.fields(v):
                    if f2 == 2:
                        items.append(v2)
                    elif f2 == 3:
                        if wt2 == wire.WIRE_LEN:
                            items.extend(wire.unpack_packed_varints(v2))
                        else:
                            items.append(wire.decode_signed_varint(v2))
                    elif f2 == 4:
                        if wt2 == wire.WIRE_LEN:
                            items.extend(wire.unpack_packed(v2, "<f"))
                        else:
                            items.append(struct.unpack("<f", v2)[0])
                    elif f2 == 5:
                        # `repeated bool b = 5 [packed = true]` — TF writers
                        # emit one length-delimited blob of 0/1 varints
                        if wt2 == wire.WIRE_LEN:
                            items.extend(
                                bool(b)
                                for b in wire.unpack_packed_varints(
                                    v2, signed=False
                                )
                            )
                        else:
                            items.append(bool(v2))
                    elif f2 == 6:
                        # list(type) — distinct from list(int): TF's op
                        # validation rejects the wrong list arm, so the
                        # kind must survive a parse->encode round trip
                        kind = "type_list"
                        if wt2 == wire.WIRE_LEN:
                            items.extend(
                                wire.unpack_packed_varints(v2, signed=False)
                            )
                        else:
                            items.append(int(v2))
                    elif f2 == 7:
                        items.append(parse_shape(v2))
                    elif f2 == 8:
                        items.append(TensorProto.parse(v2))
                return AttrValue(kind, items)
        return AttrValue("none", None)

    def encode(self) -> bytes:
        out = bytearray()
        if self.kind == "s":
            wire.write_len_field(out, 2, self.value)
        elif self.kind == "i":
            wire.write_varint_field(out, 3, self.value)
        elif self.kind == "f":
            wire.write_fixed32_field(out, 4, struct.pack("<f", self.value))
        elif self.kind == "b":
            wire.write_varint_field(out, 5, int(self.value))
        elif self.kind == "type":
            wire.write_varint_field(out, 6, self.value)
        elif self.kind == "shape":
            wire.write_len_field(out, 7, encode_shape(self.value))
        elif self.kind == "tensor":
            wire.write_len_field(out, 8, self.value.encode())
        elif self.kind == "list":
            lst = bytearray()
            for it in self.value:
                if isinstance(it, bool):
                    wire.write_varint_field(lst, 5, int(it))
                elif isinstance(it, int):
                    wire.write_varint_field(lst, 3, it)
                elif isinstance(it, float):
                    wire.write_fixed32_field(lst, 4, struct.pack("<f", it))
                elif isinstance(it, bytes):
                    wire.write_len_field(lst, 2, it)
                elif isinstance(it, Shape):
                    wire.write_len_field(lst, 7, encode_shape(it))
                elif isinstance(it, TensorProto):
                    wire.write_len_field(lst, 8, it.encode())
                else:
                    raise wire.WireError(
                        f"cannot encode list attr item {type(it).__name__}"
                    )
            wire.write_len_field(out, 1, bytes(lst))
        elif self.kind == "func":
            fname, fattrs = self.value
            msg = bytearray()
            wire.write_len_field(msg, 1, fname.encode())
            for k in sorted(fattrs):
                entry = bytearray()
                wire.write_len_field(entry, 1, k.encode())
                wire.write_len_field(entry, 2, fattrs[k].encode())
                wire.write_len_field(msg, 2, bytes(entry))
            wire.write_len_field(out, 10, bytes(msg))
        elif self.kind == "type_list":
            # ListValue.type: `repeated DataType type = 6 [packed = true]`
            packed = bytearray()
            for en in self.value:
                wire.write_varint(packed, int(en))
            lst = bytearray()
            wire.write_len_field(lst, 6, bytes(packed))
            wire.write_len_field(out, 1, bytes(lst))
        elif self.kind == "none":
            pass
        else:
            raise wire.WireError(f"unknown attr kind {self.kind!r}")
        return bytes(out)


# -- NodeDef / GraphDef (graph.proto) ---------------------------------------


@dataclasses.dataclass
class NodeDef:
    name: str
    op: str
    inputs: List[str]
    attrs: Dict[str, AttrValue]
    device: str = ""

    @staticmethod
    def parse(buf: bytes) -> "NodeDef":
        name = op = device = ""
        inputs: List[str] = []
        attrs: Dict[str, AttrValue] = {}
        for field, wt, v in wire.fields(buf):
            if field == 1:
                name = v.decode()
            elif field == 2:
                op = v.decode()
            elif field == 3:
                inputs.append(v.decode())
            elif field == 4:
                device = v.decode()
            elif field == 5:
                k = ""
                av = AttrValue("none", None)
                for f2, _, v2 in wire.fields(v):
                    if f2 == 1:
                        k = v2.decode()
                    elif f2 == 2:
                        av = AttrValue.parse(v2)
                attrs[k] = av
        return NodeDef(name, op, inputs, attrs, device)

    def encode(self) -> bytes:
        out = bytearray()
        wire.write_len_field(out, 1, self.name.encode())
        wire.write_len_field(out, 2, self.op.encode())
        for i in self.inputs:
            wire.write_len_field(out, 3, i.encode())
        if self.device:
            wire.write_len_field(out, 4, self.device.encode())
        for k in sorted(self.attrs):
            entry = bytearray()
            wire.write_len_field(entry, 1, k.encode())
            wire.write_len_field(entry, 2, self.attrs[k].encode())
            wire.write_len_field(out, 5, bytes(entry))
        return bytes(out)


@dataclasses.dataclass
class FunctionDef:
    """A library function (function.proto) — the body TF2 control flow
    (``StatelessIf``/``If``/``While``) calls by name.

    ``input_args``/``output_args`` are the signature's ArgDef names in
    declaration order (with TF dtype enums where declared); body node
    inputs use the function-ref grammar ``node:out_arg:idx`` for node
    outputs and bare names for input args; ``ret`` maps each output arg
    to such a ref."""

    name: str
    input_args: List[Tuple[str, int]]
    output_args: List[Tuple[str, int]]
    nodes: List[NodeDef]
    ret: Dict[str, str]

    @staticmethod
    def parse(buf: bytes) -> "FunctionDef":
        name = ""
        input_args: List[Tuple[str, int]] = []
        output_args: List[Tuple[str, int]] = []
        nodes: List[NodeDef] = []
        ret: Dict[str, str] = {}
        for field, wt, v in wire.fields(buf):
            if field == 1 and wt == wire.WIRE_LEN:  # signature: OpDef
                for f2, _, v2 in wire.fields(v):
                    if f2 == 1:
                        name = v2.decode()
                    elif f2 in (2, 3):  # input_arg / output_arg: ArgDef
                        an, at = "", 0
                        for f3, _, v3 in wire.fields(v2):
                            if f3 == 1:
                                an = v3.decode()
                            elif f3 == 3:
                                at = int(v3)
                        (input_args if f2 == 2 else output_args).append(
                            (an, at)
                        )
            elif field == 3 and wt == wire.WIRE_LEN:
                nodes.append(NodeDef.parse(v))
            elif field == 4 and wt == wire.WIRE_LEN:  # ret map entry
                k = rv = ""
                for f2, _, v2 in wire.fields(v):
                    if f2 == 1:
                        k = v2.decode()
                    elif f2 == 2:
                        rv = v2.decode()
                ret[k] = rv
        return FunctionDef(name, input_args, output_args, nodes, ret)

    def encode(self) -> bytes:
        sig = bytearray()
        wire.write_len_field(sig, 1, self.name.encode())
        for f2, args in ((2, self.input_args), (3, self.output_args)):
            for an, at in args:
                arg = bytearray()
                wire.write_len_field(arg, 1, an.encode())
                if at:
                    wire.write_varint_field(arg, 3, at)
                wire.write_len_field(sig, f2, bytes(arg))
        out = bytearray()
        wire.write_len_field(out, 1, bytes(sig))
        for n in self.nodes:
            wire.write_len_field(out, 3, n.encode())
        for k in sorted(self.ret):
            entry = bytearray()
            wire.write_len_field(entry, 1, k.encode())
            wire.write_len_field(entry, 2, self.ret[k].encode())
            wire.write_len_field(out, 4, bytes(entry))
        return bytes(out)


@dataclasses.dataclass
class GraphDef:
    nodes: List[NodeDef]
    functions: Dict[str, FunctionDef] = dataclasses.field(
        default_factory=dict
    )

    @staticmethod
    def parse(buf: bytes) -> "GraphDef":
        nodes = []
        functions: Dict[str, FunctionDef] = {}
        for field, wt, v in wire.fields(buf):
            if field == 1 and wt == wire.WIRE_LEN:
                nodes.append(NodeDef.parse(v))
            elif field == 2 and wt == wire.WIRE_LEN:  # FunctionDefLibrary
                for f2, wt2, v2 in wire.fields(v):
                    if f2 == 1 and wt2 == wire.WIRE_LEN:
                        fd = FunctionDef.parse(v2)
                        functions[fd.name] = fd
        return GraphDef(nodes, functions)

    def encode(self) -> bytes:
        out = bytearray()
        for n in self.nodes:
            wire.write_len_field(out, 1, n.encode())
        if self.functions:
            lib = bytearray()
            for fname in sorted(self.functions):
                wire.write_len_field(lib, 1, self.functions[fname].encode())
            wire.write_len_field(out, 2, bytes(lib))
        return bytes(out)

    def node_map(self) -> Dict[str, NodeDef]:
        return {n.name: n for n in self.nodes}


def parse_graphdef(data: bytes) -> GraphDef:
    return GraphDef.parse(data)
