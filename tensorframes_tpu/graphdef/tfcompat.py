"""Make emitted GraphDefs importable by real TensorFlow.

Our writer (``builder.GraphBuilder``, the exporters, ``dsl.to_graphdef``)
emits the *semantic* attrs each op needs — our importer infers dtypes from
the values flowing through the graph, the way XLA tracing does.  Real TF's
``import_graph_def`` is stricter: every attr an ``OpDef`` declares without
a default (``T``, ``SrcT``/``DstT``, ``Tidx``, ``Index``, ``N``, ...) must
be present in the ``NodeDef`` or the import is rejected (the reference
ships TF-generated graphs, which always carry them —
``ExtractNodes.scala:14-74`` pins that byte-level contract).

``complete_for_tf`` closes the gap: one topological dtype-propagation pass
over the parsed graph fills every missing TF-required dtype/count attr, so
any graph this framework writes round-trips through a live TensorFlow
(``tests/test_tf_live.py`` proves it against a real TF subprocess).
Existing attrs are never overwritten.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import dtypes as dt
from .proto import AttrValue, GraphDef, NodeDef

_BOOL = dt.by_name("bool").tf_enum
_I32 = dt.by_name("int32").tf_enum
_I64 = dt.by_name("int64").tf_enum
_F32 = dt.by_name("float32").tf_enum
_U8 = dt.by_name("uint8").tf_enum

# ops whose single output and required ``T`` both take the first input's
# dtype (elementwise unary/binary, activations, pooling, conv...)
_PASS_T = frozenset(
    """Identity Snapshot StopGradient PreventGradient Neg Abs Sign Square
    Reciprocal Inv Exp Expm1 Log Log1p Sqrt Rsqrt Erf Erfc Sin Cos Tan
    Asin Acos Atan Sinh Cosh Floor Ceil Round Rint Relu Relu6 Elu Selu
    LeakyRelu Sigmoid Tanh Softplus Softsign Softmax LogSoftmax ZerosLike
    OnesLike LRN MaxPool AvgPool BiasAdd ClipByValue InvertPermutation
    CheckNumerics Add AddV2 Sub Mul Div RealDiv FloorDiv FloorMod Mod
    Maximum Minimum Pow SquaredDifference Atan2 MatMul BatchMatMul
    BatchMatMulV2 Conv2D Conv3D DepthwiseConv2dNative MaxPool3D
    AvgPool3D DepthToSpace SpaceToDepth
    ResizeNearestNeighbor""".split()
)
_CMP = frozenset(
    "Equal NotEqual Less LessEqual Greater GreaterEqual".split()
)
_REDUCE = frozenset("Sum Mean Min Max Prod".split())
# (T attr name, index-typed attr name keyed on second input)
_IDX_PAIR = {
    "Reshape": ("T", "Tshape"),
    "ExpandDims": ("T", "Tdim"),
    "Transpose": ("T", "Tperm"),
    "BroadcastTo": ("T", "Tidx"),
    "Slice": ("T", "Index"),
    "StridedSlice": ("T", "Index"),
    "Pad": ("T", "Tpaddings"),
    "PadV2": ("T", "Tpaddings"),
    "MirrorPad": ("T", "Tpaddings"),
    "Tile": ("T", "Tmultiples"),
    "Gather": ("Tparams", "Tindices"),
    "GatherNd": ("Tparams", "Tindices"),
    "Cumsum": ("T", "Tidx"),
    "Cumprod": ("T", "Tidx"),
}


def _ref_parts(ref: str) -> Optional[Tuple[str, int]]:
    if ref.startswith("^"):
        return None  # control edge: ordering only
    if ":" in ref:
        name, idx = ref.rsplit(":", 1)
        return name, int(idx)
    return ref, 0


def _topo(nodes: List[NodeDef]) -> List[NodeDef]:
    # iterative DFS: input chains in exported models can exceed Python's
    # recursion limit (a 1000-node sequential graph is not exotic)
    by_name = {n.name: n for n in nodes}
    order: List[NodeDef] = []
    state: Dict[str, int] = {}  # 1 = on stack, 2 = done
    for root in nodes:
        stack: List[Tuple[NodeDef, bool]] = [(root, False)]
        while stack:
            n, children_done = stack.pop()
            if children_done:
                state[n.name] = 2
                order.append(n)
                continue
            st = state.get(n.name)
            if st is not None:  # done, or a cycle (TF rejects those anyway)
                continue
            state[n.name] = 1
            stack.append((n, True))
            for ref in n.inputs:
                parts = _ref_parts(ref)
                if parts and parts[0] in by_name:
                    dep = by_name[parts[0]]
                    if state.get(dep.name) is None:
                        stack.append((dep, False))
    return order


def complete_for_tf(graph: GraphDef) -> GraphDef:
    """Return a copy of ``graph`` with TF-required dtype/count attrs filled.

    Unknown ops (or inputs whose dtype cannot be resolved) are left
    untouched — the pass is best-effort and never raises on them; every op
    in the importer registry (``docs/GRAPHDEF_OPS.md``) is covered.  The
    only attrs it cannot conjure are ``Split.num_split`` / ``Unpack.num``
    (they define the node's output arity) and ``Einsum.equation`` (it
    defines the contraction itself) — the author must supply those, and
    our own importer requires them too; ``SplitV.num_split`` is derived
    from the ``size_splits`` Const when missing.
    """
    out_dtypes: Dict[str, List[Optional[int]]] = {}
    const_elems: Dict[str, int] = {}  # Const node -> tensor element count

    def in_dt(node: NodeDef, i: int) -> Optional[int]:
        data_ins = [r for r in node.inputs if not r.startswith("^")]
        if i >= len(data_ins):
            return None
        parts = _ref_parts(data_ins[i])
        if parts is None:
            return None
        name, idx = parts
        dts = out_dtypes.get(name)
        if dts is None:
            return None
        if idx < len(dts):
            return dts[idx]
        # out-of-range output index (e.g. the producer's arity was
        # under-estimated because num/num_split was absent): guessing
        # dts[0] could stamp a WRONG dtype attr into the emitted NodeDef;
        # best-effort means leave the attr unset instead (ADVICE r5)
        return None

    new_nodes: List[NodeDef] = []
    for old in _topo(graph.nodes):
        node = NodeDef(
            old.name, old.op, list(old.inputs), dict(old.attrs), old.device
        )
        op = node.op
        attrs = node.attrs

        def put(key: str, enum: Optional[int]):
            if enum is not None and key not in attrs:
                attrs[key] = AttrValue("type", enum)

        def have(key: str) -> Optional[int]:
            av = attrs.get(key)
            return av.value if av is not None and av.kind == "type" else None

        def put_int(key: str, value: int):
            if key not in attrs:
                attrs[key] = AttrValue("i", value)

        n_data = len([r for r in node.inputs if not r.startswith("^")])
        t0 = in_dt(node, 0)
        outs: List[Optional[int]] = [t0]

        if op in ("Const", "Placeholder", "PlaceholderV2"):
            outs = [have("dtype")]
            if op == "Const":
                val = attrs.get("value")
                if val is not None and val.kind == "tensor":
                    try:
                        const_elems[node.name] = int(
                            np.asarray(val.value.value).size
                        )
                    except Exception:
                        pass
        elif op == "PlaceholderWithDefault":
            put("dtype", t0)
            outs = [have("dtype")]
        elif op == "NoOp":
            outs = []
        elif op in ("Switch", "RefSwitch"):
            put("T", t0)
            outs = [t0, t0]
        elif op == "Merge":
            put("T", t0)
            put_int("N", n_data)
            outs = [t0, _I32]
        elif op in _PASS_T:
            put("T", t0)
            if op == "CheckNumerics" and "message" not in attrs:
                attrs["message"] = AttrValue("s", b"")
            outs = [t0]
        elif op in _CMP:
            put("T", t0)
            outs = [_BOOL]
        elif op in ("Select", "SelectV2"):
            t = in_dt(node, 1)
            put("T", t)
            outs = [t]
        elif op == "AddN":
            put_int("N", n_data)
            put("T", t0)
        elif op == "Einsum":
            put_int("N", n_data)
            put("T", t0)
        elif op == "IdentityN":
            dts = [in_dt(node, i) for i in range(n_data)]
            if "T" not in attrs and all(d is not None for d in dts):
                attrs["T"] = AttrValue("type_list", list(dts))
            outs = dts
        elif op == "Cast":
            put("SrcT", t0)
            outs = [have("DstT")]
        elif op == "Shape":
            put("T", t0)
            put("out_type", _I32)
            outs = [have("out_type")]
        elif op == "Rank":
            put("T", t0)
            outs = [_I32]
        elif op == "Size":
            put("T", t0)
            put("out_type", _I32)
            outs = [have("out_type")]
        elif op in _REDUCE:
            put("T", t0)
            put("Tidx", in_dt(node, 1))
            outs = [t0]
        elif op in ("All", "Any"):
            put("Tidx", in_dt(node, 1))
            outs = [_BOOL]
        elif op in ("ArgMax", "ArgMin"):
            put("T", t0)
            put("Tidx", in_dt(node, 1))
            put("output_type", _I64)
            outs = [have("output_type")]
        elif op == "UnsortedSegmentSum":
            put("T", t0)
            put("Tindices", in_dt(node, 1))
            put("Tnumsegments", in_dt(node, 2))
            outs = [t0]
        elif op in _IDX_PAIR:
            t_key, idx_key = _IDX_PAIR[op]
            put(t_key, t0)
            put(idx_key, in_dt(node, 1))
            outs = [t0]
        elif op == "Squeeze":
            put("T", t0)
            if "squeeze_dims" not in attrs:
                attrs["squeeze_dims"] = AttrValue("list", [])
            outs = [t0]
        elif op == "GatherV2":
            put("Tparams", t0)
            put("Tindices", in_dt(node, 1))
            put("Taxis", in_dt(node, 2))
            put_int("batch_dims", 0)
            outs = [t0]
        elif op == "Concat":
            t = in_dt(node, 1)
            put("T", t)
            put_int("N", n_data - 1)
            outs = [t]
        elif op == "ConcatV2":
            put("T", t0)
            put("Tidx", in_dt(node, n_data - 1))
            put_int("N", n_data - 1)
            outs = [t0]
        elif op == "Pack":
            put("T", t0)
            put_int("N", n_data)
            outs = [t0]
        elif op == "Unpack":
            put("T", t0)
            num_av = attrs.get("num")
            num = int(num_av.value) if num_av and num_av.kind == "i" else 1
            outs = [t0] * num
        elif op == "Split":
            t = in_dt(node, 1)
            put("T", t)
            ns_av = attrs.get("num_split")
            ns = int(ns_av.value) if ns_av and ns_av.kind == "i" else 1
            outs = [t] * ns
        elif op == "SplitV":
            put("T", t0)
            put("Tlen", in_dt(node, 1))
            if "num_split" not in attrs:
                # derivable here (unlike Split/Unpack, whose counts define
                # the output arity and must come from the author): it is
                # the element count of the size_splits Const
                data_ins = [r for r in node.inputs if not r.startswith("^")]
                parts = _ref_parts(data_ins[1]) if len(data_ins) > 1 else None
                sizes = const_elems.get(parts[0]) if parts else None
                if sizes is not None:
                    attrs["num_split"] = AttrValue("i", sizes)
            ns_av = attrs.get("num_split")
            ns = int(ns_av.value) if ns_av and ns_av.kind == "i" else 1
            outs = [t0] * ns
        elif op == "OneHot":
            t = in_dt(node, 2)
            put("T", t)
            put("TI", t0)
            outs = [t]
        elif op == "TopKV2":
            put("T", t0)
            outs = [t0, _I32]
        elif op == "Fill":
            t = in_dt(node, 1)
            put("T", t)
            put("index_type", t0)
            outs = [t]
        elif op == "Range":
            put("Tidx", t0)
            outs = [t0]
        elif op in ("Conv2DBackpropInput", "Conv3DBackpropInputV2"):
            t = in_dt(node, 1)
            put("T", t)
            if op == "Conv3DBackpropInputV2":
                # unlike the 2D op (fixed int32 input_sizes), the 3D op
                # types its input_sizes operand via Tshape
                put("Tshape", in_dt(node, 0))
            outs = [t]
        elif op == "FusedBatchNorm":
            put("T", t0)
            outs = [t0] * 5
        elif op in ("FusedBatchNormV2", "FusedBatchNormV3"):
            u = in_dt(node, 1)
            put("T", t0)
            put("U", u)
            outs = [t0] + [u] * (5 if op.endswith("V3") else 4)
        elif op in ("SpaceToBatchND", "BatchToSpaceND"):
            put("T", t0)
            put("Tblock_shape", in_dt(node, 1))
            key = "Tpaddings" if op == "SpaceToBatchND" else "Tcrops"
            put(key, in_dt(node, 2))
            outs = [t0]
        elif op == "ResizeBilinear":
            put("T", t0)
            outs = [_F32]
        elif op in ("DecodeJpeg", "DecodePng", "DecodeBmp", "DecodeImage"):
            outs = [have("dtype") or _U8]
        # unknown op: leave attrs alone; outs defaults to [first input dtype]

        out_dtypes[node.name] = outs
        new_nodes.append(node)

    # preserve the caller's node order (topo order was only for inference)
    order = {n.name: i for i, n in enumerate(graph.nodes)}
    new_nodes.sort(key=lambda n: order[n.name])
    # the FunctionDefLibrary passes through untouched: dropping it would
    # leave If/StatelessIf/PartitionedCall nodes with dangling function
    # refs that real TF rejects (ADVICE r5 medium).  Function bodies are
    # not attr-completed — TF-built FunctionDefs already carry their attrs
    return GraphDef(new_nodes, dict(graph.functions))
