"""Minimal protobuf wire-format codec (pure python, no deps).

Implements just enough of the public protobuf encoding
(https://protobuf.dev/programming-guides/encoding/) to read and write TF
``GraphDef`` messages: varints, 64/32-bit fixed fields, and length-delimited
fields.  Deprecated group wire types are skipped.  This replaces the
reference's ~46k lines of generated protobuf-java bindings (SURVEY.md §2.5)
with ~150 lines, because the framework only *interchanges* GraphDefs — it
never executes from them directly.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Tuple

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_LEN = 2
WIRE_SGROUP = 3
WIRE_EGROUP = 4
WIRE_FIXED32 = 5


class WireError(ValueError):
    """Malformed protobuf bytes."""


# -- decoding ---------------------------------------------------------------


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise WireError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise WireError("varint too long")


def _skip_group(buf: bytes, pos: int, field: int) -> int:
    while True:
        tag, pos = read_varint(buf, pos)
        f, wt = tag >> 3, tag & 7
        if wt == WIRE_EGROUP:
            if f != field:
                raise WireError("mismatched group end")
            return pos
        _, _, pos = _read_value(buf, pos, f, wt)


def _read_value(buf: bytes, pos: int, field: int, wt: int):
    if wt == WIRE_VARINT:
        v, pos = read_varint(buf, pos)
        return field, v, pos
    if wt == WIRE_FIXED64:
        if pos + 8 > len(buf):
            raise WireError("truncated fixed64")
        return field, buf[pos : pos + 8], pos + 8
    if wt == WIRE_LEN:
        n, pos = read_varint(buf, pos)
        if pos + n > len(buf):
            raise WireError("truncated length-delimited field")
        return field, buf[pos : pos + n], pos + n
    if wt == WIRE_FIXED32:
        if pos + 4 > len(buf):
            raise WireError("truncated fixed32")
        return field, buf[pos : pos + 4], pos + 4
    if wt == WIRE_SGROUP:
        return field, None, _skip_group(buf, pos, field)
    raise WireError(f"unknown wire type {wt}")


def fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield ``(field_number, wire_type, value)`` triples.

    Values: int for varint, bytes for fixed/length-delimited, None for
    skipped groups.
    """
    pos = 0
    while pos < len(buf):
        tag, pos = read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        field, v, pos = _read_value(buf, pos, field, wt)
        yield field, wt, v


def zigzag_decode(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def decode_signed_varint(v: int) -> int:
    """Interpret a varint as two's-complement int64 (proto int64 fields)."""
    if v >= 1 << 63:
        v -= 1 << 64
    return v


def unpack_packed(data: bytes, fmt: str) -> List:
    """Unpack a packed repeated scalar field (e.g. '<f' floats)."""
    size = struct.calcsize(fmt)
    if len(data) % size:
        raise WireError("packed field length mismatch")
    return [x[0] for x in struct.iter_unpack(fmt, data)]


def unpack_packed_varints(data: bytes, signed: bool = True) -> List[int]:
    out = []
    pos = 0
    while pos < len(data):
        v, pos = read_varint(data, pos)
        out.append(decode_signed_varint(v) if signed else v)
    return out


# -- encoding ---------------------------------------------------------------


def write_varint(out: bytearray, v: int) -> None:
    if v < 0:
        v += 1 << 64  # two's-complement encoding for negative int64
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def write_tag(out: bytearray, field: int, wt: int) -> None:
    write_varint(out, (field << 3) | wt)


def write_len_field(out: bytearray, field: int, data: bytes) -> None:
    write_tag(out, field, WIRE_LEN)
    write_varint(out, len(data))
    out.extend(data)


def write_varint_field(out: bytearray, field: int, v: int) -> None:
    write_tag(out, field, WIRE_VARINT)
    write_varint(out, v)


def write_fixed32_field(out: bytearray, field: int, data: bytes) -> None:
    write_tag(out, field, WIRE_FIXED32)
    out.extend(data)
