"""Lower a parsed GraphDef to a :class:`~tensorframes_tpu.program.Program`.

The analog of the reference's ``analyzeGraphTF`` + session execution
(``TensorFlowOps.scala:101-141``, ``DebugRowOps.scala:783-801``): inputs are
the graph's ``Placeholder`` nodes (zero-input nodes of placeholder type —
same identification rule as ``TensorFlowOps.scala:106-108``), outputs are the
requested fetches, and the node graph is evaluated lazily over jax values.

Constant folding falls out of the evaluation model: ``Const`` nodes produce
host numpy arrays, numpy-only subgraphs stay numpy (TF graphs encode shape /
reduction-index operands as Const inputs), and only values derived from
placeholders become traced jax values.  Ops that structurally require static
operands (Reshape targets, axes, paddings) therefore see real numpy arrays
whenever the graph is a legal frozen graph.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

from .. import dtypes as dt
from ..program import Program, ProgramError
from ..shape import Shape
from . import decode as decode_mod
from . import ops as op_registry
from .proto import GraphDef, NodeDef, TensorProto, parse_graphdef

_PLACEHOLDER_OPS = ("Placeholder", "PlaceholderV2", "PlaceholderWithDefault")

# dead-branch sentinel for statically-resolved v1 conds (Switch/Merge)
_DEAD = object()

# flat output-tuple position of each named output arg, for the function-
# body ref grammar ``node:out_arg:idx`` (multi-output ops only; a single
# output arg resolves by idx alone — covers number_attr outputs like
# Split's)
_OUTPUT_ARGS = {
    "TopKV2": ("values", "indices"),
    "Switch": ("output_false", "output_true"),
    "Merge": ("output", "value_index"),
    "FusedBatchNorm": ("y", "batch_mean", "batch_variance",
                       "reserve_space_1", "reserve_space_2"),
    "FusedBatchNormV2": ("y", "batch_mean", "batch_variance",
                         "reserve_space_1", "reserve_space_2"),
    "FusedBatchNormV3": ("y", "batch_mean", "batch_variance",
                         "reserve_space_1", "reserve_space_2",
                         "reserve_space_3"),
}

_MAX_FUNC_DEPTH = 16


def _func_attr(node: NodeDef, key: str) -> str:
    av = node.attrs.get(key)
    if av is None or av.kind != "func":
        raise GraphImportError(
            f"node {node.name!r} ({node.op}) is missing function attr "
            f"{key!r}"
        )
    return av.value[0]


def _static_bool_pred(pred, what: str):
    """None when the predicate is traced (-> caller raises); else bool."""
    try:
        arr = np.asarray(pred)  # tracers refuse this
    except Exception:
        return None
    if arr.dtype != np.bool_:
        raise GraphImportError(f"{what} predicate has dtype {arr.dtype}; "
                               f"expected bool")
    if arr.size != 1:
        # bool(arr) on a multi-element array would raise numpy's opaque
        # "truth value of an array is ambiguous" — name the node instead
        raise GraphImportError(
            f"{what} predicate has shape {arr.shape}; expected a scalar "
            f"bool (a control-flow predicate must be a single value)"
        )
    return bool(arr.reshape(()))


def _eval_function(graph: GraphDef, fname: str, args, depth: int):
    """Inline-evaluate a library FunctionDef body (the branch functions
    TF2 control flow calls): args bind to the signature's input_args,
    body nodes evaluate through the op registry, and the signature's
    output_args resolve through the ``ret`` map.  Returns the flat list
    of output values."""
    if depth > _MAX_FUNC_DEPTH:
        raise GraphImportError(
            f"function call depth exceeds {_MAX_FUNC_DEPTH} at {fname!r}"
        )
    fd = graph.functions.get(fname)
    if fd is None:
        raise GraphImportError(
            f"GraphDef library has no function {fname!r}; functions: "
            f"{sorted(graph.functions)}"
        )
    if len(args) != len(fd.input_args):
        raise GraphImportError(
            f"function {fname!r} takes {len(fd.input_args)} args, got "
            f"{len(args)}"
        )
    env: Dict[str, Any] = {an: v for (an, _), v in zip(fd.input_args, args)}
    nodes = {n.name: n for n in fd.nodes}

    def resolve(ref: str):
        parts = ref.split(":")
        if len(parts) == 1:
            if ref not in env:
                raise GraphImportError(
                    f"function {fname!r}: bare ref {ref!r} is not an "
                    f"input arg"
                )
            return env[ref]
        if len(parts) != 3:
            raise GraphImportError(
                f"function {fname!r}: malformed body ref {ref!r}"
            )
        node_name, out_arg, idx = parts[0], parts[1], int(parts[2])
        if node_name not in env:
            raise GraphImportError(
                f"function {fname!r}: ref {ref!r} precedes its node "
                f"(bodies must be topologically ordered)"
            )
        val = env[node_name]
        node_op = nodes[node_name].op if node_name in nodes else None
        names = _OUTPUT_ARGS.get(node_op)
        if names is not None:
            if out_arg not in names:
                raise GraphImportError(
                    f"function {fname!r}: {node_op} has no output arg "
                    f"{out_arg!r} (ref {ref!r})"
                )
            # flat tuple position = the named arg's slot plus the index
            # WITHIN that arg: every op in _OUTPUT_ARGS today has
            # single-tensor output args (idx always 0), but a future
            # number_attr-sized output arg must not silently alias the
            # arg's slot 0 (advisor, round 5).  The base is exact only
            # while the PRECEDING args are single tensors, so indexing
            # into a non-final arg is refused rather than mis-resolved.
            if idx != 0 and out_arg != names[-1]:
                raise GraphImportError(
                    f"function {fname!r}: ref {ref!r} indexes into "
                    f"output arg {out_arg!r} of {node_op}, which "
                    f"precedes other output args; flat positions after "
                    f"a sized arg are unknown — extend _OUTPUT_ARGS "
                    f"with per-arg sizes to support this op"
                )
            # Remaining limitation, by construction: names.index assumes
            # every arg BEFORE out_arg is a single tensor, so a sized
            # NON-final arg would shift later names' bases undetectably
            # (len(val) vs len(names) cannot say WHICH arg grew).  No op
            # in the table has one today; adding one requires per-arg
            # sizes here, and the guard above already refuses the
            # detectable inner-index form.
            flat = names.index(out_arg) + idx
        else:
            flat = idx  # single output arg (possibly number_attr-sized)
        if isinstance(val, tuple):
            return val[flat]
        if flat != 0:
            raise GraphImportError(
                f"function {fname!r}: node {node_name!r} is "
                f"single-output, ref {ref!r}"
            )
        return val

    for node in fd.nodes:  # FunctionDef bodies are serialized in topo order
        if node.op == "Const":
            av = node.attrs.get("value")
            if av is None or not isinstance(av.value, TensorProto):
                raise GraphImportError(
                    f"function {fname!r}: Const {node.name!r} has no value"
                )
            env[node.name] = av.value.value
            continue
        if node.op in ("If", "StatelessIf"):
            ins = [resolve(r) for r in node.inputs if not r.startswith("^")]
            taken = _static_bool_pred(ins[0], f"{node.op} {node.name!r}")
            if taken is None:
                raise op_registry.UnsupportedOpError(
                    f"{node.op} node {node.name!r} has a data-dependent "
                    f"predicate; only constant-predicate conds are "
                    f"supported"
                )
            branch = _func_attr(
                node, "then_branch" if taken else "else_branch")
            outs = _eval_function(graph, branch, ins[1:], depth + 1)
            env[node.name] = outs[0] if len(outs) == 1 else tuple(outs)
            continue
        if node.op in ("PartitionedCall", "StatefulPartitionedCall"):
            ins = [resolve(r) for r in node.inputs if not r.startswith("^")]
            outs = _eval_function(
                graph, _func_attr(node, "f"), ins, depth + 1)
            env[node.name] = outs[0] if len(outs) == 1 else tuple(outs)
            continue
        impl = op_registry.REGISTRY.get(node.op)
        if impl is None:
            raise op_registry.UnsupportedOpError(
                f"function {fname!r}: op {node.op!r} (node "
                f"{node.name!r}) has no JAX lowering"
            )
        ins = [resolve(r) for r in node.inputs if not r.startswith("^")]
        env[node.name] = impl(ins, node.attrs)

    out_vals = []
    for out_arg, _ in fd.output_args:
        ref = fd.ret.get(out_arg)
        if ref is None:
            raise GraphImportError(
                f"function {fname!r}: ret map lacks output {out_arg!r}"
            )
        out_vals.append(resolve(ref))
    return out_vals


class GraphImportError(ValueError):
    """The GraphDef cannot be lowered (unknown op, bad fetch, cycle...).

    ``code``: the stable ``TFSxxx`` diagnostic code (``docs/ANALYSIS.md``)
    that ``tfs.check`` reports for the same failure pre-dispatch —
    ``TFS121`` for decode-prelude contract violations, ``TFS123`` for
    structural import errors (the default)."""

    def __init__(self, message: str, code: str = "TFS123"):
        super().__init__(message)
        self.code = code


def load_graphdef(source: Union[str, bytes, os.PathLike]) -> GraphDef:
    """Load from serialized bytes or a ``.pb`` file path (the reference's two
    ingestion paths: ``PythonOpBuilder.graph``/``graphFromFile``,
    ``PythonInterface.scala:110-118``)."""
    if isinstance(source, (str, os.PathLike)):
        with open(source, "rb") as f:
            data = f.read()
    else:
        data = bytes(source)
    return parse_graphdef(data)


def _split_ref(ref: str) -> Tuple[str, int]:
    if ref.startswith("^"):  # control dependency — ordering only, no data
        return ref[1:], -1
    if ":" in ref:
        name, idx = ref.rsplit(":", 1)
        return name, int(idx)
    return ref, 0


def import_graphdef(
    graph: Union[GraphDef, bytes, str, os.PathLike],
    fetches: Sequence[str],
    inputs: Optional[Mapping[str, str]] = None,
    outputs: Optional[Mapping[str, str]] = None,
) -> Program:
    """Build a Program from a frozen GraphDef.

    ``fetches``: output tensor names (``"out"`` or ``"out:0"``).
    ``inputs``: placeholder name -> frame column (the reference feed-dict,
    ``PythonInterface.scala:120-127``).
    ``outputs``: fetch ref -> result column name — the output-direction
    rename needed when a frozen graph's node names don't follow a verb's
    naming contract (e.g. an Add node ``out`` driving ``reduce_rows`` over
    column ``z`` must surface as output ``z``).
    """
    if not isinstance(graph, GraphDef):
        graph = load_graphdef(graph)
    nodes = graph.node_map()
    if not nodes:
        raise GraphImportError("GraphDef has no nodes")

    out_map = dict(outputs or {})
    unknown = set(out_map) - {f for f in fetches}
    if unknown:
        raise GraphImportError(
            f"outputs maps unknown fetch(es) {sorted(unknown)}; "
            f"fetches: {list(fetches)}"
        )
    bad = [k for k, v in out_map.items() if not v or not isinstance(v, str)]
    if bad:
        raise GraphImportError(
            f"outputs renames for {sorted(bad)} must be non-empty strings"
        )
    fetch_list: List[Tuple[str, str, int]] = []
    for f in fetches:
        name, idx = _split_ref(f)
        if name not in nodes:
            raise GraphImportError(
                f"fetch {f!r} not found in graph; nodes: "
                f"{sorted(nodes)[:20]}{'...' if len(nodes) > 20 else ''}"
            )
        out_name = out_map.get(f, name if idx == 0 else f"{name}_{idx}")
        fetch_list.append((out_name, name, idx))
    if not fetch_list:
        raise GraphImportError("no fetches requested")
    dup = {n for n in (o for o, _, _ in fetch_list)
           if sum(1 for o, _, _ in fetch_list if o == n) > 1}
    if dup:
        raise GraphImportError(
            f"fetches produce colliding output name(s) {sorted(dup)}; "
            f"disambiguate with the outputs rename map"
        )

    # prune to the transitive closure of the fetches (TF session pruning —
    # placeholders outside the closure must not become required inputs)
    reachable: set = set()
    stack = [name for _, name, _ in fetch_list]
    while stack:
        cur = stack.pop()
        if cur in reachable:
            continue
        reachable.add(cur)
        node = nodes.get(cur)
        if node is not None:
            for ref in node.inputs:
                rn, _ = _split_ref(ref)
                stack.append(rn)
    placeholders: List[NodeDef] = [
        n
        for n in graph.nodes
        if n.op in _PLACEHOLDER_OPS
        and n.name in reachable
        and not (n.op == "PlaceholderWithDefault" and n.inputs)
    ]

    input_names = [p.name for p in placeholders]
    if not input_names:
        raise GraphImportError(
            "GraphDef has no Placeholder nodes; programs need at least one "
            "column-fed input"
        )

    # in-graph image decode (read_image.py:120-167 feeds encoded bytes to a
    # graph starting at DecodeJpeg): route each reachable Decode* node to a
    # host prelude on the placeholder that feeds it — XLA hosts neither
    # string tensors nor the data-dependent decoded shape
    decode_src: Dict[str, str] = {}  # decode node -> feeding placeholder
    host_prelude: Dict[str, Any] = {}
    ph_set = set(input_names)
    for n in graph.nodes:
        if n.op not in decode_mod.DECODE_OPS or n.name not in reachable:
            continue
        src, _ = _split_ref(n.inputs[0])
        seen = set()
        while (
            src in nodes
            and nodes[src].op in ("Identity", "Snapshot")
            and src not in seen
        ):
            seen.add(src)
            src, _ = _split_ref(nodes[src].inputs[0])
        if src not in ph_set:
            raise GraphImportError(
                f"{n.op} node {n.name!r} decodes a computed value; only "
                f"placeholder-fed bytes can be decoded (the decode runs as "
                f"a host stage before the device program)"
                , code="TFS121"
            )
        # attrs the PIL prelude cannot honour are rejected here, not
        # silently diverged from: TF's dtype attr rescales values
        # (float in [0,1], uint16) and ratio downsamples at decode
        dt_av = n.attrs.get("dtype")
        if dt_av is not None and dt_av.kind == "type" and dt_av.value != 4:
            raise GraphImportError(
                f"{n.op} node {n.name!r} requests dtype enum "
                f"{dt_av.value}; only uint8 decode is supported (pass an "
                f"explicit host_stage fn for other output types)"
                , code="TFS121"
            )
        ratio_av = n.attrs.get("ratio")
        if ratio_av is not None and ratio_av.kind == "i" and int(
            ratio_av.value
        ) not in (0, 1):
            raise GraphImportError(
                f"{n.op} node {n.name!r} requests decode ratio "
                f"{int(ratio_av.value)}; downsampling decode is not "
                f"supported (pass an explicit host_stage fn)"
                , code="TFS121"
            )
        ch_av = n.attrs.get("channels")
        channels = int(ch_av.value) if ch_av and ch_av.kind == "i" else 0
        if src in decode_src.values() and n.name not in decode_src:
            prev = next(d for d, s in decode_src.items() if s == src)
            prev_ch = host_prelude[src]._tfs_channels
            if int(channels) != prev_ch:
                raise GraphImportError(
                    f"placeholder {src!r} feeds decode nodes with "
                    f"conflicting channels ({prev!r} vs {n.name!r})"
                    , code="TFS121"
                )
        decode_src[n.name] = src
        fn = decode_mod.pil_decoder(channels, n.op)
        fn._tfs_channels = int(channels)
        host_prelude[src] = fn
    # A placeholder that feeds a Decode* prelude is re-fed DECODED uint8
    # pixels at run time, so any OTHER reachable consumer of its bytes —
    # beyond the Identity/Snapshot forwarding chain into the decoders —
    # would silently read pixels where the graph says encoded bytes.
    # Reject, naming both consumers (advisor, round 5).
    if host_prelude:
        byte_chain: Dict[str, str] = {ph: ph for ph in host_prelude}
        changed = True
        while changed:  # resolve Identity/Snapshot chains to fixpoint
            changed = False
            for n in graph.nodes:
                if (
                    n.name in reachable
                    and n.name not in byte_chain
                    and n.op in ("Identity", "Snapshot")
                    and n.inputs
                ):
                    src, _ = _split_ref(n.inputs[0])
                    if src in byte_chain:
                        byte_chain[n.name] = byte_chain[src]
                        changed = True
        for n in graph.nodes:
            if (
                n.name not in reachable
                or n.op in decode_mod.DECODE_OPS
                or n.name in byte_chain  # the forwarding chain itself
            ):
                continue
            for ref in n.inputs:
                rn, ri = _split_ref(ref)
                if ri == -1 or rn not in byte_chain:
                    continue
                ph = byte_chain[rn]
                decs = sorted(d for d, s in decode_src.items() if s == ph)
                raise GraphImportError(
                    f"placeholder {ph!r} feeds both a decode host prelude "
                    f"({', '.join(decs)}) and non-decode consumer "
                    f"{n.name!r} ({n.op}); the prelude replaces the fed "
                    f"bytes with decoded uint8 pixels, so {n.name!r} would "
                    f"silently receive pixels instead of the encoded "
                    f"bytes. Feed that consumer from its own placeholder, "
                    f"or decode explicitly via host_stage."
                    , code="TFS121"
                )
        for out, name, _ in fetch_list:
            if name in byte_chain:
                ph = byte_chain[name]
                decs = sorted(d for d, s in decode_src.items() if s == ph)
                raise GraphImportError(
                    f"fetch {out!r} reads placeholder {ph!r}, which feeds "
                    f"a decode host prelude ({', '.join(decs)}); the "
                    f"prelude replaces the fed bytes with decoded uint8 "
                    f"pixels, so the fetch would silently return pixels. "
                    f"Fetch the decode node instead, or feed the bytes "
                    f"through their own placeholder."
                    , code="TFS121"
                )
    feed = dict(inputs or {})
    for k in feed:
        if k not in input_names:
            raise GraphImportError(
                f"inputs maps unknown placeholder {k!r}; placeholders: "
                f"{input_names}"
            )

    # topological order of the reachable subgraph, computed ONCE at import
    # (iterative — Inception/VGG-class frozen graphs exceed Python's
    # recursion limit; cycles are detected here, not at call time)
    order: List[str] = []
    state: Dict[str, int] = {}  # 0=visiting, 1=done
    work: List[Tuple[str, bool]] = [
        (name, False) for _, name, _ in reversed(fetch_list)
    ]
    while work:
        name, processed = work.pop()
        if processed:
            state[name] = 1
            order.append(name)
            continue
        st = state.get(name)
        if st == 1:
            continue
        if st == 0:
            raise GraphImportError(f"cycle in GraphDef at node {name!r}")
        node = nodes.get(name)
        if node is None:
            raise GraphImportError(f"node {name!r} referenced but not defined")
        state[name] = 0
        work.append((name, True))
        for ref in node.inputs:
            rn, _ = _split_ref(ref)
            if state.get(rn) == 0:
                raise GraphImportError(f"cycle in GraphDef at node {rn!r}")
            if state.get(rn) != 1:
                work.append((rn, False))

    def _pick(name: str, v: Any, idx: int) -> Any:
        if idx == -1:  # control dependency: ordering only, no value
            return None
        if v is _DEAD:
            return _DEAD
        if isinstance(v, tuple):
            if idx >= len(v):
                raise GraphImportError(
                    f"node {name!r} has {len(v)} outputs, requested :{idx}"
                )
            return v[idx]
        if idx != 0:
            raise GraphImportError(
                f"node {name!r} is single-output, requested :{idx}"
            )
        return v

    def fn(**feeds):
        cache: Dict[str, Any] = dict(feeds)
        for name in order:
            if name in cache:
                continue
            node = nodes[name]
            # dead-tensor rule (TF): a node with ANY fully-dead input —
            # control edges included — is dead, except Merge, which is
            # precisely the op that survives dead data inputs
            if node.op != "Merge" and any(
                cache[_split_ref(ref)[0]] is _DEAD for ref in node.inputs
            ):
                cache[name] = _DEAD
                continue
            # v1 control flow with a STATIC predicate (frozen graphs keep
            # the Switch/Merge a tf.cond left behind when the predicate
            # froze to a Const): resolve the branch at import time — the
            # dead branch propagates a sentinel and is never executed,
            # matching TF's dead-tensor semantics
            if node.op in ("Switch", "RefSwitch"):
                data_refs = [r for r in node.inputs if not r.startswith("^")]
                dn, di = _split_ref(data_refs[0])
                pn, pi = _split_ref(data_refs[1])
                data = _pick(dn, cache[dn], di)
                pred = _pick(pn, cache[pn], pi)
                if data is _DEAD or pred is _DEAD:
                    cache[name] = _DEAD  # a nested cond in a dead branch
                    continue
                taken = _static_bool_pred(
                    pred, f"Switch node {name!r}")
                if taken is None:
                    raise op_registry.UnsupportedOpError(
                        f"Switch node {name!r} has a data-dependent "
                        f"predicate; only constant-predicate conds (the "
                        f"frozen-graph form) are supported"
                    )
                # output:0 = false branch, output:1 = true branch
                cache[name] = (
                    _DEAD if taken else data,
                    data if taken else _DEAD,
                )
                continue
            if node.op == "Merge":
                vals = []
                for ref in node.inputs:
                    rn, ri = _split_ref(ref)
                    if ri == -1:
                        continue
                    vals.append(_pick(rn, cache[rn], ri))
                alive = [
                    (i, v) for i, v in enumerate(vals) if v is not _DEAD
                ]
                if len(alive) == 0:
                    cache[name] = _DEAD  # whole cond sits in a dead branch
                    continue
                if len(alive) > 1:
                    raise op_registry.UnsupportedOpError(
                        f"Merge node {name!r} has {len(alive)} live "
                        f"inputs; exactly one branch must be statically "
                        f"selected (constant-predicate cond)"
                    )
                idx, val = alive[0]
                cache[name] = (val, np.int32(idx))
                continue
            if node.op == "Const":
                av = node.attrs.get("value")
                if av is None or not isinstance(av.value, TensorProto):
                    raise GraphImportError(
                        f"Const node {name!r} has no tensor value"
                    )
                cache[name] = av.value.value  # host numpy — const folding
                continue
            if node.op in ("If", "StatelessIf"):
                # TF2 control flow: branch FunctionDefs called by name —
                # same static-predicate contract as v1 Switch/Merge
                ins = []
                for ref in node.inputs:
                    rn, ri = _split_ref(ref)
                    if ri != -1:
                        ins.append(_pick(rn, cache[rn], ri))
                if any(v is _DEAD for v in ins):
                    cache[name] = _DEAD  # sits in a dead v1 branch
                    continue
                taken = _static_bool_pred(
                    ins[0], f"{node.op} node {name!r}")
                if taken is None:
                    raise op_registry.UnsupportedOpError(
                        f"{node.op} node {name!r} has a data-dependent "
                        f"predicate; only constant-predicate conds (the "
                        f"frozen-graph form) are supported"
                    )
                branch = _func_attr(
                    node, "then_branch" if taken else "else_branch")
                outs = _eval_function(graph, branch, ins[1:], 1)
                cache[name] = outs[0] if len(outs) == 1 else tuple(outs)
                continue
            if node.op in ("PartitionedCall", "StatefulPartitionedCall"):
                ins = []
                for ref in node.inputs:
                    rn, ri = _split_ref(ref)
                    if ri != -1:
                        ins.append(_pick(rn, cache[rn], ri))
                if any(v is _DEAD for v in ins):
                    cache[name] = _DEAD  # sits in a dead v1 branch
                    continue
                outs = _eval_function(
                    graph, _func_attr(node, "f"), ins, 1)
                cache[name] = outs[0] if len(outs) == 1 else tuple(outs)
                continue
            if node.op in _PLACEHOLDER_OPS:
                if node.op == "PlaceholderWithDefault" and node.inputs:
                    dn, di = _split_ref(node.inputs[0])
                    cache[name] = _pick(dn, cache[dn], di)
                    continue
                raise GraphImportError(
                    f"placeholder {name!r} was not fed; feeds: "
                    f"{sorted(feeds)}"
                )
            if node.op in decode_mod.DECODE_OPS:
                # the host prelude already decoded this placeholder's
                # bytes: the decode node's output IS the fed value
                cache[name] = cache[decode_src[name]]
                continue
            impl = op_registry.REGISTRY.get(node.op)
            if impl is None:
                raise op_registry.UnsupportedOpError(
                    f"GraphDef op {node.op!r} (node {name!r}) has no JAX "
                    f"lowering; supported ops: {sorted(op_registry.REGISTRY)}"
                )
            ins = []
            for ref in node.inputs:
                rn, ri = _split_ref(ref)
                v = _pick(rn, cache[rn], ri)
                if ri != -1:
                    ins.append(v)
            if any(v is _DEAD for v in ins):
                # inside a statically-dead cond branch: never execute,
                # propagate deadness toward the Merge (TF's dead-tensor
                # semantics)
                cache[name] = _DEAD
                continue
            cache[name] = impl(ins, node.attrs)
        result = {
            out: _pick(name, cache[name], idx) for out, name, idx in fetch_list
        }
        dead = sorted(k for k, v in result.items() if v is _DEAD)
        if dead:
            raise GraphImportError(
                f"fetch(es) {dead} lie inside a statically-dead cond "
                f"branch (their Switch predicate froze the other way)"
            )
        return result

    program = Program(
        fn,
        input_names,
        fetches=[out for out, _, _ in fetch_list],
        feed_dict=feed,
    )
    program.host_prelude.update(host_prelude)
    return program


def placeholder_specs(
    graph: Union[GraphDef, bytes, str, os.PathLike]
) -> Dict[str, Tuple[Optional[dt.ScalarType], Optional[Shape]]]:
    """Declared dtype/shape of each placeholder — the ``GraphNodeSummary``
    input half (``TensorFlowOps.scala:163-169``) read from attrs."""
    if not isinstance(graph, GraphDef):
        graph = load_graphdef(graph)
    out = {}
    for n in graph.nodes:
        if n.op in _PLACEHOLDER_OPS:
            ten = n.attrs.get("dtype")
            st = (
                dt.from_tf_enum(ten.value)
                if ten is not None and ten.kind == "type"
                else None
            )
            shp = n.attrs.get("shape")
            shape = shp.value if shp is not None and shp.kind == "shape" else None
            out[n.name] = (st, shape)
    return out
