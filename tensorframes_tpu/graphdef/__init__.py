"""GraphDef import: run frozen TF models as XLA programs — no TensorFlow dep.

The reference's whole execution model is "ship a serialized ``GraphDef`` to
the runtime" (``TensorFlowOps.scala:101-141``; the frozen-model scoring flow
``read_image.py:108-167`` is benchmark configs #3/#4 in BASELINE.json).  The
TPU-native equivalent keeps GraphDef as an *interchange* format only: a
minimal pure-python protobuf wire codec (``wire.py``/``proto.py``) parses the
graph, and ``importer.py`` lowers the node graph onto jax ops
(``ops.py`` registry), producing the same :class:`~tensorframes_tpu.program.Program`
every verb consumes.  Internally the IR is the jaxpr — protos never reach the
device (SURVEY.md §2.6).
"""

from .importer import import_graphdef, load_graphdef
from .proto import AttrValue, GraphDef, NodeDef, TensorProto, parse_graphdef

__all__ = [
    "import_graphdef",
    "load_graphdef",
    "parse_graphdef",
    "GraphDef",
    "NodeDef",
    "AttrValue",
    "TensorProto",
]
