"""Programmatic GraphDef construction.

The write-side counterpart of the importer: the reference's Scala DSL emits
``NodeDef`` protos (``dsl/DslImpl.scala:143-157``, ``ProtoConversions.scala``)
that are binary-compared against python TF's output in its golden tests
(``dsl/ExtractNodes.scala``).  Here the builder serves the same two purposes
TPU-natively: generating wire-format fixtures for importer tests without a
TensorFlow install, and exporting programs for interchange with TF tooling.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .. import dtypes as dt
from ..shape import Shape
from .proto import AttrValue, GraphDef, NodeDef, TensorProto


class GraphBuilder:
    """Accumulates NodeDefs; names must be unique (TF graph invariant)."""

    def __init__(self):
        self.nodes: List[NodeDef] = []
        self._names = set()

    def _add(
        self,
        op: str,
        name: str,
        inputs: Sequence[str] = (),
        attrs: Optional[Dict[str, AttrValue]] = None,
    ) -> str:
        if name in self._names:
            raise ValueError(f"duplicate node name {name!r}")
        self._names.add(name)
        self.nodes.append(NodeDef(name, op, list(inputs), attrs or {}))
        return name

    def placeholder(
        self, name: str, dtype="float32", shape: Optional[Sequence[int]] = None
    ) -> str:
        st = dtype if isinstance(dtype, dt.ScalarType) else dt.by_name(dtype)
        attrs = {"dtype": AttrValue("type", st.tf_enum)}
        if shape is not None:
            attrs["shape"] = AttrValue("shape", Shape(shape))
        return self._add("Placeholder", name, (), attrs)

    def const(self, name: str, value) -> str:
        tp = TensorProto.from_numpy(np.asarray(value))
        return self._add(
            "Const",
            name,
            (),
            {
                "value": AttrValue("tensor", tp),
                "dtype": AttrValue("type", tp.dtype),
            },
        )

    def op(
        self,
        op: str,
        name: str,
        inputs: Sequence[str],
        **attrs,
    ) -> str:
        encoded: Dict[str, AttrValue] = {}
        for k, v in attrs.items():
            if isinstance(v, AttrValue):
                encoded[k] = v
            elif isinstance(v, bool):
                encoded[k] = AttrValue("b", v)
            elif isinstance(v, int):
                encoded[k] = AttrValue("i", v)
            elif isinstance(v, float):
                encoded[k] = AttrValue("f", v)
            elif isinstance(v, bytes):
                encoded[k] = AttrValue("s", v)
            elif isinstance(v, str):
                encoded[k] = AttrValue("s", v.encode())
            elif isinstance(v, (list, tuple)):
                encoded[k] = AttrValue("list", list(v))
            else:
                raise ValueError(
                    f"cannot encode attr {k}={v!r} ({type(v).__name__})"
                )
        return self._add(op, name, inputs, encoded)

    def build(self) -> GraphDef:
        return GraphDef(list(self.nodes))

    def to_bytes(self) -> bytes:
        """Serialize, with TF-required dtype/count attrs filled in
        (``tfcompat.complete_for_tf``) so the emitted bytes import into a
        real TensorFlow, not only into our own importer — the contract the
        reference's golden tests pin (``ExtractNodes.scala:14-74``)."""
        from .tfcompat import complete_for_tf

        return complete_for_tf(self.build()).encode()
