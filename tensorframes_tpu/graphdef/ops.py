"""TF op -> JAX lowering registry for GraphDef import.

Covers the op vocabulary of the reference's workloads: the DSL-emitted ops
(``dsl/DslImpl.scala`` emits Placeholder/Const/Identity/Add/Div/Sum/Min with
``reduction_indices``), the test graphs (``graph.pb``/``graph2.pb``: Const +
Placeholder + Add), and the frozen-model scoring vocabulary
(``read_image.py``'s VGG/Inception class of graphs: Conv2D, pooling, batch
norm, activations, dense layers) plus the K-Means demo's
``unsorted_segment_sum``/``argmin`` pre-aggregation kernel
(``kmeans_demo.py:101-168``).

Each entry maps ``(inputs, attrs) -> jax value(s)``; multi-output ops return
tuples and consumers address them as ``node:k``.  Reduction/shape operands
that TF passes as const *inputs* (reduction_indices, shape, paddings, axis)
must be compile-time constants — the importer resolves them via constant
folding before lowering (XLA needs static shapes; SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import dtypes as dt


class UnsupportedOpError(NotImplementedError):
    """A GraphDef node's op has no JAX lowering registered.

    ``code``: the stable ``TFSxxx`` diagnostic code (``docs/ANALYSIS.md``)
    ``tfs.check`` reports for the same failure pre-dispatch."""

    code = "TFS120"


def _attr(attrs, name, default=None):
    av = attrs.get(name)
    return default if av is None or av.kind == "none" else av.value


def _static(x, what: str) -> np.ndarray:
    """Require a compile-time constant operand (e.g. reshape target)."""
    if isinstance(x, np.ndarray):
        return x
    if isinstance(x, (int, float, list, tuple)):
        return np.asarray(x)
    raise UnsupportedOpError(
        f"{what} must be a compile-time constant in the imported graph "
        f"(got a traced value); freeze it into the GraphDef"
    )


def _np_dtype(attrs, key="T", default=np.float32):
    en = _attr(attrs, key)
    return dt.from_tf_enum(en).np_dtype if en is not None else default


def _axes(v) -> Optional[Tuple[int, ...]]:
    a = np.asarray(v).reshape(-1)
    return tuple(int(x) for x in a)


def _str_attr(attrs, name: str, default: bytes) -> str:
    v = _attr(attrs, name, default)
    return v.decode() if isinstance(v, bytes) else str(v)


def _padding_str(attrs) -> str:
    return _str_attr(attrs, "padding", b"VALID")


def _pool(x, attrs, reducer, init, avg=False):
    ksize = [int(k) for k in _attr(attrs, "ksize")]
    strides = [int(s) for s in _attr(attrs, "strides")]
    padding = _padding_str(attrs)
    default_fmt = b"NDHWC" if len(ksize) == 5 else b"NHWC"
    fmt = _str_attr(attrs, "data_format", default_fmt)
    if fmt not in ("NHWC", "NDHWC"):
        raise UnsupportedOpError(f"pooling data_format {fmt} not supported")
    out = lax.reduce_window(
        x, init, reducer, tuple(ksize), tuple(strides), padding
    )
    if avg:
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(
            ones, 0.0, lax.add, tuple(ksize), tuple(strides), padding
        )
        out = out / counts
    return out


def _conv2d(ins, attrs):
    x, w = ins
    strides = [int(s) for s in _attr(attrs, "strides", [1, 1, 1, 1])]
    dilations = [int(d) for d in _attr(attrs, "dilations", [1, 1, 1, 1])]
    padding = _padding_str(attrs)
    fmt = _str_attr(attrs, "data_format", b"NHWC")
    if fmt != "NHWC":
        raise UnsupportedOpError(f"Conv2D data_format {fmt} not supported")
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=strides[1:3],
        padding=padding,
        rhs_dilation=dilations[1:3],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _conv3d(ins, attrs):
    # the gap-table promise (docs/GRAPHDEF_OPS.md): same lowering as
    # Conv2D with three spatial dims
    x, w = ins
    strides = [int(s) for s in _attr(attrs, "strides", [1] * 5)]
    dilations = [int(d) for d in _attr(attrs, "dilations", [1] * 5)]
    padding = _padding_str(attrs)
    fmt = _str_attr(attrs, "data_format", b"NDHWC")
    if fmt != "NDHWC":
        raise UnsupportedOpError(f"Conv3D data_format {fmt} not supported")
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=strides[1:4],
        padding=padding,
        rhs_dilation=dilations[1:4],
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )


def _mirror_pad(ins, attrs):
    x, pads = ins
    mode = _str_attr(attrs, "mode", b"REFLECT")
    if mode not in ("REFLECT", "SYMMETRIC"):
        raise UnsupportedOpError(f"MirrorPad mode {mode} not supported")
    pads = np.asarray(_static(pads, "MirrorPad paddings")).astype(int)
    return jnp.pad(
        x,
        [(int(a), int(b)) for a, b in pads],
        # numpy "reflect" excludes the edge (TF REFLECT); "symmetric"
        # repeats it (TF SYMMETRIC)
        mode="reflect" if mode == "REFLECT" else "symmetric",
    )


def _depthwise_conv2d(ins, attrs):
    x, w = ins  # w: [H, W, C, M]
    strides = [int(s) for s in _attr(attrs, "strides", [1, 1, 1, 1])]
    padding = _padding_str(attrs)
    h, wd, c, m = w.shape
    # feature_group_count=C expects flat output channel index c*M + m, which
    # is exactly the [H,W,C,M] memory order — reshape directly, NO transpose
    w2 = jnp.reshape(w, (h, wd, 1, c * m))
    return lax.conv_general_dilated(
        x,
        w2,
        window_strides=strides[1:3],
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )


def _fused_batch_norm(ins, attrs):
    x, scale, offset, mean, var = ins
    eps = float(_attr(attrs, "epsilon", 1e-3))
    is_training = bool(_attr(attrs, "is_training", False))
    if is_training:
        raise UnsupportedOpError(
            "FusedBatchNorm with is_training=True is not supported for "
            "frozen-graph scoring"
        )
    inv = lax.rsqrt(var + eps) * scale
    y = x * inv + (offset - mean * inv)
    return (y, mean, var, mean, var)


def _strided_slice(ins, attrs):
    x, begin, end, strides = ins
    begin = _static(begin, "StridedSlice begin").tolist()
    end = _static(end, "StridedSlice end").tolist()
    strides = _static(strides, "StridedSlice strides").tolist()
    begin_mask = int(_attr(attrs, "begin_mask", 0))
    end_mask = int(_attr(attrs, "end_mask", 0))
    ellipsis_mask = int(_attr(attrs, "ellipsis_mask", 0))
    new_axis_mask = int(_attr(attrs, "new_axis_mask", 0))
    shrink_mask = int(_attr(attrs, "shrink_axis_mask", 0))
    if ellipsis_mask or new_axis_mask:
        raise UnsupportedOpError(
            "StridedSlice ellipsis/new_axis masks not supported"
        )
    idx = []
    for i in range(len(begin)):
        if shrink_mask & (1 << i):
            idx.append(int(begin[i]))
            continue
        b = None if begin_mask & (1 << i) else int(begin[i])
        e = None if end_mask & (1 << i) else int(end[i])
        idx.append(slice(b, e, int(strides[i])))
    return x[tuple(idx)]


def _concat_v2(ins, attrs):
    axis = int(_static(ins[-1], "ConcatV2 axis"))
    return jnp.concatenate(ins[:-1], axis=axis)


def resize_bilinear(
    x,
    out_h: int,
    out_w: int,
    align_corners: bool = False,
    half_pixel_centers: bool = False,
):
    """TF-1.x ``ResizeBilinear`` semantics (legacy kernel: source coord =
    ``out_idx * in/out`` unless align_corners/half_pixel_centers).

    Exposed as a public helper so native models (``models/vgg.py``) use
    THE SAME resize as imported frozen graphs — exporting a model and
    re-importing it cannot diverge on resize convention.  Output is
    float32 like TF's kernel (uint8 inputs included)."""
    x = jnp.asarray(x, jnp.float32)
    n, h, w, c = x.shape

    def coords(out: int, size: int):
        if align_corners and out > 1:
            src = jnp.arange(out, dtype=jnp.float32) * (
                (size - 1) / (out - 1)
            )
        else:
            idx = jnp.arange(out, dtype=jnp.float32)
            scale = size / out
            src = (idx + 0.5) * scale - 0.5 if half_pixel_centers else (
                idx * scale
            )
        src = jnp.clip(src, 0.0, size - 1)
        lo = jnp.floor(src).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, size - 1)
        return lo, hi, src - lo

    hl, hh, hf = coords(out_h, h)
    wl, wh, wf = coords(out_w, w)
    xh = (
        x[:, hl] * (1.0 - hf)[None, :, None, None]
        + x[:, hh] * hf[None, :, None, None]
    )
    return (
        xh[:, :, wl] * (1.0 - wf)[None, None, :, None]
        + xh[:, :, wh] * wf[None, None, :, None]
    )


def _resize_bilinear_op(ins, attrs):
    size = _static(ins[1], "ResizeBilinear size").reshape(-1)
    return resize_bilinear(
        ins[0],
        int(size[0]),
        int(size[1]),
        align_corners=bool(_attr(attrs, "align_corners", False)),
        half_pixel_centers=bool(_attr(attrs, "half_pixel_centers", False)),
    )


def _resize_nearest_op(ins, attrs):
    size = _static(ins[1], "ResizeNearestNeighbor size").reshape(-1)
    x = ins[0]
    n, h, w, c = x.shape
    out_h, out_w = int(size[0]), int(size[1])
    align = bool(_attr(attrs, "align_corners", False))
    half = bool(_attr(attrs, "half_pixel_centers", False))

    def idx(out, sz):
        if align and out > 1:
            src = jnp.arange(out, dtype=jnp.float32) * ((sz - 1) / (out - 1))
            return jnp.round(src).astype(jnp.int32)
        scale = sz / out
        i = jnp.arange(out, dtype=jnp.float32)
        src = jnp.floor((i + 0.5) * scale) if half else jnp.floor(i * scale)
        return jnp.clip(src.astype(jnp.int32), 0, sz - 1)

    return x[:, idx(out_h, h)][:, :, idx(out_w, w)]


def _lrn(ins, attrs):
    """TF ``LRN``: x / (bias + alpha * sum_{window over channels} x^2)^beta
    (AlexNet-era local response normalisation; depth_radius default 5)."""
    x = ins[0]
    r = int(_attr(attrs, "depth_radius", 5))
    bias = float(_attr(attrs, "bias", 1.0))
    alpha = float(_attr(attrs, "alpha", 1.0))
    beta = float(_attr(attrs, "beta", 0.5))
    sq = x * x
    win = lax.reduce_window(
        sq,
        0.0,
        lax.add,
        (1, 1, 1, 2 * r + 1),
        (1, 1, 1, 1),
        [(0, 0), (0, 0), (0, 0), (r, r)],
    )
    return x / (bias + alpha * win) ** beta


def _range(ins):
    # output dtype follows Tidx = the operands' dtype (TF emits int32
    # Range from int32 starts; numpy's platform default would widen it)
    start = np.asarray(_static(ins[0], "Range start"))
    return np.arange(
        start.item(),
        np.asarray(_static(ins[1], "Range limit")).item(),
        np.asarray(_static(ins[2], "Range delta")).item(),
        dtype=start.dtype,
    )


def _split_v(ins):
    sizes = np.asarray(
        _static(ins[1], "SplitV size_splits"), dtype=np.int64
    ).reshape(-1)
    axis = int(_static(ins[2], "SplitV axis"))
    dim = ins[0].shape[axis]
    neg = np.flatnonzero(sizes < 0)
    if neg.size > 1:
        raise UnsupportedOpError(
            "SplitV size_splits may contain at most one -1"
        )
    if neg.size == 1:  # TF's remainder convention: -1 = what's left
        sizes = sizes.copy()
        sizes[neg[0]] = dim - (sizes.sum() - sizes[neg[0]])
    return tuple(jnp.split(ins[0], np.cumsum(sizes[:-1]).tolist(), axis=axis))


def _one_hot(ins, attrs):
    indices, depth, on, off = ins
    axis = int(_attr(attrs, "axis", -1))
    # output dtype is T = on/off_value's dtype (one_hot's own float default
    # would widen f32 graphs to f64 under x64)
    return jax.nn.one_hot(
        indices,
        int(_static(depth, "OneHot depth")),
        axis=axis,
        dtype=jnp.result_type(on),
    ) * (on - off) + off


def _space_depth(ins, attrs, to_depth: bool):
    x = ins[0]
    bs = int(_attr(attrs, "block_size"))
    n, h, w, c = x.shape
    if to_depth:
        x = jnp.reshape(x, (n, h // bs, bs, w // bs, bs, c))
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
        return jnp.reshape(x, (n, h // bs, w // bs, bs * bs * c))
    x = jnp.reshape(x, (n, h, w, bs, bs, c // (bs * bs)))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return jnp.reshape(x, (n, h * bs, w * bs, c // (bs * bs)))


def _conv_backprop_input(ins, attrs, spatial: int, op_name: str):
    """TF ``Conv{2,3}DBackpropInput`` used as a DECONV layer in inference
    graphs (segmentation/upsampling nets): the gradient of the forward
    conv w.r.t. its input, applied as a forward op.

    Lowered in the exact adjoint form — an lhs-dilated conv of the
    spatially-flipped, channel-swapped kernel with per-edge padding
    derived from the FORWARD conv's padding — so every ``input_sizes``
    TF accepts round-trips exactly, including odd SAME shapes with
    stride 2 (the classic DeepLab 65x65) and dilated kernels."""
    in_shape = [int(d) for d in _static(ins[0], f"{op_name} input_sizes")]
    # w: [*K, Cin, Cout]; dy: [N, *out_spatial, Cout]
    w, dy = ins[1], ins[2]
    ones = [1] * (spatial + 2)
    strides = [int(s) for s in _attr(attrs, "strides", ones)]
    dilations = [int(d) for d in _attr(attrs, "dilations", ones)]
    padding = _padding_str(attrs)
    default_fmt = b"NDHWC" if spatial == 3 else b"NHWC"
    fmt = _str_attr(attrs, "data_format", default_fmt)
    if fmt != default_fmt.decode():
        raise UnsupportedOpError(
            f"{op_name} data_format {fmt} not supported"
        )
    if padding not in ("SAME", "VALID"):
        raise UnsupportedOpError(
            f"{op_name} padding {padding!r} not supported (EXPLICIT "
            f"paddings would silently change the adjoint arithmetic)"
        )
    pads = []
    for i in range(spatial):
        hi_in, ho = in_shape[1 + i], dy.shape[1 + i]
        s, d, k = strides[1 + i], dilations[1 + i], w.shape[i]
        k_eff = (k - 1) * d + 1
        if padding == "SAME":
            total = max((ho - 1) * s + k_eff - hi_in, 0)
            fwd_lo = total // 2
        else:  # VALID
            fwd_lo = 0
        lo = k_eff - 1 - fwd_lo
        hi = hi_in - 1 - (ho - 1) * s + fwd_lo
        pads.append((lo, hi))
    w2 = jnp.flip(jnp.asarray(w), tuple(range(spatial)))
    w2 = w2.swapaxes(spatial, spatial + 1)  # [*K, Cout, Cin]
    io_layout = ("NDHWC", "DHWIO", "NDHWC") if spatial == 3 else (
        "NHWC", "HWIO", "NHWC")
    return lax.conv_general_dilated(
        dy,
        w2,
        window_strides=(1,) * spatial,
        padding=pads,
        lhs_dilation=tuple(strides[1:1 + spatial]),
        rhs_dilation=tuple(dilations[1:1 + spatial]),
        dimension_numbers=io_layout,
    )


def _conv2d_backprop_input(ins, attrs):
    return _conv_backprop_input(ins, attrs, 2, "Conv2DBackpropInput")


def _space_to_batch_nd(ins, attrs):
    x = ins[0]
    block = [int(b) for b in _static(ins[1], "SpaceToBatchND block_shape")]
    pads = _static(ins[2], "SpaceToBatchND paddings")
    pad_width = [(0, 0)] + [
        (int(a), int(b)) for a, b in pads
    ] + [(0, 0)] * (x.ndim - 1 - len(block))
    x = jnp.pad(x, pad_width)
    n = x.shape[0]
    spatial = x.shape[1 : 1 + len(block)]
    rest = x.shape[1 + len(block):]
    # [N, s1/b1, b1, s2/b2, b2, ..., rest] -> [b1 b2 ... N, s/b..., rest]
    shape = [n]
    for s, b in zip(spatial, block):
        shape += [s // b, b]
    x = jnp.reshape(x, shape + list(rest))
    nb = len(block)
    perm = (
        [2 * i + 2 for i in range(nb)]
        + [0]
        + [2 * i + 1 for i in range(nb)]
        + list(range(1 + 2 * nb, x.ndim))
    )
    x = jnp.transpose(x, perm)
    out_n = n * int(np.prod(block))
    return jnp.reshape(
        x,
        [out_n] + [s // b for s, b in zip(spatial, block)] + list(rest),
    )


def _batch_to_space_nd(ins, attrs):
    x = ins[0]
    block = [int(b) for b in _static(ins[1], "BatchToSpaceND block_shape")]
    crops = _static(ins[2], "BatchToSpaceND crops")
    nb = len(block)
    n = x.shape[0] // int(np.prod(block))
    spatial = x.shape[1 : 1 + nb]
    rest = x.shape[1 + nb:]
    x = jnp.reshape(x, list(block) + [n] + list(spatial) + list(rest))
    # [b1, b2, N, s1, s2, rest] -> [N, s1, b1, s2, b2, rest]
    perm = [nb]
    for i in range(nb):
        perm += [nb + 1 + i, i]
    perm += list(range(2 * nb + 1, x.ndim))
    x = jnp.transpose(x, perm)
    x = jnp.reshape(
        x, [n] + [s * b for s, b in zip(spatial, block)] + list(rest)
    )
    idx = [slice(None)]
    for d, (a, b) in enumerate(crops):
        idx.append(slice(int(a), x.shape[1 + d] - int(b)))
    return x[tuple(idx)]


def _cum(fn):
    def go(ins, attrs):
        axis = int(_static(ins[1], "Cumsum axis"))
        reverse = bool(_attr(attrs, "reverse", False))
        exclusive = bool(_attr(attrs, "exclusive", False))
        x = ins[0]
        if reverse:
            x = jnp.flip(x, axis)
        out = fn(x, axis=axis)
        if exclusive:
            pad = [(0, 0)] * x.ndim
            pad[axis] = (1, 0)
            sl = [slice(None)] * x.ndim
            sl[axis] = slice(0, x.shape[axis])
            ident = 0 if fn is jnp.cumsum else 1
            out = jnp.pad(out, pad, constant_values=ident)[tuple(sl)]
        if reverse:
            out = jnp.flip(out, axis)
        return out

    return go


def _reduction(fn):
    def go(ins, attrs):
        x, axes = ins
        keep = bool(_attr(attrs, "keep_dims", _attr(attrs, "keepdims", False)))
        # TF semantics: reduction_indices=[] is the identity, so the empty
        # tuple must reach jnp as axis=() (NOT None = reduce-all)
        ax = _axes(_static(axes, "reduction_indices"))
        return fn(x, axis=ax, keepdims=keep)

    return go


# op name -> (inputs, attrs) -> value | tuple of values
REGISTRY: Dict[str, Callable[[List[Any], Dict], Any]] = {
    # plumbing
    "Identity": lambda ins, at: ins[0],
    "IdentityN": lambda ins, at: tuple(ins),
    "NoOp": lambda ins, at: (),
    "StopGradient": lambda ins, at: ins[0],
    "PreventGradient": lambda ins, at: ins[0],
    "CheckNumerics": lambda ins, at: ins[0],
    # arithmetic
    "Add": lambda ins, at: ins[0] + ins[1],
    "AddV2": lambda ins, at: ins[0] + ins[1],
    "AddN": lambda ins, at: sum(ins[1:], ins[0]),
    "Sub": lambda ins, at: ins[0] - ins[1],
    "Mul": lambda ins, at: ins[0] * ins[1],
    "Div": lambda ins, at: ins[0] / ins[1],
    "RealDiv": lambda ins, at: ins[0] / ins[1],
    "FloorDiv": lambda ins, at: jnp.floor_divide(ins[0], ins[1]),
    "Maximum": lambda ins, at: jnp.maximum(ins[0], ins[1]),
    "Minimum": lambda ins, at: jnp.minimum(ins[0], ins[1]),
    "Neg": lambda ins, at: -ins[0],
    "Abs": lambda ins, at: jnp.abs(ins[0]),
    "Exp": lambda ins, at: jnp.exp(ins[0]),
    "Log": lambda ins, at: jnp.log(ins[0]),
    "Sqrt": lambda ins, at: jnp.sqrt(ins[0]),
    "Rsqrt": lambda ins, at: lax.rsqrt(ins[0]),
    "Square": lambda ins, at: ins[0] * ins[0],
    "SquaredDifference": lambda ins, at: (ins[0] - ins[1]) ** 2,
    "Pow": lambda ins, at: ins[0] ** ins[1],
    "Tanh": lambda ins, at: jnp.tanh(ins[0]),
    "Sigmoid": lambda ins, at: jax.nn.sigmoid(ins[0]),
    "Relu": lambda ins, at: jax.nn.relu(ins[0]),
    "Relu6": lambda ins, at: jnp.clip(ins[0], 0.0, 6.0),
    "Elu": lambda ins, at: jax.nn.elu(ins[0]),
    "Softplus": lambda ins, at: jax.nn.softplus(ins[0]),
    "Softmax": lambda ins, at: jax.nn.softmax(ins[0], axis=-1),
    "LogSoftmax": lambda ins, at: jax.nn.log_softmax(ins[0], axis=-1),
    # comparison / select
    "Equal": lambda ins, at: ins[0] == ins[1],
    "NotEqual": lambda ins, at: ins[0] != ins[1],
    "Less": lambda ins, at: ins[0] < ins[1],
    "LessEqual": lambda ins, at: ins[0] <= ins[1],
    "Greater": lambda ins, at: ins[0] > ins[1],
    "GreaterEqual": lambda ins, at: ins[0] >= ins[1],
    "Select": lambda ins, at: jnp.where(ins[0], ins[1], ins[2]),
    "SelectV2": lambda ins, at: jnp.where(ins[0], ins[1], ins[2]),
    # linear algebra
    "MatMul": lambda ins, at: jnp.matmul(
        ins[0].T if _attr(at, "transpose_a", False) else ins[0],
        ins[1].T if _attr(at, "transpose_b", False) else ins[1],
    ),
    "BatchMatMul": lambda ins, at: jnp.matmul(
        jnp.swapaxes(ins[0], -1, -2) if _attr(at, "adj_x", False) else ins[0],
        jnp.swapaxes(ins[1], -1, -2) if _attr(at, "adj_y", False) else ins[1],
    ),
    "BatchMatMulV2": lambda ins, at: jnp.matmul(
        jnp.swapaxes(ins[0], -1, -2) if _attr(at, "adj_x", False) else ins[0],
        jnp.swapaxes(ins[1], -1, -2) if _attr(at, "adj_y", False) else ins[1],
    ),
    "BiasAdd": lambda ins, at: ins[0] + ins[1],
    # TF-2.x frozen graphs express most contractions as Einsum; the
    # equation attr is jnp.einsum's own grammar (ellipses included)
    "Einsum": lambda ins, at: jnp.einsum(
        _str_attr(at, "equation", b""), *ins
    ),
    "Conv2D": _conv2d,
    "DepthwiseConv2dNative": _depthwise_conv2d,
    "MaxPool": lambda ins, at: _pool(ins[0], at, lax.max, -jnp.inf),
    "AvgPool": lambda ins, at: _pool(ins[0], at, lax.add, 0.0, avg=True),
    "Conv3D": _conv3d,
    "MaxPool3D": lambda ins, at: _pool(ins[0], at, lax.max, -jnp.inf),
    "AvgPool3D": lambda ins, at: _pool(ins[0], at, lax.add, 0.0, avg=True),
    "MirrorPad": _mirror_pad,
    "FusedBatchNorm": _fused_batch_norm,
    "FusedBatchNormV2": _fused_batch_norm,
    "FusedBatchNormV3": _fused_batch_norm,
    # reductions (reduction indices arrive as const inputs)
    "Sum": _reduction(jnp.sum),
    "Mean": _reduction(jnp.mean),
    "Min": _reduction(jnp.min),
    "Max": _reduction(jnp.max),
    "Prod": _reduction(jnp.prod),
    "All": _reduction(jnp.all),
    "Any": _reduction(jnp.any),
    "ArgMax": lambda ins, at: jnp.argmax(
        ins[0], axis=int(_static(ins[1], "ArgMax axis"))
    ).astype(_np_dtype(at, "output_type", np.int64)),
    "ArgMin": lambda ins, at: jnp.argmin(
        ins[0], axis=int(_static(ins[1], "ArgMin axis"))
    ).astype(_np_dtype(at, "output_type", np.int64)),
    "UnsortedSegmentSum": lambda ins, at: jax.ops.segment_sum(
        ins[0],
        ins[1],
        num_segments=int(_static(ins[2], "UnsortedSegmentSum num_segments")),
    ),
    # shape ops (shape operands must be consts — _static enforces it)
    "Reshape": lambda ins, at: jnp.reshape(
        ins[0], [int(d) for d in _static(ins[1], "Reshape shape")]
    ),
    "Squeeze": lambda ins, at: jnp.squeeze(
        ins[0],
        axis=tuple(int(d) for d in _attr(at, "squeeze_dims", []) or [])
        or None,
    ),
    "ExpandDims": lambda ins, at: jnp.expand_dims(
        ins[0], int(_static(ins[1], "ExpandDims axis"))
    ),
    "Transpose": lambda ins, at: jnp.transpose(
        ins[0], _axes(_static(ins[1], "Transpose perm"))
    ),
    "ConcatV2": _concat_v2,
    "Concat": lambda ins, at: jnp.concatenate(
        ins[1:], axis=int(_static(ins[0], "Concat axis"))
    ),
    "Pack": lambda ins, at: jnp.stack(ins, axis=int(_attr(at, "axis", 0))),
    "Unpack": lambda ins, at: tuple(
        jnp.moveaxis(ins[0], int(_attr(at, "axis", 0)), 0)
    ),
    "StridedSlice": _strided_slice,
    "Slice": lambda ins, at: lax.dynamic_slice(
        ins[0],
        [int(b) for b in _static(ins[1], "Slice begin")],
        [
            int(s) if s != -1 else ins[0].shape[i] - int(b)
            for i, (b, s) in enumerate(
                zip(
                    _static(ins[1], "Slice begin"),
                    _static(ins[2], "Slice size"),
                )
            )
        ],
    ),
    "Pad": lambda ins, at: jnp.pad(
        ins[0],
        [(int(a), int(b)) for a, b in _static(ins[1], "Pad paddings")],
    ),
    "PadV2": lambda ins, at: jnp.pad(
        ins[0],
        [(int(a), int(b)) for a, b in _static(ins[1], "Pad paddings")],
        constant_values=ins[2],
    ),
    "Shape": lambda ins, at: np.asarray(ins[0].shape, dtype=np.int32),
    "Rank": lambda ins, at: np.asarray(len(ins[0].shape), dtype=np.int32),
    "Size": lambda ins, at: np.asarray(ins[0].size, dtype=np.int32),
    "Fill": lambda ins, at: jnp.full(
        [int(d) for d in _static(ins[0], "Fill dims")], ins[1]
    ),
    "ZerosLike": lambda ins, at: jnp.zeros_like(ins[0]),
    "OnesLike": lambda ins, at: jnp.ones_like(ins[0]),
    "Tile": lambda ins, at: jnp.tile(
        ins[0], [int(m) for m in _static(ins[1], "Tile multiples")]
    ),
    "GatherV2": lambda ins, at: jnp.take(
        ins[0], ins[1], axis=int(_static(ins[2], "GatherV2 axis"))
    ),
    "Gather": lambda ins, at: jnp.take(ins[0], ins[1], axis=0),
    "Cast": lambda ins, at: jnp.asarray(ins[0]).astype(
        _np_dtype(at, "DstT")
    ),
    "Range": lambda ins, at: _range(ins),
    # ---- round 5: TF-1.x inference-closure growth (VERDICT r4 next #5) ----
    # image ops (frozen scoring graphs resize in-graph: read_image.py's
    # vgg_preprocessing -> ResizeBilinear)
    "ResizeBilinear": _resize_bilinear_op,
    "ResizeNearestNeighbor": _resize_nearest_op,
    "LRN": _lrn,
    # splitting (the Concat inverse; axis is input 0 for Split, input 2
    # for SplitV, matching TF's inconsistent signatures)
    "Split": lambda ins, at: tuple(
        jnp.split(
            ins[1], int(_attr(at, "num_split")),
            axis=int(_static(ins[0], "Split axis")),
        )
    ),
    "SplitV": lambda ins, at: _split_v(ins),
    "TopKV2": lambda ins, at: tuple(
        (v, i.astype(np.int32))
        for v, i in [lax.top_k(ins[0], int(_static(ins[1], "TopKV2 k")))]
    )[0],
    # elementwise closure
    "Floor": lambda ins, at: jnp.floor(ins[0]),
    "Ceil": lambda ins, at: jnp.ceil(ins[0]),
    "Round": lambda ins, at: jnp.round(ins[0]),  # half-to-even, like TF
    "Rint": lambda ins, at: jnp.round(ins[0]),
    "Sign": lambda ins, at: jnp.sign(ins[0]),
    "FloorMod": lambda ins, at: jnp.mod(ins[0], ins[1]),
    "Mod": lambda ins, at: jnp.fmod(ins[0], ins[1]),  # truncation mod
    "Reciprocal": lambda ins, at: 1.0 / ins[0],
    "Inv": lambda ins, at: 1.0 / ins[0],
    "Log1p": lambda ins, at: jnp.log1p(ins[0]),
    "Expm1": lambda ins, at: jnp.expm1(ins[0]),
    "Erf": lambda ins, at: jax.scipy.special.erf(ins[0]),
    "Erfc": lambda ins, at: jax.scipy.special.erfc(ins[0]),
    "Sin": lambda ins, at: jnp.sin(ins[0]),
    "Cos": lambda ins, at: jnp.cos(ins[0]),
    "Tan": lambda ins, at: jnp.tan(ins[0]),
    "Asin": lambda ins, at: jnp.arcsin(ins[0]),
    "Acos": lambda ins, at: jnp.arccos(ins[0]),
    "Atan": lambda ins, at: jnp.arctan(ins[0]),
    "Atan2": lambda ins, at: jnp.arctan2(ins[0], ins[1]),
    "Sinh": lambda ins, at: jnp.sinh(ins[0]),
    "Cosh": lambda ins, at: jnp.cosh(ins[0]),
    "LeakyRelu": lambda ins, at: jax.nn.leaky_relu(
        ins[0], float(_attr(at, "alpha", 0.2))
    ),
    "Selu": lambda ins, at: jax.nn.selu(ins[0]),
    "Softsign": lambda ins, at: jax.nn.soft_sign(ins[0]),
    "ClipByValue": lambda ins, at: jnp.clip(ins[0], ins[1], ins[2]),
    # indexing / shaping closure
    "BroadcastTo": lambda ins, at: jnp.broadcast_to(
        ins[0], [int(d) for d in _static(ins[1], "BroadcastTo shape")]
    ),
    "OneHot": _one_hot,
    "GatherNd": lambda ins, at: ins[0][
        tuple(jnp.moveaxis(ins[1], -1, 0))
    ],
    "DepthToSpace": lambda ins, at: _space_depth(ins, at, to_depth=False),
    "SpaceToDepth": lambda ins, at: _space_depth(ins, at, to_depth=True),
    "InvertPermutation": lambda ins, at: jnp.argsort(ins[0]).astype(
        ins[0].dtype  # NOT np.asarray(...).dtype: input may be traced
    ),
    "Cumsum": _cum(jnp.cumsum),
    "Cumprod": _cum(jnp.cumprod),
    # deconv + dilated-conv plumbing (segmentation/deeplab-style graphs)
    "Conv2DBackpropInput": _conv2d_backprop_input,
    "Conv3DBackpropInputV2": lambda ins, at: _conv_backprop_input(
        ins, at, 3, "Conv3DBackpropInputV2"
    ),
    "SpaceToBatchND": _space_to_batch_nd,
    "BatchToSpaceND": _batch_to_space_nd,
    # graph plumbing aliases
    "Snapshot": lambda ins, at: ins[0],
    "PlaceholderWithDefault": lambda ins, at: ins[0],
}
