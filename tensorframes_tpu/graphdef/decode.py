"""Host-side image decoding for in-graph ``Decode*`` nodes.

The reference's flagship scoring graph begins at ``DecodeJpeg``
(``read_image.py:120-167``): users feed ENCODED bytes and the graph
decodes in-session.  XLA can host neither string tensors nor the
data-dependent [H, W, C] shape a decoder produces, so the TPU-native
split runs decode on the host — this module supplies the PIL-backed
stage functions that ``importer.import_graphdef`` attaches to a
Program's ``host_prelude`` when it meets a decode node (the engine
merges the prelude into the verb's ``host_stage`` automatically).

Uniformity contract: a host stage must emit one uniform [rows, H, W, C]
array per device call, so every image inside one block (``map_blocks``)
or one shape bucket (``map_rows``) must share a size.  Mixed sizes raise
with guidance rather than silently padding — grouping by size (or
pre-resizing on host) is the caller's policy decision.
"""

from __future__ import annotations

import io

import numpy as np

# ops the importer routes to a host prelude instead of a device lowering
DECODE_OPS = ("DecodeJpeg", "DecodePng", "DecodeImage", "DecodeBmp")

_MODES = {1: "L", 3: "RGB", 4: "RGBA"}


def pil_decoder(channels: int = 0, op: str = "DecodeJpeg"):
    """Build a host_stage fn: list of encoded byte cells -> uint8 pixels.

    ``channels`` follows the TF attr: 0 = the file's native channel
    count (grayscale stays [H, W, 1], RGB stays 3-channel, PNG alpha is
    kept — TF's behaviour), 1 = grayscale, 3 = RGB, 4 = RGBA.
    """
    ch = int(channels)
    mode = _MODES.get(ch) if ch else None  # None: decode natively
    if ch and mode is None:
        raise ValueError(
            f"{op}: channels={channels} is not decodable (0, 1, 3 or 4)"
        )

    def decode(cells):
        try:
            from PIL import Image
        except ImportError as e:  # pragma: no cover - depends on install
            raise RuntimeError(
                f"decoding an in-graph {op} node needs the optional "
                f"Pillow dependency, which is not importable here; pass "
                f"an explicit host_stage fn for this input instead"
            ) from e
        arrs = []
        for c in cells:
            img = Image.open(io.BytesIO(bytes(c)))
            if mode is not None:
                img = img.convert(mode)
            elif img.mode not in ("L", "RGB", "RGBA"):
                # palette/CMYK/LA files have no TF-decode layout; RGB is
                # what TF's decoders produce for them
                img = img.convert("RGB")
            a = np.asarray(img, dtype=np.uint8)
            if a.ndim == 2:  # "L" gives [H, W]; TF emits [H, W, 1]
                a = a[..., None]
            arrs.append(a)
        by_size = {}
        for i, a in enumerate(arrs):
            by_size.setdefault(a.shape, []).append(i)
        if len(by_size) > 1:
            # name the offending ROWS, not just the size set: the fix is
            # grouping/resizing specific rows, so point at them (indices
            # are relative to this device call's block / shape bucket)
            majority = max(by_size.items(), key=lambda kv: len(kv[1]))[0]
            offenders = "; ".join(
                f"rows {_fmt_rows(idxs)} decoded to {shape}"
                for shape, idxs in sorted(by_size.items())
                if shape != majority
            )
            raise ValueError(
                f"{op} host decode produced mixed image sizes within one "
                f"device call: majority size is {majority}, but {offenders} "
                f"(row indices within this block/bucket); images must be "
                f"uniform per block (map_blocks) or per shape bucket "
                f"(map_rows) — group rows by size or pre-resize in a "
                f"custom host_stage"
            )
        return np.stack(arrs)

    return decode


def _fmt_rows(idxs, cap: int = 8) -> str:
    """``[0, 3, 7]`` -> ``"0, 3, 7"``, long lists elided with a count."""
    shown = ", ".join(str(i) for i in idxs[:cap])
    extra = len(idxs) - cap
    return f"{shown}, … (+{extra} more)" if extra > 0 else shown
