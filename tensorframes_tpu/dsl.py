"""Graph-construction DSL: build tensor programs without writing a function.

Re-design of the reference's Scala DSL
(``/root/reference/src/main/scala/org/tensorframes/dsl/package.scala:44-131``,
``dsl/Operation.scala``, ``dsl/DslImpl.scala``): a tiny lazy ``Node`` graph
with the same public surface — ``placeholder``, ``constant``, ``zeros`` /
``ones`` / ``fill``, ``block`` / ``row`` auto-placeholders bound to frame
columns (``dsl/DslImpl.scala:90-107``), ``identity`` / ``add`` / ``div``,
``reduce_sum`` / ``reduce_min`` / ``reduce_max``, operator sugar ``+ - * /``
(``dsl/Operation.scala:52-57``) and ``.named`` (the fetch-naming contract).

Where the reference freezes nodes into TF ``NodeDef`` protos executed by
libtensorflow, here ``build_program`` lowers the node graph into a jax
function wrapped as a :class:`~tensorframes_tpu.program.Program` — the same
object every verb consumes, so DSL graphs and plain python functions are
interchangeable.

Naming: the reference assigns paths through a *mutable global scope stack*
that is documented thread-unsafe (``dsl/Paths.scala:10-12``).  We instead
name nodes at build time: user-``named`` nodes keep their names (duplicates
are an error), anonymous interior nodes get deterministic ``{op}_{i}`` names
— no global state, safe under concurrency.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from . import dtypes
from .frame import TensorFrame
from .program import Program, ProgramError
from .shape import Shape, UNKNOWN


class DslError(ValueError):
    """Malformed DSL graph (unnamed fetch collisions, arity errors...)."""


_node_ids = itertools.count()


class Node:
    """One lazy operation in a DSL graph.

    ``op`` is the operation tag; ``parents`` are input Nodes; ``attrs`` are
    op-static parameters (constant values, reduction axes...).  Mirrors the
    reference ``Operation``/``Node`` (``dsl/Operation.scala:40-133``) minus
    the proto plumbing.
    """

    def __init__(
        self,
        op: str,
        parents: Sequence["Node"] = (),
        name: Optional[str] = None,
        **attrs,
    ):
        self.id = next(_node_ids)
        self.op = op
        self.parents = list(parents)
        self.name = name
        self.attrs = attrs

    # -- naming (the fetch contract) ----------------------------------------

    def named(self, name: str) -> "Node":
        """Name this node — required for fetches (reference ``named``
        operator, ``dsl/Operation.scala:60-66``)."""
        self.name = str(name)
        return self

    # -- operator sugar (dsl/Operation.scala:52-57) -------------------------

    def __add__(self, other):
        return add(self, other)

    def __radd__(self, other):
        return add(constant(other), self)

    def __sub__(self, other):
        return sub(self, other)

    def __rsub__(self, other):
        return sub(constant(other), self)

    def __mul__(self, other):
        return mul(self, other)

    def __rmul__(self, other):
        return mul(constant(other), self)

    def __truediv__(self, other):
        return div(self, other)

    def __rtruediv__(self, other):
        return div(constant(other), self)

    # -- program bridge ------------------------------------------------------

    def to_program(self) -> Program:
        return build_program([self])

    def __repr__(self):
        nm = self.name or f"{self.op}#{self.id}"
        return f"Node({nm})"


def _as_node(x) -> Node:
    if isinstance(x, Node):
        return x
    return constant(x)


# ---------------------------------------------------------------------------
# public constructors (dsl/package.scala:44-131)
# ---------------------------------------------------------------------------


def placeholder(
    dtype, shape: Sequence[int], name: Optional[str] = None
) -> Node:
    """An input fed by a frame column of the same name
    (``dsl/package.scala:60-66``)."""
    st = dtype if isinstance(dtype, dtypes.ScalarType) else dtypes.by_name(
        str(np.dtype(dtype))
    )
    return Node("placeholder", name=name, dtype=st, shape=Shape(shape))


def constant(value, name: Optional[str] = None) -> Node:
    """Embed a literal tensor (``dsl/package.scala:70-72``; the reference
    encodes these as ``DenseTensor`` protos, ``DenseTensor.scala:73-115`` —
    here the value rides along as a numpy array)."""
    return Node("const", name=name, value=np.asarray(value))


def zeros(shape: Sequence[int], dtype="float64") -> Node:
    return fill(shape, 0.0, dtype)


def ones(shape: Sequence[int], dtype="float64") -> Node:
    return fill(shape, 1.0, dtype)


def fill(shape: Sequence[int], value, dtype="float64") -> Node:
    """``dsl/package.scala:76-90``."""
    st = dtype if isinstance(dtype, dtypes.ScalarType) else dtypes.by_name(dtype)
    return Node("fill", shape=Shape(shape), value=value, dtype=st)


def block(frame: TensorFrame, col: str, name: Optional[str] = None) -> Node:
    """Auto-placeholder bound to a column at BLOCK level: shape
    ``[unknown_rows, *cell]`` read from the frame schema — the reference's
    ``extractPlaceholder`` (``dsl/DslImpl.scala:90-107``) / python
    ``tfs.block`` (``core.py:338-368``)."""
    ci = frame.schema[col]
    return Node(
        "placeholder",
        name=name or col,
        dtype=ci.scalar_type,
        shape=ci.cell_shape.prepend(UNKNOWN),
        column=col,
    )


def row(frame: TensorFrame, col: str, name: Optional[str] = None) -> Node:
    """Auto-placeholder at ROW (cell) level (``core.py:370-391``)."""
    ci = frame.schema[col]
    return Node(
        "placeholder",
        name=name or col,
        dtype=ci.scalar_type,
        shape=ci.cell_shape,
        column=col,
    )


def identity(x: Node, name: Optional[str] = None) -> Node:
    return Node("identity", [_as_node(x)], name=name)


def add(a, b, name: Optional[str] = None) -> Node:
    return Node("add", [_as_node(a), _as_node(b)], name=name)


def sub(a, b, name: Optional[str] = None) -> Node:
    return Node("sub", [_as_node(a), _as_node(b)], name=name)


def mul(a, b, name: Optional[str] = None) -> Node:
    return Node("mul", [_as_node(a), _as_node(b)], name=name)


def div(a, b, name: Optional[str] = None) -> Node:
    return Node("div", [_as_node(a), _as_node(b)], name=name)


def matmul(a, b, name: Optional[str] = None) -> Node:
    return Node("matmul", [_as_node(a), _as_node(b)], name=name)


def reduce_sum(
    x: Node, axis: Optional[Sequence[int]] = None, name: Optional[str] = None
) -> Node:
    """``dsl/package.scala:120-124`` (reduction over all dims by default,
    matching the reference's ``reduction_indices`` = all)."""
    return Node("reduce_sum", [_as_node(x)], name=name, axis=axis)


def reduce_min(
    x: Node, axis: Optional[Sequence[int]] = None, name: Optional[str] = None
) -> Node:
    return Node("reduce_min", [_as_node(x)], name=name, axis=axis)


def reduce_max(
    x: Node, axis: Optional[Sequence[int]] = None, name: Optional[str] = None
) -> Node:
    return Node("reduce_max", [_as_node(x)], name=name, axis=axis)


def reduce_mean(
    x: Node, axis: Optional[Sequence[int]] = None, name: Optional[str] = None
) -> Node:
    return Node("reduce_mean", [_as_node(x)], name=name, axis=axis)


# ---------------------------------------------------------------------------
# lowering
# ---------------------------------------------------------------------------

_EVAL = {
    "identity": lambda ins, at: ins[0],
    "add": lambda ins, at: ins[0] + ins[1],
    "sub": lambda ins, at: ins[0] - ins[1],
    "mul": lambda ins, at: ins[0] * ins[1],
    "div": lambda ins, at: ins[0] / ins[1],
    "matmul": lambda ins, at: ins[0] @ ins[1],
    "reduce_sum": lambda ins, at: jnp.sum(ins[0], axis=at.get("axis")),
    "reduce_min": lambda ins, at: jnp.min(ins[0], axis=at.get("axis")),
    "reduce_max": lambda ins, at: jnp.max(ins[0], axis=at.get("axis")),
    "reduce_mean": lambda ins, at: jnp.mean(ins[0], axis=at.get("axis")),
}


def _collect(fetches: Sequence[Node]) -> List[Node]:
    """Transitive closure in deterministic topological order (the reference's
    freeze + dedup, ``dsl/DslImpl.scala:38-75``)."""
    # iterative DFS — deep op chains must not hit Python's recursion limit
    # (same constraint as graphdef/importer.py's topo sort)
    seen: Dict[int, Node] = {}
    order: List[Node] = []
    for f in fetches:
        stack: List[Tuple[Node, int]] = [(f, 0)]
        while stack:
            n, pi = stack.pop()
            if pi == 0 and n.id in seen:
                continue
            seen[n.id] = n
            if pi < len(n.parents):
                stack.append((n, pi + 1))
                child = n.parents[pi]
                if child.id not in seen:
                    stack.append((child, 0))
            else:
                order.append(n)
    return order


def _assign_names(
    order: Sequence[Node], fetch_nodes: Sequence[Node]
) -> Dict[int, str]:
    """Name assignment: user names win, must be unique; anonymous fetches
    are an error (outputs need stable column names).  Generated names live
    in a local node->name map so building a program never mutates the
    user's Node objects (nodes shared between programs would otherwise
    collide on their first generated name)."""
    names: Dict[int, str] = {}
    used: Dict[str, Node] = {}
    counters: Dict[str, int] = {}
    for n in order:
        if n.name is not None:
            if n.name in used and used[n.name] is not n:
                raise DslError(f"duplicate node name {n.name!r} in DSL graph")
            used[n.name] = n
            names[n.id] = n.name
    for f in fetch_nodes:
        if f.name is None:
            raise DslError(
                "fetch nodes must be named: use node.named('out') so the "
                "output column has a stable name"
            )
    for n in order:
        if n.name is None:
            i = counters.get(n.op, 0)
            counters[n.op] = i + 1
            candidate = f"{n.op}_{i}"
            while candidate in used:
                i += 1
                counters[n.op] = i + 1
                candidate = f"{n.op}_{i}"
            names[n.id] = candidate
            used[candidate] = n
    return names


# DSL op tag -> TF op name, for GraphDef export (the reference's DSL emits
# NodeDef protos directly, dsl/DslImpl.scala:143-157 / ProtoConversions)
_TF_OPS = {
    "identity": "Identity",
    "add": "Add",
    "sub": "Sub",
    "mul": "Mul",
    "div": "RealDiv",
    "matmul": "MatMul",
}
_TF_REDUCE = {
    "reduce_sum": "Sum",
    "reduce_min": "Min",
    "reduce_max": "Max",
    "reduce_mean": "Mean",
}


def to_graphdef(fetches: Sequence[Node]) -> bytes:
    """Export DSL fetch nodes as serialized TF GraphDef bytes.

    The write-side mirror of the reference's DSL, which builds ``NodeDef``
    protos and golden-tests them against python TF's output
    (``dsl/DslImpl.scala:143-157``, ``dsl/ExtractNodes.scala:14-74``).  The
    exported graph round-trips through ``graphdef.import_graphdef`` (our
    golden axis, no TF install needed) and is consumable by TF tooling /
    the bridge protocol.

    Reductions need an explicit ``axis`` (the wire format encodes
    ``reduction_indices`` as a Const input, which requires concrete axes).
    """
    from .graphdef.builder import GraphBuilder

    fetch_nodes = list(fetches)
    for f in fetch_nodes:
        if not isinstance(f, Node):
            raise DslError(f"fetches must be DSL nodes, got {type(f).__name__}")
    order = _collect(fetch_nodes)
    names = _assign_names(order, fetch_nodes)
    g = GraphBuilder()
    for n in order:
        nm = names[n.id]
        ins = [names[p.id] for p in n.parents]
        if n.op == "placeholder":
            g.placeholder(nm, n.attrs["dtype"], list(n.attrs["shape"]))
        elif n.op == "const":
            g.const(nm, n.attrs["value"])
        elif n.op == "fill":
            st = n.attrs["dtype"]
            g.const(
                nm,
                np.full(
                    tuple(n.attrs["shape"]), n.attrs["value"], st.np_dtype
                ),
            )
        elif n.op in _TF_OPS:
            g.op(_TF_OPS[n.op], nm, ins)
        elif n.op in _TF_REDUCE:
            axis = n.attrs.get("axis")
            if axis is None:
                raise DslError(
                    f"{n.op} needs an explicit axis=[...] for GraphDef "
                    f"export (reduction_indices must be concrete)"
                )
            ax = g.const(
                f"{nm}/reduction_indices", np.asarray(axis, np.int32)
            )
            g.op(_TF_REDUCE[n.op], nm, ins + [ax])
        else:  # pragma: no cover - every public constructor is mapped
            raise DslError(f"DSL op {n.op!r} has no GraphDef lowering")
    return g.to_bytes()


def build_program(
    fetches: Sequence[Union[Node, Any]],
    feed_dict: Optional[Dict[str, str]] = None,
) -> Program:
    """Lower DSL fetch nodes to a :class:`Program`.

    Fetch nodes must be named (``.named("z")``) — the reference's requested
    -fetches contract (``Node.hints``, ``dsl/Operation.scala:166-176``).
    Anonymous interior nodes get deterministic generated names.
    """
    fetch_nodes = [f for f in fetches]
    for f in fetch_nodes:
        if not isinstance(f, Node):
            raise DslError(f"fetches must be DSL nodes, got {type(f).__name__}")
    order = _collect(fetch_nodes)
    names = _assign_names(order, fetch_nodes)

    placeholders = [n for n in order if n.op == "placeholder"]
    if not placeholders:
        raise DslError(
            "DSL graph has no placeholders; programs need at least one "
            "column-fed input"
        )
    input_names = [names[p.id] for p in placeholders]
    feed = dict(feed_dict or {})
    for p in placeholders:
        pname = names[p.id]
        col = p.attrs.get("column")
        # auto column binding from block()/row(); explicit user feed wins
        if col is not None and col != pname and pname not in feed:
            feed[pname] = col

    def fn(**inputs):
        cache: Dict[int, Any] = {}
        for p in placeholders:
            cache[p.id] = inputs[names[p.id]]
        for n in order:
            if n.id in cache:
                continue
            if n.op == "const":
                cache[n.id] = jnp.asarray(n.attrs["value"])
            elif n.op == "fill":
                shape = n.attrs["shape"]
                if not shape.is_static:
                    raise DslError(
                        f"fill shape {shape} must be static"
                    )
                cache[n.id] = jnp.full(
                    tuple(shape),
                    n.attrs["value"],
                    dtype=n.attrs["dtype"].np_dtype,
                )
            else:
                ev = _EVAL.get(n.op)
                if ev is None:
                    raise DslError(f"unknown DSL op {n.op!r}")
                cache[n.id] = ev([cache[p.id] for p in n.parents], n.attrs)
        return {f.name: cache[f.id] for f in fetch_nodes}

    return Program(
        fn,
        input_names,
        fetches=[f.name for f in fetch_nodes],
        feed_dict=feed,
    )
