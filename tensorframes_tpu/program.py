"""Program: the named-input tensor program fed to every verb.

TPU-native re-design of the reference's graph layer (L4): where the reference
ships a serialized TF ``GraphDef`` whose ``Placeholder`` nodes are named after
DataFrame columns (``TensorFlowOps.scala:101-141``), a ``Program`` here wraps a
*jax-traceable function* whose argument names are the input names and whose
outputs are named fetches.  Under ``jit`` the function is traced once per input
signature and compiled by XLA — the compiled executable plays the role of the
broadcast graph bytes (SURVEY.md §2.7 P6: program broadcast == jit cache).

Three construction paths, mirroring the reference's three graph sources:
python function (== python TF graph), the DSL (``tensorframes_tpu.dsl``), and
frozen ``GraphDef`` import (``tensorframes_tpu.graphdef``) — the latter two
both produce a plain traceable function and land here.

``analyze_program`` is the analog of ``TensorFlowOps.analyzeGraphTF``
(``TensorFlowOps.scala:101-141``): it runs shape inference (``jax.eval_shape``
— no FLOPs, no device) over declared input specs and returns a
``GraphNodeSummary`` per input/output, with user hints overriding inferred
shapes exactly like the reference's ``ShapeDescription`` override
(``TensorFlowOps.scala:126-133``).
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes
from .dtypes import ScalarType
from .schema import SchemaError
from .shape import Shape


class ProgramError(ValueError):
    """Raised for malformed programs (bad signature, bad outputs, bad hints)."""


@dataclasses.dataclass(frozen=True)
class GraphNodeSummary:
    """Shape/dtype summary of one program input or output.

    Mirrors ``GraphNodeSummary`` (``TensorFlowOps.scala:163-169``)."""

    name: str
    is_input: bool
    is_output: bool
    scalar_type: ScalarType
    shape: Shape

    def __repr__(self):
        role = "input" if self.is_input else "output"
        return f"{self.name}[{role}]: {self.scalar_type}{self.shape}"


class Program:
    """A tensor program with named inputs and named outputs.

    ``fn`` takes keyword arrays named by ``input_names`` and returns either a
    ``dict`` of named outputs, a single array (allowed only when ``fetches``
    names exactly one output), or a tuple matching ``fetches``.  Outputs are
    canonically ordered sorted-by-name, matching the reference's output schema
    ordering (``DebugRowOps.scala:349-372``).

    ``feed_dict`` maps input name -> frame column name, the reference's
    ``map_rows`` feed-dict contract (``core.py:175-211``,
    ``PythonInterface.scala:120-127``).
    """

    def __init__(
        self,
        fn: Callable[..., Any],
        input_names: Sequence[str],
        fetches: Optional[Sequence[str]] = None,
        feed_dict: Optional[Mapping[str, str]] = None,
    ):
        self._fn = fn
        self._input_names = list(input_names)
        self._declared_fetches = list(fetches) if fetches is not None else None
        self._feed = dict(feed_dict or {})
        for k in self._feed:
            if k not in self._input_names:
                raise ProgramError(
                    f"feed_dict key {k!r} is not a program input; "
                    f"inputs are {self._input_names}"
                )
        self._fetches: Optional[List[str]] = None  # resolved at first trace
        self._jitted = None

    # -- construction --------------------------------------------------------

    @staticmethod
    def wrap(
        fn_or_program,
        fetches: Optional[Sequence[str]] = None,
        feed_dict: Optional[Mapping[str, str]] = None,
    ) -> "Program":
        if isinstance(fn_or_program, Program):
            if fetches is not None and sorted(fetches) != sorted(
                fn_or_program._declared_fetches or []
            ):
                raise ProgramError(
                    "cannot re-declare fetches on an existing Program; pass "
                    "fetches when the program is created/imported"
                )
            if feed_dict:
                return fn_or_program.with_feed(feed_dict)
            return fn_or_program
        # DSL nodes (and sequences of them) lower to a Program
        is_node = hasattr(fn_or_program, "to_program")
        is_node_seq = (
            isinstance(fn_or_program, (list, tuple))
            and fn_or_program
            and all(hasattr(x, "to_program") for x in fn_or_program)
        )
        if is_node or is_node_seq:
            from . import dsl  # local import: dsl depends on this module

            nodes = [fn_or_program] if is_node else list(fn_or_program)
            p = dsl.build_program(nodes, feed_dict=feed_dict)
            if fetches is not None and sorted(fetches) != sorted(
                p._declared_fetches or []
            ):
                raise ProgramError(
                    f"fetches {sorted(fetches)} do not match the DSL fetch "
                    f"node names {sorted(p._declared_fetches or [])}; name "
                    f"fetch nodes with .named(...) instead"
                )
            return p
        if not callable(fn_or_program):
            raise ProgramError(
                f"expected a callable, Program, or DSL node(s), got "
                f"{type(fn_or_program).__name__}"
            )
        sig = inspect.signature(fn_or_program)
        names = []
        for p in sig.parameters.values():
            if p.kind in (
                inspect.Parameter.POSITIONAL_OR_KEYWORD,
                inspect.Parameter.KEYWORD_ONLY,
            ):
                names.append(p.name)
            elif p.kind in (
                inspect.Parameter.VAR_POSITIONAL,
                inspect.Parameter.VAR_KEYWORD,
            ):
                raise ProgramError(
                    "program functions must declare explicit named parameters "
                    "(column names); *args/**kwargs are not allowed"
                )
        if not names:
            raise ProgramError("a program needs at least one named input")
        return Program(fn_or_program, names, fetches, feed_dict)

    def with_feed(self, feed_dict: Mapping[str, str]) -> "Program":
        """A copy with additional input->column renames merged in."""
        merged = dict(self._feed)
        merged.update(feed_dict)
        return Program(
            self._fn, self._input_names, self._declared_fetches, merged
        )

    # -- accessors -----------------------------------------------------------

    @property
    def input_names(self) -> List[str]:
        return list(self._input_names)

    def column_for_input(self, name: str) -> str:
        """Frame column feeding a given input (identity unless feed_dict)."""
        return self._feed.get(name, name)

    @property
    def columns_needed(self) -> List[str]:
        return [self.column_for_input(n) for n in self._input_names]

    @property
    def fetches(self) -> Optional[List[str]]:
        return list(self._fetches) if self._fetches is not None else (
            sorted(self._declared_fetches) if self._declared_fetches else None
        )

    # -- execution -----------------------------------------------------------

    def _normalize_outputs(self, out) -> Dict[str, Any]:
        if isinstance(out, dict):
            res = dict(out)
        elif isinstance(out, (tuple, list)):
            if self._declared_fetches is None or len(self._declared_fetches) != len(
                out
            ):
                raise ProgramError(
                    "tuple program outputs require fetches=[...] of matching "
                    f"length; got {len(out)} outputs, fetches="
                    f"{self._declared_fetches}"
                )
            res = dict(zip(self._declared_fetches, out))
        else:
            if self._declared_fetches is None or len(self._declared_fetches) != 1:
                raise ProgramError(
                    "a program returning a single array must declare exactly "
                    "one fetch name (pass fetches=['name']), or return a dict "
                    "{name: array}"
                )
            res = {self._declared_fetches[0]: out}
        if self._declared_fetches is not None:
            missing = [f for f in self._declared_fetches if f not in res]
            if missing:
                raise ProgramError(
                    f"program outputs {sorted(res)} are missing requested "
                    f"fetches {missing}"
                )
            res = {f: res[f] for f in self._declared_fetches}
        if not res:
            raise ProgramError("program produced no outputs")
        for name, v in res.items():
            if not isinstance(name, str):
                raise ProgramError(f"output names must be strings, got {name!r}")
            res[name] = jnp.asarray(v)
        # canonical order: sorted by name (DebugRowOps.scala:349-372)
        ordered = {k: res[k] for k in sorted(res)}
        if self._fetches is None:
            self._fetches = list(ordered)
        return ordered

    def call(self, inputs: Mapping[str, Any]) -> Dict[str, Any]:
        """Run the program (traceable; used inside jit/vmap/shard_map)."""
        kwargs = {n: inputs[n] for n in self._input_names}
        return self._normalize_outputs(self._fn(**kwargs))

    def jitted(self):
        """The compiled entry: traced once per input shape/dtype signature.

        jax's jit cache is the broadcast mechanism (SURVEY.md P6): every block
        with the same signature reuses the same XLA executable, on any device.
        """
        if self._jitted is None:
            def _run(inputs):
                return self.call(inputs)

            self._jitted = jax.jit(_run)
        return self._jitted

    # -- analysis ------------------------------------------------------------

    def analyze(
        self,
        input_specs: Mapping[str, Any],
        hints: Optional[Mapping[str, Sequence[int]]] = None,
    ) -> List[GraphNodeSummary]:
        """Shape-infer the program against input specs without executing it.

        ``input_specs``: input name -> (ScalarType, Shape) or ShapeDtypeStruct.
        ``hints``: output name -> shape override (the ``ShapeDescription``
        mechanism, ``ShapeDescription.scala:3-16``).
        """
        structs = {}
        for n in self._input_names:
            if n not in input_specs:
                raise ProgramError(
                    f"analyze: no spec for program input {n!r}; "
                    f"got specs for {sorted(input_specs)}"
                )
            spec = input_specs[n]
            if isinstance(spec, jax.ShapeDtypeStruct):
                structs[n] = spec
            else:
                st, shape = spec
                if not Shape(shape).is_static:
                    raise ProgramError(
                        f"analyze: input {n!r} spec must be static, got "
                        f"{Shape(shape)}"
                    )
                structs[n] = jax.ShapeDtypeStruct(
                    tuple(Shape(shape)), st.np_dtype
                )
        out_structs = jax.eval_shape(lambda ins: self.call(ins), structs)
        hints = dict(hints or {})
        summaries: List[GraphNodeSummary] = []
        for n in self._input_names:
            s = structs[n]
            summaries.append(
                GraphNodeSummary(
                    n, True, False, dtypes.from_numpy(s.dtype), Shape(s.shape)
                )
            )
        for name, s in out_structs.items():
            shape = Shape(hints.pop(name)) if name in hints else Shape(s.shape)
            summaries.append(
                GraphNodeSummary(
                    name, False, True, dtypes.from_numpy(s.dtype), shape
                )
            )
        if hints:
            raise ProgramError(
                f"shape hints given for non-existent outputs: {sorted(hints)}; "
                f"program outputs are {sorted(out_structs)}"
            )
        return summaries
