"""Program: the named-input tensor program fed to every verb.

TPU-native re-design of the reference's graph layer (L4): where the reference
ships a serialized TF ``GraphDef`` whose ``Placeholder`` nodes are named after
DataFrame columns (``TensorFlowOps.scala:101-141``), a ``Program`` here wraps a
*jax-traceable function* whose argument names are the input names and whose
outputs are named fetches.  Under ``jit`` the function is traced once per input
signature and compiled by XLA — the compiled executable plays the role of the
broadcast graph bytes (SURVEY.md §2.7 P6: program broadcast == jit cache).

Three construction paths, mirroring the reference's three graph sources:
python function (== python TF graph), the DSL (``tensorframes_tpu.dsl``), and
frozen ``GraphDef`` import (``tensorframes_tpu.graphdef``) — the latter two
both produce a plain traceable function and land here.

``analyze_program`` is the analog of ``TensorFlowOps.analyzeGraphTF``
(``TensorFlowOps.scala:101-141``): it runs shape inference (``jax.eval_shape``
— no FLOPs, no device) over declared input specs and returns a
``GraphNodeSummary`` per input/output, with user hints overriding inferred
shapes exactly like the reference's ``ShapeDescription`` override
(``TensorFlowOps.scala:126-133``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import inspect
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes, observability
from .dtypes import ScalarType
from .schema import SchemaError
from .shape import Shape, UNKNOWN


class ProgramError(ValueError):
    """Raised for malformed programs (bad signature, bad outputs, bad hints)."""


@dataclasses.dataclass(frozen=True)
class GraphNodeSummary:
    """Shape/dtype summary of one program input or output.

    Mirrors ``GraphNodeSummary`` (``TensorFlowOps.scala:163-169``)."""

    name: str
    is_input: bool
    is_output: bool
    scalar_type: ScalarType
    shape: Shape

    def __repr__(self):
        role = "input" if self.is_input else "output"
        return f"{self.name}[{role}]: {self.scalar_type}{self.shape}"


def deserialize_program(data: bytes) -> "Program":
    """Rehydrate a :meth:`Program.serialize` artifact.

    The artifact is self-contained (params frozen in, shapes possibly
    symbolic): the deserialized program runs on any backend jax supports,
    the way the reference's broadcast graph bytes run in any executor.
    Block-level semantics only — the frozen executable cannot be re-vmapped,
    so feed it to ``map_blocks``/``reduce_*``, not ``map_rows``."""
    import json

    from jax import export as jexp

    sep = data.index(b"\x00")
    header = json.loads(data[:sep].decode())
    if header.get("format") != "tfs-program-v1":
        raise ProgramError(
            f"not a serialized tensorframes program (format="
            f"{header.get('format')!r})"
        )
    exported = jexp.deserialize(data[sep + 1 :])
    input_names = header["inputs"]

    def fn(**kwargs):
        return exported.call({n: kwargs[n] for n in input_names})

    return Program(
        fn, input_names, header["fetches"], header.get("feed") or None
    )


class Program:
    """A tensor program with named inputs and named outputs.

    ``fn`` takes keyword arrays named by ``input_names`` and returns either a
    ``dict`` of named outputs, a single array (allowed only when ``fetches``
    names exactly one output), or a tuple matching ``fetches``.  Outputs are
    canonically ordered sorted-by-name, matching the reference's output schema
    ordering (``DebugRowOps.scala:349-372``).

    ``feed_dict`` maps input name -> frame column name, the reference's
    ``map_rows`` feed-dict contract (``core.py:175-211``,
    ``PythonInterface.scala:120-127``).
    """

    def __init__(
        self,
        fn: Callable[..., Any],
        input_names: Sequence[str],
        fetches: Optional[Sequence[str]] = None,
        feed_dict: Optional[Mapping[str, str]] = None,
        params: Optional[Mapping[str, Any]] = None,
    ):
        self._fn = fn
        self._declared_fetches = list(fetches) if fetches is not None else None
        all_names = list(input_names)
        # a param value may be a single array OR a pytree of arrays (a model
        # parameter tree) — both flow through jit as traced arguments
        self._params: Dict[str, Any] = {
            k: jax.tree_util.tree_map(jnp.asarray, v)
            for k, v in (params or {}).items()
        }
        # monotonic params generation: bumped by update_params so caches
        # keyed on live param VALUES (the planner's cross-plan CSE
        # registry) can tell two states of one Program apart without
        # holding or hashing the arrays themselves
        self._params_version = 0
        for k in self._params:
            if k not in all_names:
                raise ProgramError(
                    f"params key {k!r} is not a program argument; "
                    f"arguments are {all_names}"
                )
        # column-fed inputs exclude param-fed arguments
        self._input_names = [n for n in all_names if n not in self._params]
        if not self._input_names:
            raise ProgramError(
                "a program needs at least one column-fed input (all "
                "arguments were bound by params)"
            )
        self._feed = dict(feed_dict or {})
        for k in self._feed:
            if k not in self._input_names:
                raise ProgramError(
                    f"feed_dict key {k!r} is not a program input; "
                    f"inputs are {self._input_names}"
                )
        self._fetches: Optional[List[str]] = None  # resolved at first trace
        self._jitted = None
        self._jit_raw_obj = None
        self._vmapped = None
        self._vmap_raw_obj = None
        self._derived: Dict[Any, Any] = {}
        # output name -> Shape hint (ShapeDescription.scala:3-16); applied by
        # analyze() as a refinement and checked by the verbs at run time
        self._shape_hints: Dict[str, Shape] = {}
        # input name -> host preprocessing fn the engine merges into each
        # verb's host_stage (set by the GraphDef importer for in-graph
        # Decode* nodes; an explicit caller host_stage wins per input)
        self.host_prelude: Dict[str, Any] = {}

    # -- construction --------------------------------------------------------

    @staticmethod
    def wrap(
        fn_or_program,
        fetches: Optional[Sequence[str]] = None,
        feed_dict: Optional[Mapping[str, str]] = None,
        params: Optional[Mapping[str, Any]] = None,
    ) -> "Program":
        if isinstance(fn_or_program, Program):
            if params:
                raise ProgramError(
                    "cannot bind params on an existing Program; pass params "
                    "when the program is created, or call update_params"
                )
            if fetches is not None and sorted(fetches) != sorted(
                fn_or_program._declared_fetches or []
            ):
                raise ProgramError(
                    "cannot re-declare fetches on an existing Program; pass "
                    "fetches when the program is created/imported"
                )
            if feed_dict:
                return fn_or_program.with_feed(feed_dict)
            return fn_or_program
        # DSL nodes (and sequences of them) lower to a Program
        is_node = hasattr(fn_or_program, "to_program")
        is_node_seq = (
            isinstance(fn_or_program, (list, tuple))
            and fn_or_program
            and all(hasattr(x, "to_program") for x in fn_or_program)
        )
        if is_node or is_node_seq:
            if params:
                raise ProgramError(
                    "params are not supported for DSL-node programs; use "
                    "dsl.constant for fixed values or a python-function "
                    "program for updatable params"
                )
            from . import dsl  # local import: dsl depends on this module

            nodes = [fn_or_program] if is_node else list(fn_or_program)
            p = dsl.build_program(nodes, feed_dict=feed_dict)
            if fetches is not None and sorted(fetches) != sorted(
                p._declared_fetches or []
            ):
                raise ProgramError(
                    f"fetches {sorted(fetches)} do not match the DSL fetch "
                    f"node names {sorted(p._declared_fetches or [])}; name "
                    f"fetch nodes with .named(...) instead"
                )
            return p
        if not callable(fn_or_program):
            raise ProgramError(
                f"expected a callable, Program, or DSL node(s), got "
                f"{type(fn_or_program).__name__}"
            )
        sig = inspect.signature(fn_or_program)
        names = []
        for p in sig.parameters.values():
            if p.kind in (
                inspect.Parameter.POSITIONAL_OR_KEYWORD,
                inspect.Parameter.KEYWORD_ONLY,
            ):
                names.append(p.name)
            elif p.kind in (
                inspect.Parameter.VAR_POSITIONAL,
                inspect.Parameter.VAR_KEYWORD,
            ):
                raise ProgramError(
                    "program functions must declare explicit named parameters "
                    "(column names); *args/**kwargs are not allowed"
                )
        if not names:
            raise ProgramError("a program needs at least one named input")
        return Program(fn_or_program, names, fetches, feed_dict, params)

    def with_feed(self, feed_dict: Mapping[str, str]) -> "Program":
        """A copy with additional input->column renames merged in."""
        merged = dict(self._feed)
        merged.update(feed_dict)
        p = Program(
            self._fn,
            self._input_names + list(self._params),
            self._declared_fetches,
            merged,
            self._params,
        )
        p._shape_hints = dict(self._shape_hints)
        p.host_prelude = dict(self.host_prelude)
        return p

    def with_shape_hints(
        self, hints: Mapping[str, Sequence[int]]
    ) -> "Program":
        """A copy carrying output-shape hints (the reference's
        ``ShapeDescription`` override, ``TensorFlowOps.scala:126-133``):
        each hint refines — never contradicts — the engine-inferred shape.
        Applied by ``analyze`` and checked against real outputs by the map
        verbs."""
        p = Program(
            self._fn,
            self._input_names + list(self._params),
            self._declared_fetches,
            self._feed,
            self._params,
        )
        p._shape_hints = dict(self._shape_hints)
        p.host_prelude = dict(self.host_prelude)
        for name, s in hints.items():
            p._shape_hints[name] = Shape(s)
        if self._declared_fetches is not None:
            bad = sorted(set(p._shape_hints) - set(self._declared_fetches))
            if bad:
                raise ProgramError(
                    f"shape hints for unknown outputs {bad}; program "
                    f"outputs are {sorted(self._declared_fetches)}"
                )
        return p

    @property
    def shape_hints(self) -> Dict[str, Shape]:
        return dict(self._shape_hints)

    # -- accessors -----------------------------------------------------------

    @property
    def input_names(self) -> List[str]:
        """Column-fed input names (param-bound arguments excluded)."""
        return list(self._input_names)

    @property
    def param_names(self) -> List[str]:
        return list(self._params)

    @property
    def params(self) -> Dict[str, Any]:
        return dict(self._params)

    def update_params(self, **arrays) -> "Program":
        """Replace param values in place (shapes/dtypes must match).

        This is the iterative-driver contract: the reference re-embeds
        updated constants into a fresh graph every step
        (``kmeans_demo.py:68-80``, re-broadcast each iteration); here params
        are *traced arguments* of the compiled executable, so a shape-stable
        update reuses the jit cache — no re-trace, no re-compile, no
        re-broadcast."""
        # validate EVERY key before mutating anything: a mid-loop raise
        # must not leave _params half-updated at the old version — the
        # planner's CSE registry keys on (id, _params_version) and a
        # silent partial update would let it serve stale results
        validated: Dict[str, Any] = {}
        for k, v in arrays.items():
            if k not in self._params:
                raise ProgramError(
                    f"update_params: {k!r} is not a param; params are "
                    f"{sorted(self._params)}"
                )
            old = self._params[k]
            new = jax.tree_util.tree_map(jnp.asarray, v)
            old_leaves, old_def = jax.tree_util.tree_flatten(old)
            new_leaves, new_def = jax.tree_util.tree_flatten(new)
            if old_def != new_def:
                raise ProgramError(
                    f"update_params: {k!r} must keep its pytree structure "
                    f"(got {new_def}, expected {old_def}); structure "
                    f"changes force a re-compile — build a new Program"
                )
            for ol, nl in zip(old_leaves, new_leaves):
                if nl.shape != ol.shape or nl.dtype != ol.dtype:
                    raise ProgramError(
                        f"update_params: {k!r} must keep shape {ol.shape} /"
                        f" dtype {ol.dtype}, got {nl.shape} / {nl.dtype} "
                        f"(shape changes force a re-compile; build a new "
                        f"Program instead)"
                    )
            validated[k] = new
        self._params.update(validated)
        self._params_version += 1
        return self

    def column_for_input(self, name: str) -> str:
        """Frame column feeding a given input (identity unless feed_dict)."""
        return self._feed.get(name, name)

    @property
    def columns_needed(self) -> List[str]:
        return [self.column_for_input(n) for n in self._input_names]

    @property
    def fetches(self) -> Optional[List[str]]:
        return list(self._fetches) if self._fetches is not None else (
            sorted(self._declared_fetches) if self._declared_fetches else None
        )

    # -- execution -----------------------------------------------------------

    def _normalize_outputs(self, out) -> Dict[str, Any]:
        if isinstance(out, dict):
            res = dict(out)
        elif isinstance(out, (tuple, list)):
            if self._declared_fetches is None or len(self._declared_fetches) != len(
                out
            ):
                raise ProgramError(
                    "tuple program outputs require fetches=[...] of matching "
                    f"length; got {len(out)} outputs, fetches="
                    f"{self._declared_fetches}"
                )
            res = dict(zip(self._declared_fetches, out))
        else:
            if self._declared_fetches is None or len(self._declared_fetches) != 1:
                raise ProgramError(
                    "a program returning a single array must declare exactly "
                    "one fetch name (pass fetches=['name']), or return a dict "
                    "{name: array}"
                )
            res = {self._declared_fetches[0]: out}
        if self._declared_fetches is not None:
            missing = [f for f in self._declared_fetches if f not in res]
            if missing:
                raise ProgramError(
                    f"program outputs {sorted(res)} are missing requested "
                    f"fetches {missing}"
                )
            res = {f: res[f] for f in self._declared_fetches}
        if not res:
            raise ProgramError("program produced no outputs")
        for name, v in res.items():
            if not isinstance(name, str):
                raise ProgramError(f"output names must be strings, got {name!r}")
            res[name] = jnp.asarray(v)
        # canonical order: sorted by name (DebugRowOps.scala:349-372)
        ordered = {k: res[k] for k in sorted(res)}
        if self._fetches is None:
            self._fetches = list(ordered)
        return ordered

    def call(
        self,
        inputs: Mapping[str, Any],
        params: Optional[Mapping[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Run the program (traceable; used inside jit/vmap/shard_map).

        ``params`` lets an enclosing jit pass the param values as *traced
        arguments*; when omitted, the current ``self._params`` are captured
        as trace-time constants (correct, but an enclosing jit built around
        such a call bakes the values in)."""
        if params is None:
            params = self._params
        # jit invokes the python function only on a signature-cache miss,
        # so each call here under tracing is one (re)trace of the user
        # program — the retrace counter the bench/tests assert against.
        # Analysis-time tracing (analyze/probes/export) is suppressed.
        observability.note_program_trace()
        kwargs = {n: inputs[n] for n in self._input_names}
        kwargs.update(params)
        return self._normalize_outputs(self._fn(**kwargs))

    def jitted(self):
        """The compiled entry: traced once per input shape/dtype signature.

        jax's jit cache is the broadcast mechanism (SURVEY.md P6): every block
        with the same signature reuses the same XLA executable, on any device.
        Params flow through as traced arguments, so ``update_params`` between
        calls reuses the compiled executable.
        """
        if self._jitted is None:
            self._jitted = self._bind_live_params(self._jit_raw())
        return self._jitted

    def _jit_raw(self):
        """The raw block-level jit object (``fn(inputs, params)``) —
        shared by :meth:`jitted` and the AOT ``lower().compile()`` path."""
        if getattr(self, "_jit_raw_obj", None) is None:
            def _run(inputs, params):
                return self.call(inputs, params)

            self._jit_raw_obj = jax.jit(_run)
        return self._jit_raw_obj

    def vmapped(self):
        """Compiled row-level entry: the cell program vmapped over the lead
        axis (``map_rows``'s engine).  Cached like ``jitted``; params are
        broadcast (not vmapped) and traced as arguments."""
        if self._vmapped is None:
            self._vmapped = self._bind_live_params(self._vmap_raw())
        return self._vmapped

    def _vmap_raw(self):
        """Raw row-level jit object (see :meth:`_jit_raw`)."""
        if getattr(self, "_vmap_raw_obj", None) is None:
            def _run(inputs, params):
                return jax.vmap(
                    lambda ins: self.call(ins, params), in_axes=(0,)
                )(inputs)

            self._vmap_raw_obj = jax.jit(_run)
        return self._vmap_raw_obj

    def _bind_live_params(self, compiled):
        """Bind the CURRENT params as the trailing traced argument at every
        call — the one place where the live-params calling convention lives."""
        return lambda *args: compiled(*args, self._params)

    # cap on derived compiled callables kept per Program; least-recently
    # USED evicted first so a Program reused across many short-lived
    # meshes/executors does not pin their executables forever
    _DERIVED_CAP = 32

    def _derived_hit(self, key):
        """LRU touch: re-insert ``key`` so eviction order is recency of
        *use*, not insertion — a hot executable cannot be evicted by a
        burst of one-off keys."""
        self._derived[key] = self._derived.pop(key)
        return self._derived[key]

    def cached_jit(self, key, build_raw, **jit_kwargs):
        """Memoize ``jax.jit(build_raw(), **jit_kwargs)`` with live params
        bound.

        The verb engines build per-verb wrappers (pairwise folds, block
        reducers, shard_maps, donated prefetch entries) whose last
        positional argument is the params dict; caching them here keyed by
        verb/mode/mesh means repeated verb invocations on the same Program
        reuse one jit cache instead of re-tracing per call, and
        ``update_params`` takes effect without recompiling.  Eviction is
        LRU (a hit re-inserts the key).  ``build_raw`` returns the raw
        traceable ``fn(*args, params)``; ``jit_kwargs`` (e.g.
        ``donate_argnums``) must be part of ``key`` when they vary."""
        if key in self._derived:
            return self._derived_hit(key)
        while len(self._derived) >= self._DERIVED_CAP:
            self._derived.pop(next(iter(self._derived)))
        raw = jax.jit(build_raw(), **jit_kwargs)
        bound = self._bind_live_params(raw)
        # the raw jit object rides along so AOT warmup can lower the
        # EXACT entry the verbs execute (same module name, same donation
        # aliasing -> same persistent-cache key)
        bound.raw_jit = raw
        self._derived[key] = bound
        return bound

    # -- ahead-of-time compilation (persistent-cache cold start) -------------

    def _input_structs(
        self, input_specs: Mapping[str, Any]
    ) -> Dict[str, jax.ShapeDtypeStruct]:
        """Normalize ``input name -> (ScalarType, Shape) | ShapeDtypeStruct``
        into concrete ShapeDtypeStructs (static shapes required)."""
        structs: Dict[str, jax.ShapeDtypeStruct] = {}
        for n in self._input_names:
            if n not in input_specs:
                raise ProgramError(
                    f"no spec for program input {n!r}; got specs for "
                    f"{sorted(input_specs)}"
                )
            spec = input_specs[n]
            if isinstance(spec, jax.ShapeDtypeStruct):
                shape, dt = tuple(spec.shape), spec.dtype
            else:
                st, shape = spec
                shape, dt = tuple(Shape(shape)), st.np_dtype
            if any(d == UNKNOWN for d in shape):
                raise ProgramError(
                    f"input {n!r}: AOT compilation needs a static shape, "
                    f"got {shape} (bucket the lead dim first)"
                )
            structs[n] = jax.ShapeDtypeStruct(shape, dt)
        return structs

    def aot_compile(self, input_specs: Mapping[str, Any], rows_level=False):
        """Ahead-of-time ``lower().compile()`` at one exact (bucketed)
        input signature; returns the bound executable ``fn(inputs) ->
        {name: array}``.

        Memoized per (entry, input signature) in the derived-callable
        LRU; the returned callable carries ``.fingerprint``, a
        cross-process content hash of its lowered StableHLO (two Program
        objects wrapping the same source at the same bucket signature
        produce the same fingerprint, hence share one disk entry).  With
        the persistent compilation cache configured
        (``TFS_COMPILE_CACHE`` / :mod:`tensorframes_tpu.compile_cache`),
        the ``compile()`` step is a disk fetch in any process that has
        ever compiled this (fingerprint, signature) — a cold serving
        replica warms every bucket executable without running XLA.
        ``rows_level``: compile the vmapped cell-program entry
        (``map_rows``) instead of the block entry.

        The returned callable requires inputs matching the signature
        exactly (that is what bucketing guarantees); the engine's jitted
        entries remain the general path (they share the same raw jit
        object, so the persistent entry compiled here is the one they
        fetch)."""
        raw = self._vmap_raw() if rows_level else self._jit_raw()
        return self.aot_compile_raw(
            raw, input_specs, ("aot", bool(rows_level))
        )

    def aot_compile_raw(self, raw_jit, input_specs: Mapping[str, Any], tag):
        """:meth:`aot_compile` for an arbitrary raw jit entry of this
        program (``fn(inputs, params)``) — the engine passes its own
        donated entries (``cached_jit(...).raw_jit``) so warmup lowers
        exactly what the verbs will execute: same module name, same
        donation aliasing, hence the same persistent-cache key.  ``tag``
        namespaces the memo key in the derived-callable LRU.

        The fingerprint on the returned callable hashes the lowered
        StableHLO — no extra trace (``lower()`` already produced it) —
        and is stable across processes for the same program source and
        signature."""
        structs = self._input_structs(input_specs)
        sig = tuple(
            (n, structs[n].shape, str(structs[n].dtype))
            for n in sorted(structs)
        )
        key = (tag, sig)
        if key in self._derived:
            return self._derived_hit(key)
        param_specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(jnp.shape(a), jnp.asarray(a).dtype),
            self._params,
        )
        with observability.suppress_trace_count():
            lowered = raw_jit.lower(structs, param_specs)
            h = hashlib.sha256()
            h.update(jax.__version__.encode())
            h.update(lowered.as_text().encode())
            compiled = lowered.compile()
        fn = lambda inputs: compiled(inputs, self._params)  # noqa: E731
        fn.fingerprint = h.hexdigest()[:16]
        fn.signature = sig
        while len(self._derived) >= self._DERIVED_CAP:
            self._derived.pop(next(iter(self._derived)))
        self._derived[key] = fn
        return fn

    # -- serialization -------------------------------------------------------

    def serialize(self, input_specs: Mapping[str, Any]) -> bytes:
        """Freeze into a portable program artifact (StableHLO via
        ``jax.export``).

        The reference's program transport is frozen GraphDef bytes shipped
        to executors (``SerializedGraph``, ``TensorFlowOps.scala:21-61``);
        the XLA-native equivalent is serialized StableHLO: params are baked
        in as constants (a *frozen* program), and Unknown (-1) dims become
        symbolic — every Unknown lead dim shares one ``rows`` symbol (all
        columns of a block have the same row count), so one artifact serves
        any block size without recompiling the export.

        ``input_specs``: input name -> (ScalarType, Shape), Unknown dims
        allowed.  Round-trip via :func:`deserialize_program`.
        """
        import json

        from jax import export as jexp

        shapes: Dict[str, Shape] = {}
        stypes: Dict[str, Any] = {}
        for n in self._input_names:
            if n not in input_specs:
                raise ProgramError(
                    f"serialize: no spec for program input {n!r}; got "
                    f"specs for {sorted(input_specs)}"
                )
            spec = input_specs[n]
            if isinstance(spec, jax.ShapeDtypeStruct):
                shapes[n] = Shape(spec.shape)
                stypes[n] = spec.dtype
            else:
                st, shape = spec
                shapes[n] = Shape(shape)
                stypes[n] = st.np_dtype

        n_cell_syms = sum(
            sum(1 for d in s.dims[1:] if d == UNKNOWN)
            for s in shapes.values()
        )
        sym_names = ["rows"] + [f"u{i}" for i in range(n_cell_syms)]
        syms = list(jexp.symbolic_shape(", ".join(sym_names)))
        rows_sym, cell_syms = syms[0], syms[1:]
        next_cell = iter(cell_syms)
        structs = {}
        for n in self._input_names:
            dims = []
            for i, d in enumerate(shapes[n]):
                if d != UNKNOWN:
                    dims.append(d)
                elif i == 0:
                    dims.append(rows_sym)
                else:
                    dims.append(next(next_cell))
            structs[n] = jax.ShapeDtypeStruct(tuple(dims), stypes[n])

        with observability.suppress_trace_count():
            exported = jexp.export(jax.jit(lambda ins: self.call(ins)))(
                structs
            )
        header = json.dumps(
            {
                "format": "tfs-program-v1",
                "inputs": self._input_names,
                "fetches": self._fetches or self.fetches,
                "feed": self._feed,
            }
        ).encode()
        return header + b"\x00" + exported.serialize()

    # -- analysis ------------------------------------------------------------

    def analyze(
        self,
        input_specs: Mapping[str, Any],
        hints: Optional[Mapping[str, Sequence[int]]] = None,
    ) -> List[GraphNodeSummary]:
        """Shape-infer the program against input specs without executing it.

        ``input_specs``: input name -> (ScalarType, Shape) or ShapeDtypeStruct.
        Specs may contain Unknown (-1) dims: the program is shape-evaluated at
        two probe substitutions and output dims that depend on the unknown
        inputs come back Unknown (the lattice merge ``analyze`` uses for data,
        applied to programs).

        ``hints``: output name -> shape override (the ``ShapeDescription``
        mechanism, ``ShapeDescription.scala:3-16``), merged over any hints
        already attached via ``with_shape_hints``.  Hints *refine* inferred
        shapes — an Unknown dim becomes the hinted value, a concrete dim must
        agree (contradictions raise), mirroring the reference's hint-override
        with the stronger never-contradict guarantee.
        """
        shapes: Dict[str, Shape] = {}
        stypes: Dict[str, Any] = {}
        for n in self._input_names:
            if n not in input_specs:
                raise ProgramError(
                    f"analyze: no spec for program input {n!r}; "
                    f"got specs for {sorted(input_specs)}"
                )
            spec = input_specs[n]
            if isinstance(spec, jax.ShapeDtypeStruct):
                shapes[n] = Shape(spec.shape)
                stypes[n] = spec.dtype
            else:
                st, shape = spec
                shapes[n] = Shape(shape)
                stypes[n] = st.np_dtype

        def _eval(probe: int):
            structs = {
                n: jax.ShapeDtypeStruct(
                    tuple(probe if d == UNKNOWN else d for d in shapes[n]),
                    stypes[n],
                )
                for n in self._input_names
            }
            with observability.suppress_trace_count():
                return jax.eval_shape(lambda ins: self.call(ins), structs)

        has_unknown = any(not s.is_static for s in shapes.values())
        out_a = _eval(3)
        out_shapes: Dict[str, Shape] = {}
        if has_unknown:
            # dims that track the probe are Unknown; dims stable across
            # probes are genuinely static (the analyze lattice merge)
            out_b = _eval(7)
            for name in out_a:
                sa, sb = Shape(out_a[name].shape), Shape(out_b[name].shape)
                if sa.rank != sb.rank:
                    raise ProgramError(
                        f"analyze: output {name!r} changes rank with the "
                        f"unknown input dims ({sa} vs {sb}); its shape "
                        f"cannot be described"
                    )
                out_shapes[name] = sa.merge(sb)
        else:
            out_shapes = {n: Shape(s.shape) for n, s in out_a.items()}

        merged_hints = dict(self._shape_hints)
        for name, h in (hints or {}).items():
            merged_hints[name] = Shape(h)
        unknown_hints = sorted(set(merged_hints) - set(out_shapes))
        if unknown_hints:
            raise ProgramError(
                f"shape hints given for non-existent outputs: "
                f"{unknown_hints}; program outputs are {sorted(out_shapes)}"
            )

        summaries: List[GraphNodeSummary] = []
        for n in self._input_names:
            summaries.append(
                GraphNodeSummary(
                    n, True, False, dtypes.from_numpy(stypes[n]), shapes[n]
                )
            )
        for name, shape in out_shapes.items():
            if name in merged_hints:
                try:
                    shape = shape.refine(
                        merged_hints[name], context=f"output {name!r}"
                    )
                except Exception as e:
                    raise ProgramError(str(e)) from e
            summaries.append(
                GraphNodeSummary(
                    name,
                    False,
                    True,
                    dtypes.from_numpy(out_a[name].dtype),
                    shape,
                )
            )
        return summaries
